"""Convergence observability plane tests: the ConvergenceTracker (records,
registry joins, divergence watchdog), progress.jsonl ledger schema round
trips, convergence-report reconstruction, the /progress //healthz live
introspection path, the analyze_run --progress CLI, the convergence
sentinel (dev-scripts/check_convergence_trajectory.py), and the driver-
level contracts: divergence injection must abort the CLI with no model
artifact, and the disabled-by-default path must stay bitwise identical."""

import importlib.util
import json
import math
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.event import AnomalyEvent, EventEmitter
from photon_ml_tpu.telemetry import (
    ConvergenceTracker,
    DivergenceError,
    MetricsRegistry,
    TruncatedLedgerWarning,
    convergence_report,
    extract_progress_records,
    format_progress_report,
    iterations_to_target_metric,
    validate_ledger,
)

SENTINEL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "dev-scripts", "check_convergence_trajectory.py",
)


def _load_sentinel():
    spec = importlib.util.spec_from_file_location(
        "check_convergence_trajectory", SENTINEL
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tracker(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return ConvergenceTracker(**kw)


class TestConvergenceTracker:
    def test_coordinate_records_and_registry(self):
        reg = MetricsRegistry()
        t = _tracker(registry=reg)
        t.record_coordinate(
            0, "fixed", 100.0, loss=90.0, regularization=10.0,
            grad_norm=5.0, coef_delta_norm=2.0, solver_iterations=12,
            line_search_trials=3, convergence_reason="MAX_ITERATIONS",
        )
        t.record_coordinate(0, "per_user", 80.0)
        (rec, rec2) = t.records
        assert rec["kind"] == "coordinate" and rec["objective"] == 100.0
        assert rec["solver_iterations"] == 12
        assert rec["convergence_reason"] == "MAX_ITERATIONS"
        # optional fields stay absent when the solver has no scalar tracker
        assert "grad_norm" not in rec2 and "solver_iterations" not in rec2
        snap = reg.snapshot()
        assert snap["counters"]["progress.coordinate_updates"] == 2
        assert snap["counters"]["progress.solver_iterations"] == 12
        assert snap["gauges"]["progress.objective"]["last"] == 80.0
        assert snap["gauges"]["progress.fixed.grad_norm"]["last"] == 5.0
        assert t.healthy and t.anomaly is None

    def test_validation_and_block_records(self):
        reg = MetricsRegistry()
        t = _tracker(registry=reg)
        t.record_validation(0, "fixed", 0.75)
        t.record_blocks(0, "fixed", [
            {"block": 0, "partial_loss": 10.0, "partial_grad_norm": 1.0,
             "gap_estimate": 4.0},
            {"block": 1, "partial_loss": 12.0, "partial_grad_norm": 2.0,
             "gap_estimate": 6.0},
        ])
        kinds = [r["kind"] for r in t.records]
        assert kinds == ["validation", "block", "block"]
        snap = reg.snapshot()
        assert snap["gauges"]["progress.validation_metric"]["last"] == 0.75
        # the DuHL scheduler seam: per-block gap gauges + aggregates
        assert snap["gauges"]["stream.block_gap.0"]["last"] == 4.0
        assert snap["gauges"]["stream.block_gap.1"]["last"] == 6.0
        assert snap["gauges"]["stream.block_gap_max"]["last"] == 6.0
        assert snap["gauges"]["stream.block_gap_sum"]["last"] == 10.0

    def test_non_finite_objective_trips(self):
        emitter = EventEmitter()
        from tests._listeners import CollectingListener

        CollectingListener.received = []
        emitter.register_listener_class("tests._listeners.CollectingListener")
        t = _tracker(emitter=emitter)
        t.record_coordinate(0, "fixed", 50.0)
        with pytest.raises(DivergenceError) as err:
            t.record_coordinate(1, "fixed", float("nan"))
        assert err.value.anomaly["anomaly_kind"] == "non_finite_objective"
        assert not t.healthy
        assert t.records[-1]["kind"] == "anomaly"
        events = [e for e in CollectingListener.received
                  if isinstance(e, AnomalyEvent)]
        assert len(events) == 1
        assert events[0].kind == "non_finite_objective"
        assert events[0].coordinate_id == "fixed"

    def test_objective_increase_trips_beyond_tolerance(self):
        t = _tracker(divergence_tolerance=1e-3)
        t.record_coordinate(0, "fixed", 100.0)
        # within tolerance: allowed drift, no trip
        t.record_coordinate(0, "per_user", 100.05)
        with pytest.raises(DivergenceError) as err:
            t.record_coordinate(1, "fixed", 102.0)
        anomaly = err.value.anomaly
        assert anomaly["anomaly_kind"] == "objective_increase"
        assert anomaly["detail"]["previous_objective"] == 100.05
        assert anomaly["detail"]["allowed_objective"] == pytest.approx(
            100.05 + 1e-3 * 100.05
        )

    def test_line_search_stall_requires_large_grad(self):
        # "line search failed" with a TINY gradient is what convergence
        # looks like — must never trip
        t = _tracker(max_line_search_failures=3)
        for outer in range(6):
            t.record_coordinate(
                outer, "fixed", 50.0, grad_norm=1e-4,
                convergence_reason="OBJECTIVE_NOT_IMPROVING",
            )
        assert t.healthy
        # same reason with a still-large gradient: stall after 3 in a row
        t2 = _tracker(max_line_search_failures=3)
        t2.record_coordinate(
            0, "fixed", 50.0, grad_norm=9.0,
            convergence_reason="OBJECTIVE_NOT_IMPROVING",
        )
        t2.record_coordinate(
            1, "fixed", 50.0, grad_norm=9.0,
            convergence_reason="OBJECTIVE_NOT_IMPROVING",
        )
        with pytest.raises(DivergenceError) as err:
            t2.record_coordinate(
                2, "fixed", 50.0, grad_norm=9.0,
                convergence_reason="OBJECTIVE_NOT_IMPROVING",
            )
        assert err.value.anomaly["anomaly_kind"] == "line_search_stall"
        assert err.value.anomaly["detail"]["consecutive_failures"] == 3
        # a healthy update in between resets the streak
        t3 = _tracker(max_line_search_failures=3)
        for outer in range(2):
            t3.record_coordinate(
                outer, "fixed", 50.0 - outer, grad_norm=9.0,
                convergence_reason="OBJECTIVE_NOT_IMPROVING",
            )
        t3.record_coordinate(2, "fixed", 47.0, grad_norm=9.0,
                             convergence_reason="CONVERGED")
        t3.record_coordinate(3, "fixed", 46.0, grad_norm=9.0,
                             convergence_reason="OBJECTIVE_NOT_IMPROVING")
        assert t3.healthy

    def test_no_abort_mode_records_without_raising(self):
        t = _tracker(abort_on_divergence=False)
        t.record_coordinate(0, "fixed", 10.0)
        t.record_coordinate(1, "fixed", float("inf"))  # no raise
        assert not t.healthy
        assert t.anomaly["anomaly_kind"] == "non_finite_objective"
        health = t.health()
        assert health["healthy"] is False
        assert health["phase"] == "diverged"
        assert health["anomaly"]["anomaly_kind"] == "non_finite_objective"

    def test_health_and_progress_json(self):
        t = _tracker()
        t.record_coordinate(2, "per_user", 33.0)
        health = t.health()
        assert health == {
            "healthy": True, "phase": "training", "outer": 2,
            "coordinate": "per_user", "objective": 33.0,
        }
        doc = t.progress_json()
        assert doc["num_records"] == 1 and doc["anomaly"] is None
        json.dumps(doc)  # endpoint payload must be plain JSON
        t.finish()
        assert t.health()["phase"] == "finished"
        t.finish()  # idempotent


class TestLedgerRoundTrip:
    def _write_run(self, path, diverge=False):
        t = ConvergenceTracker(
            ledger_path=str(path), registry=MetricsRegistry(),
            abort_on_divergence=False,
        )
        t.record_coordinate(0, "fixed", 120.0, grad_norm=3.0,
                            solver_iterations=8)
        t.record_blocks(0, "fixed", [
            {"block": 0, "partial_loss": 60.0, "partial_grad_norm": 1.5,
             "gap_estimate": 2.5},
        ])
        t.record_validation(0, "fixed", 0.71)
        t.record_coordinate(0, "per_user", 100.0)
        if diverge:
            t.record_coordinate(1, "fixed", float("nan"))
        t.finish()
        return t

    def test_progress_ledger_schema_round_trip(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        self._write_run(path)
        records = validate_ledger(str(path))
        assert records[0]["type"] == "meta"
        assert records[0]["phase"] == "start"
        assert records[-1]["type"] == "meta"
        assert records[-1]["phase"] == "finish"
        assert records[-1]["healthy"] is True
        progress = extract_progress_records(records)
        assert [r["kind"] for r in progress] == [
            "coordinate", "block", "validation", "coordinate"
        ]
        assert all("ts" in r for r in progress)

    def test_anomaly_and_nan_round_trip(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        self._write_run(path, diverge=True)
        records = validate_ledger(str(path))
        assert records[-1]["healthy"] is False
        anomaly = [r for r in extract_progress_records(records)
                   if r["kind"] == "anomaly"]
        assert len(anomaly) == 1
        assert anomaly[0]["anomaly_kind"] == "non_finite_objective"
        # the NaN objective survives the JSONL round trip
        assert math.isnan(anomaly[0]["objective"])

    def test_truncated_tail_tolerated(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        self._write_run(path)
        with open(path, "a") as f:
            f.write('{"type": "progress", "kind": "coordina')  # crash cut
        with pytest.warns(TruncatedLedgerWarning, match="partial record"):
            records = validate_ledger(str(path))
        assert len(extract_progress_records(records)) == 4

    def test_validator_rejects_malformed_progress(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text(
            '{"type": "progress", "ts": 1.0, "kind": "coordinate", '
            '"outer": 0}\n'  # missing coordinate + objective
        )
        with pytest.raises(ValueError, match="progress"):
            validate_ledger(str(path))
        path.write_text('{"type": "progress", "ts": 1.0}\n')  # no kind
        with pytest.raises(ValueError, match="progress"):
            validate_ledger(str(path))
        path.write_text(
            '{"type": "progress", "ts": 1.0, "kind": "block", "outer": 0, '
            '"coordinate": "fixed", "block": 0, "partial_loss": 1.0}\n'
        )  # block record missing grad norm + gap
        with pytest.raises(ValueError, match="progress"):
            validate_ledger(str(path))


def _synthetic_progress():
    """Two coordinates over four outer iterations, converging, with a
    streamed fixed coordinate reporting block stats on every outer."""
    recs = []
    objectives = {
        0: [("fixed", 300.0), ("per_user", 200.0)],
        1: [("fixed", 110.0), ("per_user", 100.05)],
        2: [("fixed", 100.04), ("per_user", 100.02)],
        3: [("fixed", 100.01), ("per_user", 100.0)],
    }
    for outer, pairs in objectives.items():
        for cid, obj in pairs:
            if cid == "fixed":
                for b in range(2):
                    recs.append({
                        "kind": "block", "outer": outer, "coordinate": cid,
                        "block": b, "partial_loss": obj / 2 + b,
                        "partial_grad_norm": 1.0 / (outer + 1),
                        "gap_estimate": 10.0 / (outer + 1) + b,
                    })
            recs.append({
                "kind": "coordinate", "outer": outer, "coordinate": cid,
                "objective": obj, "solver_iterations": 5,
            })
        recs.append({
            "kind": "validation", "outer": outer, "coordinate": "per_user",
            "metric": 0.9 - 0.1 * outer,
        })
    return recs


class TestConvergenceReport:
    def test_reconstruction(self):
        report = convergence_report(_synthetic_progress(), tolerance=1e-3)
        assert report["num_updates"] == 8
        assert report["first_objective"] == 300.0
        assert report["final_objective"] == 100.0
        assert report["objective_drop"] == 200.0
        # objective settles within 0.1% of 100.0 at the 4th update (100.05)
        assert report["iterations_to_tolerance"] == 4
        assert report["final_validation_metric"] == pytest.approx(0.6)
        coords = report["coordinates"]
        assert set(coords) == {"fixed", "per_user"}
        assert coords["fixed"]["updates"] == 4
        assert coords["fixed"]["solver_iterations"] == 20
        # the consecutive drops partition the whole 300 -> 100 descent, so
        # the attributed shares sum to 1
        share_sum = sum(c["objective_share"] for c in coords.values())
        assert share_sum == pytest.approx(1.0)
        assert coords["per_user"]["stalled"]  # last two deltas ~0
        assert not coords["fixed"]["stalled"]  # still dropping 9.96 at n-2
        blocks = report["blocks"]["fixed"]["final_pass"]
        # final_pass keeps the LAST outer's stats per block
        assert set(blocks) == {0, 1}
        assert blocks[1]["gap_estimate"] == pytest.approx(10.0 / 4 + 1)
        assert report["blocks"]["fixed"]["gap_max"] == pytest.approx(
            10.0 / 4 + 1
        )

    def test_iterations_to_target_metric(self):
        progress = _synthetic_progress()
        assert iterations_to_target_metric(
            progress, 0.75, higher_is_better=False
        ) == 3  # validation hits 0.7 at outer 2 (0-based) -> 3rd outer
        assert iterations_to_target_metric(
            progress, 0.95, higher_is_better=False
        ) == 1
        assert iterations_to_target_metric(
            progress, 0.5, higher_is_better=False
        ) is None

    def test_empty_and_format(self):
        empty = convergence_report([])
        assert empty["num_updates"] == 0
        assert "first_objective" not in empty
        text = format_progress_report(convergence_report(
            _synthetic_progress()
        ))
        assert "== convergence report ==" in text
        assert "iters-to-tolerance : 4" in text
        assert "fixed" in text and "per_user" in text
        assert "streamed blocks [fixed]: 2 blocks" in text
        # anomalies render loudly
        bad = convergence_report(_synthetic_progress() + [{
            "kind": "anomaly", "anomaly_kind": "objective_increase",
            "outer": 3, "coordinate": "fixed", "objective": 500.0,
            "detail": {},
        }])
        assert "ANOMALY: objective_increase" in format_progress_report(bad)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


class TestLiveIntrospection:
    def test_progress_endpoint_and_healthz_503(self):
        from photon_ml_tpu.serving import IntrospectionServer

        reg = MetricsRegistry()
        t = _tracker(registry=reg, abort_on_divergence=False)
        srv = IntrospectionServer(
            registry=reg,
            health=t.health,
            extra_json={"/progress": t.progress_json},
        ).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            t.record_coordinate(0, "fixed", 42.0)
            status, body = _get(f"{base}/progress")
            doc = json.loads(body)
            assert status == 200 and doc["healthy"] is True
            assert doc["records"][0]["objective"] == 42.0
            status, body = _get(f"{base}/healthz")
            assert status == 200
            assert json.loads(body)["coordinate"] == "fixed"
            # watchdog trips -> /healthz flips 503, /progress still serves
            t.record_coordinate(1, "fixed", float("nan"))
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/healthz")
            assert err.value.code == 503
            doc = json.loads(err.value.read().decode())
            assert doc["healthy"] is False
            assert doc["anomaly"]["anomaly_kind"] == "non_finite_objective"
            status, body = _get(f"{base}/progress")
            assert status == 200
            assert json.loads(body)["anomaly"] is not None
            # the registry-backed /metrics sees the progress counters too
            status, body = _get(f"{base}/metrics")
            assert "photon_progress_coordinate_updates 2" in body
            assert "photon_progress_anomalies 1" in body
        finally:
            srv.stop()


class TestAnalyzeRunProgress:
    def test_renders_report_from_ledger(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        path = tmp_path / "progress.jsonl"
        t = ConvergenceTracker(
            ledger_path=str(path), registry=MetricsRegistry()
        )
        for outer, obj in enumerate([250.0, 120.0, 119.9]):
            t.record_coordinate(outer, "fixed", obj, solver_iterations=4)
        t.finish()
        assert main([str(path), "--progress"]) == 0
        out = capsys.readouterr().out
        assert "== convergence report ==" in out
        assert "250 -> 119.9" in out

    def test_missing_progress_exits_nonzero(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main
        from photon_ml_tpu.telemetry import RunLedger

        path = tmp_path / "plain.jsonl"
        ledger = RunLedger(str(path))
        ledger.write("meta", phase="start", label="t")
        ledger.write("meta", phase="finish", label="t")
        ledger.close()
        assert main([str(path), "--progress"]) == 1
        assert "no progress records" in capsys.readouterr().err

    def test_progress_records_are_known_types(self, tmp_path):
        """analyze_ledger must count progress records as known record
        types (no unknown-type warnings) and attach the report."""
        from photon_ml_tpu.telemetry.analyze import analyze_ledger

        path = tmp_path / "progress.jsonl"
        t = ConvergenceTracker(
            ledger_path=str(path), registry=MetricsRegistry()
        )
        t.record_coordinate(0, "fixed", 10.0)
        t.finish()
        report = analyze_ledger(str(path))
        assert report.progress is not None
        assert report.progress["num_updates"] == 1
        # round trip through the structured report dict stays stable
        assert report.to_dict()["progress"]["num_updates"] == 1


class TestConvergenceSentinel:
    def _ledger(self, tmp_path, objectives, metrics=(), anomaly=False):
        path = tmp_path / "fresh.jsonl"
        t = ConvergenceTracker(
            ledger_path=str(path), registry=MetricsRegistry(),
            abort_on_divergence=False, divergence_tolerance=float("inf"),
        )
        for outer, obj in enumerate(objectives):
            t.record_coordinate(outer, "fixed", obj)
        for outer, m in enumerate(metrics):
            t.record_validation(outer, "fixed", m)
        if anomaly:
            t.record_coordinate(len(objectives), "fixed", float("nan"))
        t.finish()
        return str(path)

    def _history(self, tmp_path, final_obj=100.0, iters=3, target=None):
        path = tmp_path / "history.jsonl"
        recs = [
            {"ts": 1.0, "mode": "convergence",
             "metric": "golden_fixture_final_objective",
             "value": final_obj, "unit": "objective", "host": "x"},
            {"ts": 1.0, "mode": "convergence",
             "metric": "golden_fixture_iterations_to_tol",
             "value": iters, "unit": "updates", "host": "x"},
        ]
        if target is not None:
            recs.append(
                {"ts": 1.0, "mode": "convergence",
                 "metric": "golden_fixture_iterations_to_target",
                 "value": target, "unit": "updates", "host": "x"})
        path.write_text("".join(json.dumps(r) + "\n" for r in recs))
        return str(path)

    def test_matching_trajectory_passes(self, tmp_path):
        mod = _load_sentinel()
        ledger = self._ledger(tmp_path, [300.0, 150.0, 100.0, 100.01])
        history = self._history(tmp_path, final_obj=100.0, iters=3)
        assert mod.main([ledger, "--history", history]) == 0

    def test_degraded_final_objective_fails(self, tmp_path):
        mod = _load_sentinel()
        # converges just as fast, but to a 50% worse objective
        ledger = self._ledger(tmp_path, [300.0, 160.0, 150.0, 150.0])
        history = self._history(tmp_path, final_obj=100.0, iters=3)
        assert mod.main([ledger, "--history", history]) == 1

    def test_slower_convergence_fails(self, tmp_path):
        mod = _load_sentinel()
        # same final objective, but the trajectory needs 6 updates
        # (golden 3 + slack 1 allows 4)
        ledger = self._ledger(
            tmp_path, [300.0, 250.0, 200.0, 150.0, 120.0, 100.0]
        )
        history = self._history(tmp_path, final_obj=100.0, iters=3)
        assert mod.main([ledger, "--history", history]) == 1

    def test_recorded_anomaly_fails(self, tmp_path):
        mod = _load_sentinel()
        ledger = self._ledger(tmp_path, [300.0, 100.0], anomaly=True)
        history = self._history(tmp_path)
        assert mod.main([ledger, "--history", history]) == 1

    def test_target_metric_gate(self, tmp_path):
        mod = _load_sentinel()
        ledger = self._ledger(
            tmp_path, [300.0, 150.0, 100.0, 100.0],
            metrics=[0.9, 0.7, 0.6, 0.6],
        )
        history = self._history(tmp_path, final_obj=100.0, iters=3, target=2)
        assert mod.main([
            ledger, "--history", history,
            "--target-metric", "0.75", "--lower-is-better",
        ]) == 0
        # golden says the metric should be reached by update 1: fail
        history_tight = self._history(
            tmp_path, final_obj=100.0, iters=3, target=1
        )
        assert mod.main([
            ledger, "--history", history_tight,
            "--target-metric", "0.65", "--lower-is-better",
        ]) == 1

    def test_infra_problems_report_and_pass(self, tmp_path):
        mod = _load_sentinel()
        # no golden baseline records at all: report-and-pass
        ledger = self._ledger(tmp_path, [300.0, 100.0])
        empty_hist = tmp_path / "none.jsonl"
        empty_hist.write_text("")
        assert mod.main([ledger, "--history", str(empty_hist)]) == 0
        assert mod.main([
            ledger, "--history", str(tmp_path / "missing.jsonl")
        ]) == 0
        # ledger with no coordinate records: nothing to gate
        bare = tmp_path / "bare.jsonl"
        bare.write_text('{"type": "meta", "ts": 1.0, "phase": "start"}\n')
        assert mod.main([
            str(bare), "--history", self._history(tmp_path)
        ]) == 0
        # crash-truncated tail: the readable prefix is still gated
        trunc = self._ledger(tmp_path, [300.0, 150.0, 100.0, 100.0])
        with open(trunc, "a") as f:
            f.write('{"type": "progress", "kind"')
        assert mod.main([trunc, "--history", self._history(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# Driver-level contracts on a tiny GLMix fit (slow lane).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_glmix(tmp_path_factory):
    """Tiny logistic GLMix fixture (fixed + per_user) for driver runs."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    root = tmp_path_factory.mktemp("progress_glmix")
    rng = np.random.default_rng(7)
    n_users, rows, dg, du = 6, 10, 4, 2
    wg = rng.normal(size=dg)
    wu = {f"user{i}": rng.normal(size=du) for i in range(n_users)}

    def make(n_rows, seed):
        r = np.random.default_rng(seed)
        records = []
        for i in range(n_rows):
            user = f"user{i % n_users}"
            xg = r.normal(size=dg)
            xu = r.normal(size=du)
            z = xg @ wg + xu @ wu[user]
            y = 1.0 if 1 / (1 + np.exp(-z)) > r.random() else 0.0
            records.append({
                "uid": f"r{i}",
                "label": y,
                "features": [("g", str(j), xg[j]) for j in range(dg)],
                "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                "metadataMap": {"userId": user},
            })
        return records

    train_dir = root / "train"
    test_dir = root / "test"
    train_dir.mkdir()
    test_dir.mkdir()
    write_training_examples(
        str(train_dir / "part-00000.avro"), make(n_users * rows, 1)
    )
    write_training_examples(
        str(test_dir / "part-00000.avro"), make(n_users * 4, 2)
    )
    config = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {
                "feature_bags": ["userFeatures"], "add_intercept": False,
            },
        },
        "coordinates": {
            "fixed": {
                "type": "fixed",
                "feature_shard": "global",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 0.1,
                },
            },
            "per_user": {
                "type": "random",
                "feature_shard": "per_user",
                "random_effect_type": "userId",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 1.0,
                },
            },
        },
        "update_order": ["fixed", "per_user"],
    }
    cfg_path = root / "game.json"
    cfg_path.write_text(json.dumps(config))
    return {"train": train_dir, "test": test_dir, "config": cfg_path}


def _train_argv(tiny_glmix, out, extra=()):
    return [
        "--train-data-dirs", str(tiny_glmix["train"]),
        "--validation-data-dirs", str(tiny_glmix["test"]),
        "--coordinate-config", str(tiny_glmix["config"]),
        "--task", "LOGISTIC_REGRESSION",
        "--output-dir", str(out),
        "--evaluator", "AUC",
        "--num-outer-iterations", "2",
        *extra,
    ]


@pytest.mark.slow
class TestDriverProgressContracts:
    def test_progress_out_end_to_end(self, tiny_glmix, tmp_path):
        """A --progress-out run writes a schema-valid ledger whose records
        reconstruct into a convergence report, and the introspection port
        file carries the bound ephemeral port."""
        from photon_ml_tpu.cli.train_game import main

        ledger_path = tmp_path / "progress.jsonl"
        port_file = tmp_path / "port"
        rc = main(_train_argv(tiny_glmix, tmp_path / "out", extra=(
            "--progress-out", str(ledger_path),
            "--introspect-port", "0",
            "--introspect-port-file", str(port_file),
        )))
        assert rc == 0
        assert int(port_file.read_text()) > 0
        records = validate_ledger(str(ledger_path))
        assert records[-1]["phase"] == "finish"
        assert records[-1]["healthy"] is True
        progress = extract_progress_records(records)
        coords = [r for r in progress if r["kind"] == "coordinate"]
        # 2 outers x 2 coordinates, all finite, with solver joins on the
        # fixed coordinate
        assert len(coords) == 4
        assert all(math.isfinite(r["objective"]) for r in coords)
        fixed = [r for r in coords if r["coordinate"] == "fixed"]
        assert all("solver_iterations" in r for r in fixed)
        assert all("coef_delta_norm" in r for r in fixed)
        vals = [r for r in progress if r["kind"] == "validation"]
        assert len(vals) == 4
        report = convergence_report(progress)
        assert report["num_updates"] == 4
        assert report["final_objective"] <= report["first_objective"]

    def test_divergence_injection_aborts_without_artifact(
        self, tiny_glmix, tmp_path, monkeypatch
    ):
        """An Inf objective mid-fit must emit AnomalyEvent, exit nonzero,
        record the anomaly in the ledger, and save NO model artifact."""
        from tests._listeners import CollectingListener

        from photon_ml_tpu.algorithm.coordinate_descent import (
            CoordinateDescent,
        )
        from photon_ml_tpu.cli.train_game import main

        orig = CoordinateDescent._record_progress
        calls = {"n": 0}

        def poisoned(self, outer, cid, coord, prev_model, model, objective,
                     loss, regularization):
            calls["n"] += 1
            if calls["n"] >= 2:  # second coordinate update blows up
                objective = float("inf")
            orig(self, outer, cid, coord, prev_model, model, objective,
                 loss, regularization)

        monkeypatch.setattr(
            CoordinateDescent, "_record_progress", poisoned
        )
        CollectingListener.received = []
        out = tmp_path / "out"
        ledger_path = tmp_path / "progress.jsonl"
        rc = main(_train_argv(tiny_glmix, out, extra=(
            "--progress-out", str(ledger_path),
            "--event-listeners", "tests._listeners.CollectingListener",
        )))
        assert rc == 2
        assert calls["n"] == 2  # aborted at the poisoned update
        assert not (out / "best").exists()  # no garbage artifact
        anomalies = [e for e in CollectingListener.received
                     if isinstance(e, AnomalyEvent)]
        assert len(anomalies) == 1
        assert anomalies[0].kind == "non_finite_objective"
        records = validate_ledger(str(ledger_path))
        assert records[-1]["phase"] == "finish"
        assert records[-1]["healthy"] is False
        kinds = [r.get("kind") for r in extract_progress_records(records)]
        assert "anomaly" in kinds

    def test_disabled_default_bitwise_identical(self, tiny_glmix, tmp_path):
        """The convergence plane must not perturb training: the same tiny
        fit with and without --progress-out produces bitwise-identical
        coefficients."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.model_io import load_game_model

        def train(tag, progress):
            out = tmp_path / tag
            extra = (
                ("--progress-out", str(out / "progress.jsonl"))
                if progress else ()
            )
            run(parse_args(_train_argv(tiny_glmix, out, extra=extra)))
            model, _ = load_game_model(str(out / "best"))
            return model

        plain = train("plain", progress=False)
        tracked = train("tracked", progress=True)
        fixed_p = np.asarray(plain.models["fixed"].coefficients.means)
        fixed_t = np.asarray(tracked.models["fixed"].coefficients.means)
        np.testing.assert_array_equal(fixed_p, fixed_t)
        re_p = dict(plain.models["per_user"].items())
        re_t = dict(tracked.models["per_user"].items())
        assert re_p == re_t

    def test_progress_rejects_sweep_configs(self, tiny_glmix, tmp_path):
        """--progress-out tracks ONE fit; a regularization sweep must fail
        fast instead of interleaving trajectories."""
        import copy

        from photon_ml_tpu.cli.train_game import parse_args, run

        cfg = json.loads(tiny_glmix["config"].read_text())
        sweep = copy.deepcopy(cfg)
        opt = sweep["coordinates"]["fixed"]["optimizer"]
        opt.pop("regularization_weight")
        opt["regularization_weights"] = [0.1, 10.0]
        cfg_path = tmp_path / "sweep.json"
        cfg_path.write_text(json.dumps(sweep))
        argv = _train_argv(tiny_glmix, tmp_path / "out")
        argv[argv.index(str(tiny_glmix["config"]))] = str(cfg_path)
        with pytest.raises(ValueError, match="ONE fit"):
            run(parse_args(argv + ["--progress-out",
                                   str(tmp_path / "p.jsonl")]))
