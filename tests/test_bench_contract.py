"""The benchmark's machine-read contract, in smoke mode on CPU.

The driver runs ``python bench.py`` at the end of every round and parses
exactly one JSON line; this gate keeps that contract honest (keys, types,
the north-star grid tile as the headline, pinned-vs-fresh baseline
reporting, engine A/B recording incl. the quality-gated bf16 entry, and
the stale-fallback failure path) without TPU hardware.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow  # each case re-runs bench.py as a child


def _smoke_env(**extra):
    env = dict(
        os.environ,
        BENCH_SMOKE="1",
        JAX_PLATFORMS="cpu",
        BENCH_PLAN_CACHE="",
        PHOTON_ML_TPU_COMPILE_CACHE="",
    )
    env.update(extra)
    return env


def test_bench_smoke_contract():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=_smoke_env(),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "glmix_logistic_train_throughput"
    assert payload["unit"] == "example_passes/sec/chip"
    assert payload["value"] > 0
    assert payload["vs_baseline"] > 0
    assert "error" not in payload
    assert "stale" not in payload

    # the HEADLINE is the north-star workload: the single-chip tile of the
    # 1B-coefficient grid layout (VERDICT r4 #4)
    assert payload["headline_workload"] == (
        "grid_2^24_coef_chip_tile_of_1B_layout"
    )
    assert payload["value"] == payload["grid16m_passes_per_s"]
    assert payload["grid16m_engine"] in ("ell", "benes", "fused")
    assert payload["grid16m_iterations"] >= 1

    # the convergence clock runs on the headline workload
    assert payload["wallclock_to_auc_s"] >= 0
    assert payload["auc_final"] >= payload["auc_target"]

    # both baseline ratios are reported; vs_baseline is one of them
    assert payload["vs_baseline_fresh"] > 0
    assert payload["vs_baseline"] in (
        payload["vs_baseline_fresh"], payload.get("vs_baseline_pinned")
    )

    # every engine of the small-dim A/B is recorded, including the
    # reduced-precision candidate; the small-dim best is at least the best
    # EXACT engine (fused_bf16 only takes it when its quality gate passes)
    engines = payload["engines"]
    for key in ("ell", "benes", "fused", "fused_bf16"):
        assert key in engines and engines[key] > 0, engines
    exact_best = max(v for k, v in engines.items() if k != "fused_bf16")
    assert payload["smalldim_passes_per_s"] >= exact_best
    assert payload["smalldim_vs_baseline"] > 0


def test_bench_failure_emits_stale_lastgood(tmp_path):
    """When the backend is unreachable and nothing was measured, the bench
    replays the repo's last good record marked stale (exit 3) instead of
    zeroing the round — the r4 failure mode (VERDICT r4 weak #1)."""
    # stage a bench.py copy next to a fabricated last-good record so the
    # test cannot touch the real repo files
    import shutil

    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path / "bench.py")
    lastgood = {
        "metric": "glmix_logistic_train_throughput",
        "value": 12345.6,
        "unit": "example_passes/sec/chip",
        "vs_baseline": 11.5,
        "headline_workload": "grid_2^24_coef_chip_tile_of_1B_layout",
        "measured_at_unix": 1785490000.0,
        "host": "testhost",
    }
    (tmp_path / "BENCH_LASTGOOD.json").write_text(json.dumps(lastgood))
    # not smoke (so the fallback path is live), but force an unreachable
    # backend: the preflight child import must fail fast
    env = _smoke_env(
        BENCH_SMOKE="0",
        JAX_PLATFORMS="nonexistent-backend",
        BENCH_PREFLIGHT_S="60",
        PYTHONPATH=REPO,
    )
    proc = subprocess.run(
        [sys.executable, str(tmp_path / "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=tmp_path,
    )
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-2000:])
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["value"] == 12345.6
    assert payload["stale"] is True
    assert payload["error"]
    assert payload["measured_at_unix"] == 1785490000.0


def test_bench_failure_without_lastgood_is_zero(tmp_path):
    """No partial, no last-good record -> the zeros line with exit 2 (the
    caller must be able to tell 'nothing known' from 'stale known')."""
    import shutil

    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path / "bench.py")
    env = _smoke_env(
        BENCH_SMOKE="0",
        JAX_PLATFORMS="nonexistent-backend",
        BENCH_PREFLIGHT_S="60",
        PYTHONPATH=REPO,
    )
    proc = subprocess.run(
        [sys.executable, str(tmp_path / "bench.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=tmp_path,
    )
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-2000:])
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["value"] == 0.0
    assert payload["error"]


def _artifact_fingerprint(path):
    """(exists, content) of a bench artifact — smoke runs must leave the
    committed full-scale record untouched."""
    if not os.path.exists(path):
        return (False, None)
    with open(path) as f:
        return (True, f.read())


def test_bench_re_adaptive_contract():
    """``--re-adaptive`` emits one JSON line with the lane-efficiency and
    speedup fields the driver parses, and the adaptive path must beat
    lockstep on executed lane-iterations even at smoke scale."""
    artifact = os.path.join(REPO, "BENCH_RE_ADAPTIVE.json")
    before = _artifact_fingerprint(artifact)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--re-adaptive"],
        capture_output=True, text=True, timeout=900, env=_smoke_env(),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "re_adaptive_speedup"
    assert "error" not in payload
    assert payload["unit"] == "x_vs_oneshot"
    assert payload["value"] > 0
    assert payload["adaptive_wall_s"] > 0
    assert payload["oneshot_wall_s"] > 0
    assert payload["executed_lane_iterations"] > 0
    # lane compaction must shed work relative to the lockstep equivalent
    assert payload["lane_iteration_savings"] is not None
    assert payload["lane_iteration_savings"] > 1.0
    assert 0.0 <= payload["wasted_lane_fraction"] < 1.0
    # one entry per bucket; widths start at the bucket size and descend
    # through powers of two
    for widths, rounds in zip(payload["dispatch_widths"], payload["rounds"]):
        assert len(widths) == rounds
        assert widths == sorted(widths, reverse=True)
        for w in widths[1:]:
            assert w & (w - 1) == 0
    assert payload["chunk_iters"] >= 1
    # smoke mode must not touch the committed full-scale artifact
    # (BENCH_RE_ADAPTIVE_WRITE gates the file write, mirroring the other
    # sub-benches)
    assert _artifact_fingerprint(artifact) == before


def test_bench_cd_scores_contract():
    """``--cd-scores`` emits one JSON line with the score-plane fields the
    driver parses. The overhead-reduction ratio is noisy at smoke scale, so
    the gate pins the DETERMINISTIC claims: zero row transfers per steady
    iteration on the device plane, exact parity, and no host re-sums."""
    artifact = os.path.join(REPO, "BENCH_CD_SCORES.json")
    before = _artifact_fingerprint(artifact)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--cd-scores"],
        capture_output=True, text=True, timeout=900, env=_smoke_env(),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "cd_score_plane_overhead_reduction"
    assert "error" not in payload
    assert payload["unit"] == "fraction_vs_host_plane"
    assert payload["value"] is not None
    assert payload["host_wall_s"] > 0
    assert payload["device_wall_s"] > 0
    assert payload["host_overhead_s"] > 0
    assert payload["device_overhead_s"] > 0
    # host and device planes must train the same model
    assert payload["parity_max_abs_diff"] <= 1e-6
    dev = payload["device_transfers"]
    host = payload["host_transfers"]
    # device plane: zero row-length transfers in the steady state
    assert dev["score_plane"] == "device"
    assert dev["row_transfers_h2d"] == 0
    assert dev["row_transfers_d2h"] == 0
    assert dev["row_transfers_per_iter"] == 0.0
    assert dev["device_plane_updates"] == dev["coordinate_updates"]
    # host plane: 2 row arrays per update (score pull + residual push)
    assert host["score_plane"] == "host"
    assert host["row_transfers_h2d"] == host["coordinate_updates"]
    assert host["row_transfers_d2h"] == host["coordinate_updates"]
    # the double-total_score() fix: no full C-way re-sums on either plane
    assert host["host_score_sums"] == 0
    assert dev["host_score_sums"] == 0
    # smoke mode must not touch the committed full-scale artifact
    assert _artifact_fingerprint(artifact) == before


def test_bench_streaming_contract(tmp_path):
    """``--streaming`` emits one JSON line A/B-ing the out-of-core streamed
    fit against the in-memory fit on the same on-disk Avro dataset. Wall
    clocks are noisy at smoke scale, so the gate pins the DETERMINISTIC
    claims: >=4 fixed-shape blocks, held-out AUC parity within 1e-3, zero
    post-warmup retraces, and honest decode/stall accounting behind the
    hide ratio."""
    artifact = os.path.join(REPO, "BENCH_STREAMING.json")
    history = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    before = _artifact_fingerprint(artifact)
    history_before = _artifact_fingerprint(history)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--streaming"],
        capture_output=True, text=True, timeout=900,
        env=_smoke_env(BENCH_TELEMETRY_DIR=str(tmp_path)),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "streaming_fit_wall_s"
    assert "error" not in payload
    assert payload["unit"] == "seconds"
    assert payload["value"] > 0
    assert payload["inmemory_fit_s"] > 0
    assert payload["cold_epoch_s"] > 0
    assert payload["warm_epoch_s"] > 0
    # the acceptance shape: at least 4 fixed-size blocks over several files
    assert payload["num_blocks"] >= 4
    assert payload["num_files"] >= 2
    assert payload["blocks_streamed"] >= payload["num_blocks"]
    # streamed full-batch trains the same model (held-out AUC parity)
    assert payload["auc_delta"] <= 1e-3
    # fixed shapes: nothing compiles after the first streamed fit
    assert payload["retraces_after_warmup"] == 0
    # prefetch accounting is internally consistent
    assert payload["decode_s"] > 0
    assert payload["decode_work_s"] > 0
    assert payload["stall_s"] >= 0
    assert payload["upload_hidden_s"] >= 0
    assert 0.0 <= payload["prefetch_hide_ratio"] <= 1.0
    assert payload["staging_bound_mb"] >= 0
    # the decoded block cache: the cold fit re-visits blocks from the cache
    # after its first data pass, and the warm fit does ZERO Avro work —
    # every warm block is a cache hit
    assert payload["cache_hit_blocks"] >= 0
    assert payload["warm_decode_work_s"] == 0.0
    assert payload["warm_cache_hit_blocks"] == payload["warm_blocks_streamed"]
    assert payload["warm_blocks_streamed"] >= payload["num_blocks"]
    assert payload["warm_prefetch_hide_ratio"] == 1.0
    # H2D byte accounting is live on both epochs
    assert payload["cold_h2d_bytes"] > 0
    assert payload["warm_h2d_bytes"] > 0
    # hierarchical residency arm: the gap-pinned resident set halves (at
    # least) the warm-epoch upload bytes on the same trajectory, adds no
    # programs, and the byte ledger telescopes exactly
    res = payload["residency"]
    assert 1 <= res["resident_blocks"] < payload["num_blocks"]
    assert res["h2d_ratio"] <= 0.5
    assert res["h2d_bytes"] + res["h2d_saved_bytes"] == (
        payload["warm_h2d_bytes"]
    )
    assert res["auc_delta"] <= 1e-3
    assert res["retraces"] == 0
    assert res["resident_matches_gap_topk"] is True
    assert len(res["resident_set"]) == res["resident_blocks"]
    assert res["pins"] >= res["resident_blocks"]
    # gap-guided scheduling A/B (DuHL): the fields the driver parses, with
    # sane visit accounting and both arms' trajectories recorded; the
    # shuffle arm visits every block every epoch so it always streams more
    assert payload["gap_visits_to_target"] >= 1
    assert payload["shuffle_visits_to_target"] >= 1
    assert payload["gap_vs_shuffle_visits"] > 0
    gap_ab = payload["gap_schedule_ab"]
    assert gap_ab["num_blocks"] > len(gap_ab["hard_blocks"]) >= 1
    assert 0.5 <= gap_ab["target_auc"] <= 1.0
    assert gap_ab["shuffle_trajectory"] and gap_ab["gap_trajectory"]
    assert (
        gap_ab["shuffle_trajectory"][-1][0] > gap_ab["gap_trajectory"][-1][0]
    )
    telemetry = payload["telemetry"]
    assert telemetry["validated"] is True
    assert telemetry["ledger"].startswith(str(tmp_path))
    # every stream_* program traced exactly once across both fits AND the
    # gap-scheduling A/B (which reuses the per-block program shapes and
    # drives the solver seam directly, below the row-plane programs)
    stream_traces = {
        k: v for k, v in telemetry["jit_traces"].items()
        if k.startswith("stream_")
    }
    assert stream_traces and all(v == 1 for v in stream_traces.values()), (
        stream_traces
    )
    assert "stream_gap_probe/trace" in stream_traces
    # smoke mode leaves committed records untouched
    assert _artifact_fingerprint(artifact) == before
    assert _artifact_fingerprint(history) == history_before


def test_bench_streaming_committed_artifact():
    """The committed full-scale record must back the PR's headline claims:
    the WARM epoch (every block reloaded from the decoded block cache) does
    zero Avro work, hides everything by the wall-based hide ratio, and
    lands within 1.2x of the in-memory fit; the prefetcher hides >=50% of
    cold decode wall clock when the host has a core to decode on (overlap
    is physically impossible on one CPU, where the decode thread and the
    solver timeshare; the record then must show the honest degraded
    accounting); AUC parity holds on >=4 blocks; nothing retraces after
    warmup; and the streamed fit's peak host RSS stays bounded (it must
    not grow past the in-memory fit's)."""
    artifact = os.path.join(REPO, "BENCH_STREAMING.json")
    assert os.path.exists(artifact), "full-scale --streaming record missing"
    with open(artifact) as f:
        payload = json.load(f)
    assert payload["metric"] == "streaming_fit_wall_s"
    assert payload["num_blocks"] >= 4
    if payload["cpus"] >= 2:
        assert payload["prefetch_hide_ratio"] >= 0.5
        assert payload["decode_workers"] >= 1
    else:
        # single-CPU record: decode work must be fully accounted and the
        # stall side must show it was exposed, not silently dropped
        assert payload["decode_workers"] == 0
        assert payload["decode_s"] > 0
        assert 0.0 <= payload["prefetch_hide_ratio"] <= 1.0
    # warm-epoch contract: zero decode work, every block a cache hit, the
    # wall-based hide ratio >= 0.8, and wall clock within 1.2x in-memory
    assert payload["warm_decode_work_s"] == 0.0
    assert payload["warm_cache_hit_blocks"] == payload["warm_blocks_streamed"]
    assert payload["warm_prefetch_hide_ratio"] >= 0.8
    assert payload["warm_epoch_s"] <= 1.2 * payload["inmemory_fit_s"]
    assert payload["upload_hidden_s"] >= 0
    assert payload["auc_delta"] <= 1e-3
    assert payload["retraces_after_warmup"] == 0
    assert payload["peak_rss_stream_delta_mb"] <= (
        payload["peak_rss_inmemory_delta_mb"]
        + payload["staging_bound_mb"] * 4 + 256
    )
    # hierarchical residency: the committed record must back the headline
    # claim — the gap-pinned resident set cuts warm-epoch H2D bytes >=2x
    # at bitwise AUC parity, the set was CHOSEN by the gap probe (equals
    # the top-k of the final measured gaps, not a static prefix), and the
    # residency fit is no slower than the plain warm epoch
    res = payload["residency"]
    assert payload["warm_h2d_bytes"] >= 2 * res["h2d_bytes"]
    assert res["auc_delta"] <= 1e-6
    assert res["retraces"] == 0
    assert res["resident_matches_gap_topk"] is True
    assert res["warm_epoch_s"] <= 1.2 * payload["warm_epoch_s"]
    # DuHL gap scheduling: the committed record must back the headline
    # claim — the gap-scheduled arm sustains the held-out AUC target in
    # >=2x fewer block visits than the blind per-epoch shuffle
    assert payload["gap_vs_shuffle_visits"] >= 2.0
    assert payload["gap_schedule_ab"]["target_reached"] == {
        "gap": True, "shuffle": True
    }


def test_bench_cd_async_contract(tmp_path):
    """``--cd-async`` emits one JSON line comparing the sync and async CD
    schedules. The speedup ratio is noisy at smoke scale, so the gate pins
    the DETERMINISTIC claims: AUC parity between the arms, retrace parity
    (the async schedule compiles nothing new), nonzero per-phase overlap
    attribution with near-full ledger coverage, and a bounded overlap
    fraction."""
    artifact = os.path.join(REPO, "BENCH_CD_ASYNC.json")
    history = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    before = _artifact_fingerprint(artifact)
    history_before = _artifact_fingerprint(history)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--cd-async"],
        capture_output=True, text=True, timeout=900,
        env=_smoke_env(BENCH_TELEMETRY_DIR=str(tmp_path)),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "cd_async_outer_iter_speedup"
    assert "error" not in payload
    assert payload["unit"] == "x_vs_sync"
    assert payload["value"] > 0
    assert payload["sync_wall_s"] > 0
    assert payload["async_wall_s"] > 0
    assert payload["staleness"] >= 1
    # both arms train to the same quality — the async gate
    assert abs(payload["auc_delta"]) <= 0.05
    # the async schedule reuses the sync pow2 program registry: no new
    # solver traces after the sync warmup
    assert payload["trace_parity"] is True
    # the analyzer attributed concurrency: every pipelined phase shows
    # nonzero overlap, and the busy-time-relative fraction is bounded
    for phase in ("fe_solve", "re_solve", "cd_driver"):
        assert payload["overlap_s"][phase] > 0, payload["overlap_s"]
    assert 0.0 < payload["overlap_fraction"] < 1.0
    assert payload["ledger_coverage"] >= 0.95
    # both arms stay on the device plane with zero steady-state row moves
    for arm in ("sync_transfers", "async_transfers"):
        t = payload[arm]
        assert t["score_plane"] == "device"
        assert t["row_transfers_h2d"] == 0
        assert t["row_transfers_d2h"] == 0
        assert t["device_plane_updates"] == t["coordinate_updates"]
    # CPU smoke runs under emulated device latency, and says so
    assert payload["device_latency_emulated"] is True
    assert payload["emulated_latency_s"] > 0
    telemetry = payload["telemetry"]
    assert telemetry["validated"] is True
    assert telemetry["ledger"].startswith(str(tmp_path))
    # smoke mode leaves committed records untouched
    assert _artifact_fingerprint(artifact) == before
    assert _artifact_fingerprint(history) == history_before


def test_bench_cd_async_committed_artifact():
    """The committed full-scale record must back the PR's headline claim:
    >=1.3x outer-iteration speedup at AUC parity with honest labeling of
    the latency-emulation methodology."""
    artifact = os.path.join(REPO, "BENCH_CD_ASYNC.json")
    assert os.path.exists(artifact), "full-scale --cd-async record missing"
    with open(artifact) as f:
        payload = json.load(f)
    assert payload["metric"] == "cd_async_outer_iter_speedup"
    assert payload["value"] >= 1.3
    assert abs(payload["auc_delta"]) <= 0.02
    assert payload["trace_parity"] is True
    assert payload["ledger_coverage"] >= 0.95
    assert "device_latency_emulated" in payload
    if payload["device_latency_emulated"]:
        assert payload["emulated_latency_s"] > 0


def test_bench_tuning_contract(tmp_path):
    """``--tuning`` closes the telemetry loop: default replay under a run
    ledger -> analyzer replay -> tuner proposal -> tuned replay, with the
    default-vs-tuned deltas in the payload. Smoke must leave both the
    committed artifact AND the perf-trajectory history untouched."""
    artifact = os.path.join(REPO, "BENCH_TUNING.json")
    history = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    before = _artifact_fingerprint(artifact)
    history_before = _artifact_fingerprint(history)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tuning"],
        capture_output=True, text=True, timeout=900,
        env=_smoke_env(BENCH_TELEMETRY_DIR=str(tmp_path)),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "tuning_p99_delta_s"
    assert "error" not in payload
    assert payload["unit"] == "seconds_default_minus_tuned"
    # both arms fully recorded, with the connecting proposal
    for arm in ("default", "tuned"):
        assert payload[arm]["latency_p99_s"] > 0
        assert payload[arm]["bucket_sizes"]
        assert payload[arm]["cache_capacity"] > 0
    assert payload["value"] == pytest.approx(
        payload["default"]["latency_p99_s"]
        - payload["tuned"]["latency_p99_s"],
        abs=1e-6,
    )
    assert set(payload["deltas"]) == {
        "latency_p99_s", "requests_per_s", "xla_compiles"
    }
    # the proposal audited the full knob space and the A/B always has a
    # control + at least one trial arm
    assert payload["proposal"]["knobs_considered"] >= 4
    assert len(payload["proposal"]["candidates"]) >= 2
    # the analyzer replay attributed the ledger's wall-clock
    assert payload["report_coverage"] >= 0.95
    telemetry = payload["telemetry"]
    assert telemetry["validated"] is True
    assert telemetry["ledger_records"] > 0
    # telemetry files land in BENCH_TELEMETRY_DIR, not the repo
    assert telemetry["ledger"].startswith(str(tmp_path))
    # smoke mode leaves committed records untouched
    assert _artifact_fingerprint(artifact) == before
    assert _artifact_fingerprint(history) == history_before


def test_bench_serving_validates_own_telemetry(tmp_path):
    """Every telemetry-mode sub-bench validates its own ledger + Chrome
    trace before writing the BENCH artifact; the files are real and land
    outside the repo."""
    from photon_ml_tpu.telemetry import validate_chrome_trace, validate_ledger

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serving"],
        capture_output=True, text=True, timeout=900,
        env=_smoke_env(BENCH_TELEMETRY_DIR=str(tmp_path)),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    telemetry = payload["telemetry"]
    assert telemetry["validated"] is True
    # the paths the bench reported really validate from the outside too
    records = validate_ledger(telemetry["ledger"])
    assert len(records) == telemetry["ledger_records"]
    validate_chrome_trace(telemetry["trace"])
    span_names = {r["name"] for r in records if r["type"] == "span"}
    assert any(n.startswith("serve/") for n in span_names)


def test_bench_history_append_when_opted_in(tmp_path):
    """BENCH_HISTORY_WRITE opts a smoke run into the perf-trajectory
    append; the record carries the fields check_perf_trajectory.py reads."""
    import shutil

    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path / "bench.py")
    env = _smoke_env(
        BENCH_HISTORY_WRITE="1",
        BENCH_TELEMETRY_DIR=str(tmp_path / "telemetry"),
        PYTHONPATH=REPO,
    )
    proc = subprocess.run(
        [sys.executable, str(tmp_path / "bench.py"), "--tuning"],
        capture_output=True, text=True, timeout=900, env=env, cwd=tmp_path,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    history = tmp_path / "BENCH_HISTORY.jsonl"
    assert history.exists()
    (rec,) = [json.loads(l) for l in history.read_text().splitlines()]
    assert rec["mode"] == "tuning"
    assert rec["metric"] == "tuning_p99_delta_s"
    assert isinstance(rec["value"], (int, float))
    assert rec["ts"] > 0 and rec["host"]


def test_bench_multihost_committed_artifact():
    """The committed full-scale --multihost record must back the PR's
    observability claims alongside the scaling headline: every cluster arm
    carries the coordinator's skew attribution (busy / allreduce-wait /
    bubble decomposition covering ~100% of pass wall), and the headline
    2-host skew/comm-wait fields are present with sane values — at
    unchanged scaling (the data-parallel speedup must not regress to pay
    for the telemetry, which piggybacks on existing messages)."""
    artifact = os.path.join(REPO, "BENCH_MULTIHOST.json")
    assert os.path.exists(artifact), "full-scale --multihost record missing"
    with open(artifact) as f:
        payload = json.load(f)
    assert payload["metric"] == "multihost_speedup_2hosts"
    # scaling headline unchanged by the observability plane
    assert payload["value"] >= 1.8
    assert payload["speedup_4hosts"] is None or payload["speedup_4hosts"] >= 3.0
    assert payload["auc_parity_delta"] <= 1e-3
    # headline skew/comm-wait attribution for the 2-host arm
    assert 0.0 <= payload["allreduce_wait_frac_2hosts"] < 1.0
    assert payload["straggler_index_2hosts"] >= 1.0
    assert payload["skew_attribution_coverage_2hosts"] >= 0.95
    # per-arm skew: exact decomposition, per-host busy attribution
    for hosts, arm in payload["hosts"].items():
        skew = arm["skew"]
        assert skew is not None, f"arm {hosts} missing skew profile"
        assert skew["passes"] >= 1
        assert skew["attribution_coverage"] >= 0.95
        assert (
            skew["busy_frac"]
            + skew["allreduce_wait_frac"]
            + skew["coordinator_bubble_frac"]
        ) == pytest.approx(skew["attribution_coverage"], abs=0.01)
        assert len(skew["hosts_busy_s"]) == int(hosts)
        assert all(v > 0 for v in skew["hosts_busy_s"].values())
    # the chaos arm profiles too (the surviving host absorbs the blocks)
    chaos_skew = payload["chaos"]["skew"]
    assert chaos_skew is not None
    assert chaos_skew["attribution_coverage"] >= 0.95


def test_bench_history_residency_mode(tmp_path, monkeypatch):
    """The streaming bench appends a 'residency' perf-trajectory record —
    the warm-epoch H2D byte ratio — alongside the streaming headline."""
    import bench

    history = tmp_path / "BENCH_HISTORY.jsonl"
    monkeypatch.setattr(bench, "_HISTORY_PATH", str(history))
    monkeypatch.setattr(bench, "_SMOKE", False)
    bench._append_history(
        {
            "metric": "residency_warm_h2d_ratio",
            "value": 0.35,
            "unit": "x_of_warm_h2d_bytes",
        },
        "residency",
    )
    (rec,) = [json.loads(l) for l in history.read_text().splitlines()]
    assert rec["mode"] == "residency"
    assert rec["metric"] == "residency_warm_h2d_ratio"
    assert 0 < rec["value"] < 1
    assert rec["ts"] > 0 and rec["host"]
