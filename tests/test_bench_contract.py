"""The benchmark's machine-read contract, in smoke mode on CPU.

The driver runs ``python bench.py`` at the end of every round and parses
exactly one JSON line; this gate keeps that contract honest (keys, types,
engine A/B recording incl. the quality-gated bf16 entry, north-star
extras) without TPU hardware.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_contract():
    env = dict(
        os.environ,
        BENCH_SMOKE="1",
        JAX_PLATFORMS="cpu",
        BENCH_PLAN_CACHE="",
        PHOTON_ML_TPU_COMPILE_CACHE="",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)

    assert payload["metric"] == "glmix_logistic_train_throughput"
    assert payload["unit"] == "example_passes/sec/chip"
    assert payload["value"] > 0
    assert payload["vs_baseline"] > 0
    assert "error" not in payload

    engines = payload["engines"]
    # every engine of the A/B is recorded, including the reduced-precision
    # candidate; the headline is at least the best EXACT engine (fused_bf16
    # only takes it when its quality gate passes) and always corresponds to
    # a recorded engine measurement
    for key in ("ell", "benes", "fused", "fused_bf16"):
        assert key in engines and engines[key] > 0, engines
    exact_best = max(v for k, v in engines.items() if k != "fused_bf16")
    assert payload["value"] >= exact_best, (payload["value"], engines)
    assert payload["value"] in engines.values(), (payload["value"], engines)

    # north-star extras ride along
    assert payload["wallclock_to_auc_s"] >= 0
    assert payload["auc_final"] >= payload["auc_target"]
    assert payload["grid16m_passes_per_s"] > 0
    assert payload["grid16m_engine"] in ("ell", "benes", "fused", "fused_bf16")
