"""REAL multi-process cluster tests: several OS processes, one JAX cluster.

The reference validates distribution on in-process local[4] Spark; the
virtual-device harness (conftest.py) is this framework's analog. This test
goes one step further than either: it forms actual
jax.distributed clusters over a local coordinator (2x4 and 4x2
process-by-device layouts — the same code path a TPU pod or Slurm launch
takes, DCN contracts included) and runs the multi-host helpers plus
cross-process data-parallel, grid, and GAME-estimator solves end to end.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("n_procs", [2, 4])
def test_cluster_end_to_end(tmp_path, n_procs):
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    # workers write to FILES, not pipes: an undrained pipe's backpressure
    # would block one worker mid-collective and hang the whole cluster
    logs = [tmp_path / f"worker{i}.log" for i in range(n_procs)]
    procs = []
    for i in range(n_procs):
        with open(logs[i], "w") as fh:
            procs.append(
                subprocess.Popen(
                    [sys.executable, _WORKER, str(i), str(n_procs), str(port)],
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    env=env,
                )
            )
    timed_out = False
    try:
        for p in procs:
            p.wait(timeout=240)
    except subprocess.TimeoutExpired:
        timed_out = True
        for p in procs:
            p.kill()
            p.wait()
    outs = [log.read_text() for log in logs]
    if timed_out:
        pytest.fail("multi-process cluster timed out:\n" + "\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i}:" in out and "OK" in out
