"""REAL multi-process cluster tests: several OS processes, one JAX cluster.

The reference validates distribution on in-process local[4] Spark; the
virtual-device harness (conftest.py) is this framework's analog. This test
goes one step further than either: it forms actual
jax.distributed clusters over a local coordinator (2x4 and 4x2
process-by-device layouts — the same code path a TPU pod or Slurm launch
takes, DCN contracts included) and runs the multi-host helpers plus
cross-process data-parallel, grid, and GAME-estimator solves end to end.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

_WORKER = os.path.join(os.path.dirname(__file__), "_multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_local_devices: int) -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}"
    )
    env["PHOTON_ML_TPU_PLAN_CACHE"] = ""
    env["PHOTON_ML_TPU_COMPILE_CACHE"] = ""
    return env



def _cluster_timeout(n_procs: int, base: int = 240) -> int:
    """N cluster processes time-share the visible cores; on a core-starved
    box (e.g. a 1-core CI runner) everything — XLA compiles included — runs
    serially, so the wall-clock budget must scale with the oversubscription
    factor."""
    try:
        cores = len(os.sched_getaffinity(0))  # honors cgroup/affinity limits
    except AttributeError:  # non-Linux
        cores = os.cpu_count() or 1
    return base * max(1, -(-n_procs // max(cores, 1)))


def _run_cluster(cmds, logs, env, timeout=240):
    """Launch one process per command with file-backed logs, wait for all,
    kill the stragglers on timeout. Returns (timed_out, outputs)."""
    procs = []
    for cmd, log in zip(cmds, logs):
        with open(log, "w") as fh:
            procs.append(
                subprocess.Popen(
                    cmd, stdout=fh, stderr=subprocess.STDOUT, env=env
                )
            )
    timed_out = False
    try:
        for p in procs:
            p.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        for p in procs:
            p.kill()
            p.wait()
    return timed_out, procs, [log.read_text() for log in logs]


def test_cli_cluster_training(tmp_path):
    """The production multi-host launch, end to end: two OS processes run
    the REAL train_game CLI with --coordinator-address/--num-processes/
    --process-id, sweep TWO fixed-effect λ configs (fit_multiple across the
    cluster, per-config digest-keyed checkpoints, validation-evaluator
    selection) over the joint 8-device grid mesh, and exactly one process
    (0) writes the winning model to the shared output directory."""
    import json

    import numpy as np

    from photon_ml_tpu.io.data_reader import write_training_examples

    rng = np.random.default_rng(7)
    n_users, rows, dg, du = 6, 30, 6, 3
    wg = rng.normal(size=dg)
    train_dir = tmp_path / "train"
    val_dir = tmp_path / "val"
    train_dir.mkdir()
    val_dir.mkdir()

    def make(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for i in range(n):
            user = f"user{i % n_users}"
            xg = r.normal(size=dg)
            xu = r.normal(size=du)
            y = 1.0 if 1 / (1 + np.exp(-(xg @ wg))) > r.random() else 0.0
            out.append({
                "uid": f"r{i}",
                "label": y,
                "features": [("g", str(j), xg[j]) for j in range(dg)],
                "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                "metadataMap": {"userId": user},
            })
        return out

    records = make(n_users * rows, 1)
    write_training_examples(str(train_dir / "part-00000.avro"), records)
    write_training_examples(str(val_dir / "part-00000.avro"), make(60, 2))
    config = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {"feature_bags": ["userFeatures"], "add_intercept": False},
        },
        "coordinates": {
            "fixed": {"type": "fixed", "feature_shard": "global",
                      "optimizer": {"optimizer": "LBFGS",
                                    "regularization": "L2",
                                    "regularization_weights": [0.1, 1e5]}},
            "per_user": {"type": "random", "feature_shard": "per_user",
                         "random_effect_type": "userId",
                         "optimizer": {"regularization": "L2",
                                       "regularization_weight": 1.0}},
        },
        "update_order": ["fixed", "per_user"],
    }
    cfg_path = tmp_path / "game.json"
    cfg_path.write_text(json.dumps(config))

    port = _free_port()
    out = tmp_path / "out"
    env = _worker_env(n_local_devices=4)
    logs = [tmp_path / f"cli{i}.log" for i in range(2)]
    cmds = [
        [
            sys.executable, "-m", "photon_ml_tpu.cli.train_game",
            "--train-data-dirs", str(train_dir),
            "--validation-data-dirs", str(val_dir),
            "--evaluator", "AUC",
            "--coordinate-config", str(cfg_path),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--num-outer-iterations", "1",
            "--checkpoint-dir", str(tmp_path / "ckpt"),
            "--parallel-data", "2", "--parallel-feat", "4",
            "--coordinator-address", f"127.0.0.1:{port}",
            "--num-processes", "2", "--process-id", str(i),
        ]
        for i in range(2)
    ]
    timed_out, procs, outs = _run_cluster(
        cmds, logs, env, timeout=_cluster_timeout(2)
    )
    if timed_out:
        pytest.fail("CLI cluster timed out:\n" + "\n".join(outs))
    for i, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"CLI worker {i} failed:\n{o}"

    # the model exists exactly once, written by process 0, and loads
    from photon_ml_tpu.io.model_io import load_game_model

    model, _ = load_game_model(str(out / "best"))
    assert "fixed" in model.models and "per_user" in model.models
    # both sweep configs trained (digest-keyed checkpoint dirs), and the
    # crushed λ=1e5 config did not win: the saved fixed effect has real
    # weight
    ckpts = sorted(p.name for p in (tmp_path / "ckpt").iterdir())
    assert len(ckpts) == 2 and all(c.startswith("config-") for c in ckpts)
    w_fixed = np.asarray(model.models["fixed"].coefficients.means)
    assert float(np.abs(w_fixed).max()) > 1e-2, w_fixed

    # scoring CLI across the same cluster: single-writer scores output
    port2 = _free_port()
    score_out = tmp_path / "scores"
    slogs = [tmp_path / f"score{i}.log" for i in range(2)]
    scmds = [
        [
            sys.executable, "-m", "photon_ml_tpu.cli.score_game",
            "--data-dirs", str(train_dir),
            "--model-dir", str(out / "best"),
            "--output-dir", str(score_out),
            "--coordinator-address", f"127.0.0.1:{port2}",
            "--num-processes", "2", "--process-id", str(i),
        ]
        for i in range(2)
    ]
    timed_out, sprocs, souts = _run_cluster(
        scmds, slogs, env, timeout=_cluster_timeout(2)
    )
    if timed_out:
        pytest.fail("score CLI cluster timed out:\n" + "\n".join(souts))
    for i, (p, o) in enumerate(zip(sprocs, souts)):
        assert p.returncode == 0, f"score worker {i} failed:\n{o}"
    # single-writer invariant, asserted on writer identity (file counts
    # alone could not distinguish a double-writer regression: both
    # processes would write the same deterministic part file names)
    assert f"saved {len(records)} scores" in souts[0]
    assert "saved 0 scores" in souts[1]
    from photon_ml_tpu.io.scores_io import load_scores

    scored = list(load_scores(str(score_out)))
    assert len(scored) == len(records)


@pytest.mark.parametrize("n_procs", [2, 4])
def test_cluster_end_to_end(tmp_path, n_procs):
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    # workers write to FILES, not pipes: an undrained pipe's backpressure
    # would block one worker mid-collective and hang the whole cluster
    logs = [tmp_path / f"worker{i}.log" for i in range(n_procs)]
    cmds = [
        [sys.executable, _WORKER, str(i), str(n_procs), str(port)]
        for i in range(n_procs)
    ]
    timed_out, procs, outs = _run_cluster(
        cmds, logs, env, timeout=_cluster_timeout(n_procs)
    )
    if timed_out:
        pytest.fail("multi-process cluster timed out:\n" + "\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i}:" in out and "OK" in out
