"""Unit tests for pointwise losses and the GLM objective.

Mirrors the reference's derivative checks
(photon-api/src/test/.../function/glm/LogisticLossFunctionTest.scala etc.):
analytic d1/d2 vs finite differences, objective grad vs jax.grad, Hessian-vector
vs jvp-of-grad, normalization-folding equivalence vs explicitly transformed data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.losses import (
    LogisticLoss,
    NormalizationContext,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    make_glm_objective,
)
from photon_ml_tpu.ops import DenseFeatures, EllFeatures, LabeledData

LOSSES = [LogisticLoss, SquaredLoss, PoissonLoss, SmoothedHingeLoss]


@pytest.mark.parametrize("loss", LOSSES)
def test_d1_matches_autodiff(loss):
    # offset avoids the hinge's kinks at u in {0, 1}
    z = jnp.linspace(-3.0, 3.0, 41) + 0.0131
    for y in (0.0, 1.0, 3.0) if loss is PoissonLoss else (0.0, 1.0):
        y_arr = jnp.full_like(z, y)
        d1_auto = jax.vmap(jax.grad(lambda zz, yy: loss.value(zz, yy)))(z, y_arr)
        np.testing.assert_allclose(loss.d1(z, y_arr), d1_auto, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("loss", [LogisticLoss, SquaredLoss, PoissonLoss])
def test_d2_matches_autodiff(loss):
    z = jnp.linspace(-3.0, 3.0, 41) + 0.0131
    y_arr = jnp.ones_like(z)
    d2_auto = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss.value(zz, yy))))(z, y_arr)
    np.testing.assert_allclose(loss.d2(z, y_arr), d2_auto, rtol=2e-4, atol=1e-5)


def test_logistic_stability_large_margins():
    z = jnp.array([-1e4, 1e4])
    y = jnp.array([1.0, 0.0])
    v = LogisticLoss.value(z, y)
    assert bool(jnp.all(jnp.isfinite(v)))
    np.testing.assert_allclose(v, [1e4, 1e4], rtol=1e-6)


def _random_data(rng, n=32, d=7, dense=True):
    X = rng.normal(size=(n, d)).astype(np.float32)
    if dense:
        feats = DenseFeatures(matrix=jnp.asarray(X))
    else:
        mask = rng.random((n, d)) < 0.4
        X = X * mask
        rows, cols = np.nonzero(X)
        from photon_ml_tpu.ops.features import from_scipy_like

        feats = from_scipy_like(rows, cols, X[rows, cols], (n, d))
    y = (rng.random(n) > 0.5).astype(np.float32)
    offsets = rng.normal(size=n).astype(np.float32) * 0.1
    weights = rng.random(n).astype(np.float32) + 0.5
    return LabeledData.create(feats, jnp.asarray(y), jnp.asarray(offsets), jnp.asarray(weights))


@pytest.mark.parametrize("dense", [True, False])
@pytest.mark.parametrize("loss", [LogisticLoss, SquaredLoss, PoissonLoss])
def test_objective_grad_matches_autodiff(rng, loss, dense):
    data = _random_data(rng, dense=dense)
    obj = make_glm_objective(loss)
    w = jnp.asarray(rng.normal(size=7).astype(np.float32)) * 0.3
    l2 = jnp.float32(0.7)
    v, g = obj.value_and_grad(w, data, l2)
    v_ref = obj.value(w, data, l2)
    g_auto = jax.grad(lambda ww: obj.value(ww, data, l2))(w)
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    np.testing.assert_allclose(g, g_auto, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("dense", [True, False])
def test_hessian_vec_matches_autodiff(rng, dense):
    data = _random_data(rng, dense=dense)
    obj = make_glm_objective(LogisticLoss)
    w = jnp.asarray(rng.normal(size=7).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.normal(size=7).astype(np.float32))
    l2 = jnp.float32(0.3)
    hv = obj.hessian_vec(w, v, data, l2)
    grad_fn = lambda ww: obj.value_and_grad(ww, data, l2)[1]
    hv_auto = jax.jvp(grad_fn, (w,), (v,))[1]
    np.testing.assert_allclose(hv, hv_auto, rtol=1e-4, atol=1e-4)


def test_hessian_diag_matches_full_hessian(rng):
    data = _random_data(rng, n=16, d=5)
    obj = make_glm_objective(LogisticLoss)
    w = jnp.asarray(rng.normal(size=5).astype(np.float32)) * 0.3
    l2 = jnp.float32(0.2)
    H = jax.hessian(lambda ww: obj.value(ww, data, l2))(w)
    np.testing.assert_allclose(
        obj.hessian_diag(w, data, l2), jnp.diag(H), rtol=1e-2, atol=1e-3
    )


def test_normalization_folding_equivalent_to_materialized(rng):
    """Objective with (factor, shift) folded in == objective on explicitly
    transformed dense features (the reference's core normalization invariant,
    NormalizationTest.scala)."""
    n, d = 24, 6
    X = rng.normal(size=(n, d)).astype(np.float32) * 3 + 1.5
    X[:, -1] = 1.0  # intercept column
    y = (rng.random(n) > 0.5).astype(np.float32)
    factor = (1.0 / (np.std(X, axis=0) + 1e-9)).astype(np.float32)
    shift = np.mean(X, axis=0).astype(np.float32)
    factor[-1], shift[-1] = 1.0, 0.0

    norm = NormalizationContext(factor=jnp.asarray(factor), shift=jnp.asarray(shift))
    data_raw = LabeledData.create(
        DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y), norm=norm
    )
    Xn = (X - shift) * factor
    data_norm = LabeledData.create(DenseFeatures(matrix=jnp.asarray(Xn)), jnp.asarray(y))

    obj_folded = make_glm_objective(LogisticLoss)
    obj_plain = make_glm_objective(LogisticLoss)

    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    l2 = jnp.float32(0.5)
    v_f, g_f = obj_folded.value_and_grad(w, data_raw, l2)
    v_p, g_p = obj_plain.value_and_grad(w, data_norm, l2)
    np.testing.assert_allclose(v_f, v_p, rtol=1e-4)
    np.testing.assert_allclose(g_f, g_p, rtol=1e-3, atol=1e-3)

    vec = jnp.asarray(rng.normal(size=d).astype(np.float32))
    np.testing.assert_allclose(
        obj_folded.hessian_vec(w, vec, data_raw, l2),
        obj_plain.hessian_vec(w, vec, data_norm, l2),
        rtol=1e-3,
        atol=1e-3,
    )
    np.testing.assert_allclose(
        obj_folded.hessian_diag(w, data_raw, l2),
        obj_plain.hessian_diag(w, data_norm, l2),
        rtol=1e-3,
        atol=1e-3,
    )


def test_ell_matches_dense(rng):
    n, d = 20, 9
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[rng.random((n, d)) < 0.5] = 0.0
    rows, cols = np.nonzero(X)
    from photon_ml_tpu.ops.features import from_scipy_like

    ell = from_scipy_like(rows, cols, X[rows, cols], (n, d))
    dense = DenseFeatures(matrix=jnp.asarray(X))
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    c = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(ell.matvec(w), dense.matvec(w), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ell.rmatvec(c), dense.rmatvec(c), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(ell.rmatvec_sq(c), dense.rmatvec_sq(c), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(ell.to_dense().matrix, X, rtol=1e-6)
