"""Out-of-core streaming training: block planning, the double-buffered
prefetcher, block-sharded solvers, and estimator/CLI parity.

The CI "Streaming parity gate" runs this whole module (including the
slow-marked golden-fixture case): streamed full-batch training must match
the in-memory fit within 1e-3 on held-out metrics, with ZERO extra jit
retraces across blocks — every streamed program compiles exactly once per
(objective, shape), however many blocks, passes, and fits run.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    build_index_maps,
    file_row_counts,
    iter_game_data,
    list_data_files,
    read_game_data,
    write_training_examples,
)
from photon_ml_tpu.streaming import (
    BlockPrefetcher,
    StreamingSource,
    reset_stream_trace_counts,
    solve_streaming,
    solve_streaming_stochastic,
    stream_trace_counts,
    streamed_objective_value,
)

FILE_ROWS = (250, 270, 180)  # uneven on purpose: blocks straddle files
N_ROWS = sum(FILE_ROWS)
D_GLOBAL = 12
D_USER = 4
N_USERS = 10
BLOCK_ROWS = 128  # 700 rows -> 6 blocks, final one ragged (60 real rows)

SHARDS = {
    "global": FeatureShardConfiguration(
        feature_bags=("features",), add_intercept=True
    ),
    "per_user": FeatureShardConfiguration(
        feature_bags=("userFeatures",), add_intercept=False
    ),
}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Synthetic GLMix logistic data over 3 uneven Avro part files."""
    rng = np.random.default_rng(11)
    root = tmp_path_factory.mktemp("stream")
    Xg = rng.normal(size=(N_ROWS, D_GLOBAL)).astype(np.float32)
    Xu = rng.normal(size=(N_ROWS, D_USER)).astype(np.float32)
    users = rng.integers(0, N_USERS, size=N_ROWS)
    wg = rng.normal(size=D_GLOBAL).astype(np.float32)
    wu = {u: rng.normal(size=D_USER).astype(np.float32) for u in range(N_USERS)}
    z = Xg @ wg + np.array(
        [Xu[i] @ wu[users[i]] for i in range(N_ROWS)], np.float32
    )
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random(N_ROWS)).astype(np.float32)

    paths = []
    row = 0
    for fi, n in enumerate(FILE_ROWS):
        recs = []
        for i in range(row, row + n):
            recs.append({
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0 + (i % 2),  # non-trivial weights
                "features": [
                    ("g", str(j), float(Xg[i, j])) for j in range(D_GLOBAL)
                ],
                "userFeatures": [
                    ("u", str(j), float(Xu[i, j])) for j in range(D_USER)
                ],
                "metadataMap": {"userId": f"u{users[i]:02d}"},
            })
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    index_maps = build_index_maps(paths, SHARDS)
    return {"paths": paths, "index_maps": index_maps, "labels": y,
            "users": users, "root": str(root)}


@pytest.fixture(scope="module")
def source(dataset):
    return StreamingSource.open(
        dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
        block_rows=BLOCK_ROWS, id_tags=("userId",),
    )


@pytest.fixture(scope="module")
def mem_data(dataset):
    data, _, _ = read_game_data(
        dataset["paths"], SHARDS, dataset["index_maps"], id_tags=("userId",)
    )
    return data


# --------------------------------------------------------------- satellite 3
class TestFileGranularReader:
    def test_list_data_files(self, dataset):
        files = list_data_files(dataset["root"])
        assert files == dataset["paths"]  # sorted part files of the dir
        assert list_data_files(dataset["paths"]) == dataset["paths"]

    def test_file_row_counts_framing_only(self, dataset):
        counts = file_row_counts(dataset["paths"])
        assert [n for _, n in counts] == list(FILE_ROWS)
        assert [p for p, _ in counts] == dataset["paths"]

    def test_iter_game_data_per_file(self, dataset, mem_data):
        rows_seen = 0
        for (path, data, uids), want in zip(
            iter_game_data(
                dataset["paths"], SHARDS, dataset["index_maps"],
                id_tags=("userId",),
            ),
            FILE_ROWS,
        ):
            assert data.num_rows == want
            assert len(uids) == want
            # stable column space: per-file dims match the global index
            assert data.feature_shards["global"].dim == (
                mem_data.feature_shards["global"].dim
            )
            np.testing.assert_array_equal(
                data.labels, mem_data.labels[rows_seen:rows_seen + want]
            )
            rows_seen += want
        assert rows_seen == N_ROWS

    def test_iter_game_data_requires_index_maps(self, dataset):
        with pytest.raises(ValueError, match="index_maps"):
            next(iter_game_data(dataset["paths"], SHARDS, None))


# ------------------------------------------------------------- block planning
class TestBlockPlan:
    def test_plan_shapes(self, source):
        plan = source.plan
        assert plan.total_rows == N_ROWS
        assert plan.num_blocks == 6  # ceil(700 / 128)
        assert plan.padded_rows == 6 * BLOCK_ROWS
        assert plan.shard_dims["global"] == D_GLOBAL + 1  # + intercept
        assert plan.shard_dims["per_user"] == D_USER
        # dense synthetic rows: width == row nnz (+ intercept)
        assert plan.shard_widths["global"] == D_GLOBAL + 1
        assert plan.shard_widths["per_user"] == D_USER

    def test_block_spans_cross_file_boundaries(self, source):
        plan = source.plan
        # block 1 is rows [128, 256): rows 128..249 from file 0, 250..255
        # from file 1 — one block stitched from two files
        spans = plan.spans(1)
        assert [(fi, hi - lo) for fi, lo, hi in spans] == [(0, 122), (1, 6)]
        # every row is covered exactly once across all blocks
        total = sum(
            hi - lo
            for b in range(plan.num_blocks)
            for _, lo, hi in plan.spans(b)
        )
        assert total == N_ROWS

    def test_ragged_final_block_padding(self, source, mem_data):
        plan = source.plan
        last = plan.num_blocks - 1
        blk = source.build_block(last)
        assert blk.num_real == N_ROWS - last * BLOCK_ROWS == 60
        # real rows carry the data; padding rows are weight-0 no-ops
        np.testing.assert_array_equal(
            blk.labels[:60], mem_data.labels[last * BLOCK_ROWS:]
        )
        assert (blk.weights[60:] == 0).all()
        assert (blk.labels[60:] == 0).all()
        vals, idx = blk.shards["global"]
        assert vals.shape == (BLOCK_ROWS, plan.shard_widths["global"])
        assert (vals[60:] == 0).all()

    def test_blocks_reassemble_dataset(self, source, mem_data):
        labels = np.concatenate([
            source.build_block(b).labels[:source.build_block(b).num_real]
            for b in range(source.plan.num_blocks)
        ])
        np.testing.assert_array_equal(labels, mem_data.labels)

    def test_id_tags_per_block(self, source, dataset):
        blk = source.build_block(0)
        want = [f"u{u:02d}" for u in dataset["users"][:BLOCK_ROWS]]
        assert list(blk.id_tags["userId"]) == want


# --------------------------------------------------------------- prefetcher
class TestPrefetcher:
    def test_order_and_shapes(self, source):
        got = [blk.index for blk in BlockPrefetcher(source, depth=2)]
        assert got == list(range(source.plan.num_blocks))

    def test_custom_order(self, source):
        order = [3, 0, 5, 1]
        pf = BlockPrefetcher(source, shards=("global",), order=order)
        got = [blk.index for blk in pf]
        assert got == order
        assert pf.stats.blocks == len(order)

    def test_sync_mode_exposes_decode(self, source):
        pf = BlockPrefetcher(source, depth=0)
        list(pf)
        assert pf.stats.decode_s > 0
        # synchronous decode hides nothing, and says so
        assert pf.stats.hide_ratio == 0.0

    def test_threaded_stats_accounting(self, source):
        pf = BlockPrefetcher(source, depth=2)
        n = len(list(pf))
        assert n == pf.stats.blocks == source.plan.num_blocks
        assert pf.stats.decode_s > 0
        assert pf.stats.stall_s >= 0
        assert 0.0 <= pf.stats.hide_ratio <= 1.0

    def test_worker_error_propagates(self, source, monkeypatch):
        def boom(*a, **k):
            raise RuntimeError("decode exploded")

        monkeypatch.setattr(source, "build_block", boom)
        with pytest.raises(RuntimeError, match="decode exploded"):
            list(BlockPrefetcher(source, depth=2))

    def test_weight_sum_is_real_rows_only(self, source, mem_data):
        pf = BlockPrefetcher(source, shards=("global",), depth=1)
        total = sum(blk.weight_sum for blk in pf)
        assert total == pytest.approx(float(np.sum(mem_data.weights)), rel=1e-6)

    def test_sync_decode_parallelism_is_serial(self, dataset):
        """depth=0 + a single worker: decode work == decode wall, so the
        reported parallelism sits at ~1.0 (and 0.0 with no decode at all)."""
        from photon_ml_tpu.streaming.prefetch import PrefetchStats

        assert PrefetchStats().decode_parallelism == 0.0
        src = StreamingSource.open(
            dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
            block_rows=BLOCK_ROWS, id_tags=("userId",), decode_workers=0,
        )
        pf = BlockPrefetcher(src, depth=0)
        list(pf)
        assert pf.stats.decode_s > 0
        assert pf.stats.decode_parallelism == pytest.approx(1.0, abs=0.2)

    @pytest.mark.skipif(
        (os.cpu_count() or 1) < 2,
        reason="decode-pool overlap needs >= 2 CPUs",
    )
    def test_decode_pool_overlap(self, dataset):
        """Satellite contract: with a 2-worker decode pool over >= 2 cold
        part files, summed per-thread decode work exceeds decode wall clock
        — the pool genuinely overlapped — and PrefetchStats reports the
        achieved parallelism (the decode_parallelism field the streaming
        bench artifact now carries)."""
        src = StreamingSource.open(
            dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
            block_rows=BLOCK_ROWS, id_tags=("userId",), decode_workers=2,
        )
        pf = BlockPrefetcher(src, depth=2)
        assert len(list(pf)) == src.plan.num_blocks
        assert pf.stats.decode_work_s > 0
        assert pf.stats.decode_parallelism > 1.0


# ---------------------------------------------------------- streamed solvers
def _fe_problem(source, mem_data):
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData

    objective = make_glm_objective(LogisticLoss)
    data = LabeledData.create(
        mem_data.sparse_features("global", engine="ell"),
        jnp.asarray(mem_data.labels),
        weights=jnp.asarray(mem_data.weights),
    )
    dim = source.plan.shard_dims["global"]
    return objective, data, dim


def _make_blocks(source):
    def gen():
        for blk in BlockPrefetcher(source, shards=("global",), depth=2):
            yield blk.data["global"]
    return gen


class TestStreamedSolver:
    def test_full_batch_parity_and_zero_retrace(self, source, mem_data):
        import jax.numpy as jnp

        from photon_ml_tpu.opt import GlmOptimizationConfiguration
        from photon_ml_tpu.opt.config import RegularizationContext
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.types import RegularizationType

        cfg = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.5,
        )
        objective, data, dim = _fe_problem(source, mem_data)
        w0 = jnp.zeros((dim,), jnp.float32)
        ref = solve(objective, w0, data, cfg)

        reset_stream_trace_counts()
        got = solve_streaming(objective, w0, _make_blocks(source), cfg)
        traces1 = dict(stream_trace_counts())
        # identical optimum within float32 solver noise
        assert float(got.value) == pytest.approx(float(ref.value), rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(got.w), np.asarray(ref.w), atol=2e-3
        )
        # a second solve (same objective, same shapes) retraces NOTHING
        got2 = solve_streaming(objective, w0, _make_blocks(source), cfg)
        traces2 = dict(stream_trace_counts())
        assert traces2 == traces1, (traces1, traces2)
        assert float(got2.value) == pytest.approx(float(got.value), rel=1e-6)
        # and every streamed program compiled exactly once
        assert all(v == 1 for v in traces1.values()), traces1

    def test_streamed_objective_value_matches(self, source, mem_data):
        import jax.numpy as jnp

        from photon_ml_tpu.opt import GlmOptimizationConfiguration

        objective, data, dim = _fe_problem(source, mem_data)
        w = jnp.asarray(
            np.random.default_rng(0).normal(size=dim).astype(np.float32)
        )
        l2 = 0.3
        ref, _ = objective.value_and_grad(w, data, l2)
        got = streamed_objective_value(
            objective, w, _make_blocks(source), dim, l2
        )
        assert float(got) == pytest.approx(float(ref), rel=1e-5)

    def test_tron_and_l1_rejected(self, source, mem_data):
        import jax.numpy as jnp

        from photon_ml_tpu.opt import GlmOptimizationConfiguration, OptimizerConfig
        from photon_ml_tpu.opt.config import OptimizerType, RegularizationContext
        from photon_ml_tpu.types import RegularizationType

        objective, _, dim = _fe_problem(source, mem_data)
        w0 = jnp.zeros((dim,), jnp.float32)
        tron = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig(optimizer=OptimizerType.TRON),
        )
        with pytest.raises(ValueError, match="TRON"):
            solve_streaming(objective, w0, _make_blocks(source), tron)
        l1 = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L1),
            regularization_weight=0.5,
        )
        with pytest.raises(ValueError, match="L1"):
            solve_streaming(objective, w0, _make_blocks(source), l1)

    def test_stochastic_mode_converges_close(self, source, mem_data):
        import jax.numpy as jnp

        from photon_ml_tpu.opt import GlmOptimizationConfiguration
        from photon_ml_tpu.opt.config import RegularizationContext
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.types import RegularizationType

        cfg = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.5,
        )
        objective, data, dim = _fe_problem(source, mem_data)
        w0 = jnp.zeros((dim,), jnp.float32)
        ref = solve(objective, w0, data, cfg)

        class _Shard:
            def __init__(self, blk):
                self.data = blk.data["global"]
                self.weight_sum = blk.weight_sum

        class _Blocks:
            def __init__(self, order):
                self.order = order

            def __iter__(self):
                for blk in BlockPrefetcher(
                    source, shards=("global",), order=list(self.order)
                ):
                    yield _Shard(blk)

        total_weight = float(np.sum(mem_data.weights))
        got = solve_streaming_stochastic(
            objective, w0,
            make_blocks_ordered=lambda order: _Blocks(order),
            configuration=cfg,
            num_blocks=source.plan.num_blocks,
            total_weight=total_weight,
            epochs=20, chunk_iters=8, blocks_per_update=3, seed=3,
        )
        # stochastic passes land NEAR the full-batch optimum: the gate is
        # the full-batch objective evaluated at the stochastic solution
        f_star = float(ref.value)
        f0 = float(streamed_objective_value(
            objective, w0, _make_blocks(source), dim, 0.5
        ))
        f_got = float(streamed_objective_value(
            objective, got.w, _make_blocks(source), dim, 0.5
        ))
        assert f_got <= f_star * 1.05, (f_got, f_star)
        # and it actually descended: >85% of the achievable improvement
        assert f_got <= f_star + 0.15 * (f0 - f_star), (f_got, f_star, f0)


# ----------------------------------------------------- estimator + CLI parity
def _auc(scores, labels):
    order = np.argsort(scores)
    ranks = np.empty(len(scores)); ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


class TestStreamingEstimator:
    def _estimator(self, with_re):
        from photon_ml_tpu.data import RandomEffectDataConfiguration
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        l2 = lambda lam: GlmOptimizationConfiguration(  # noqa: E731
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=lam,
        )
        coords = {"fixed": FixedEffectCoordinateConfiguration("global", l2(0.1))}
        if with_re:
            coords["per-user"] = RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId", num_buckets=2),
                optimizer=l2(1.0),
            )
        return GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates=coords,
            update_order=list(coords),
            num_outer_iterations=2 if with_re else 1,
        )

    @pytest.mark.parametrize("with_re", [False, True])
    def test_fit_streaming_matches_fit(self, source, mem_data, with_re):
        fit_mem = self._estimator(with_re).fit(mem_data, mem_data)
        fit_st = self._estimator(with_re).fit_streaming(
            source, validation_data=mem_data
        )
        sc_mem = np.asarray(fit_mem.model.score(mem_data))
        sc_st = np.asarray(fit_st.model.score(mem_data))
        auc_mem = _auc(sc_mem, mem_data.labels)
        auc_st = _auc(sc_st, mem_data.labels)
        assert abs(auc_mem - auc_st) < 1e-3, (auc_mem, auc_st)

    def test_second_fit_retraces_nothing(self, source, mem_data):
        self._estimator(True).fit_streaming(source)  # warm every program
        before = dict(stream_trace_counts())
        self._estimator(True).fit_streaming(source)
        after = dict(stream_trace_counts())
        assert after == before, {
            k: after[k] - before.get(k, 0)
            for k in after if after[k] != before.get(k, 0)
        }

    def test_stochastic_estimator_auc_parity(self, source, mem_data):
        """The optional stochastic mode is gated on held-out AUC parity
        with the in-memory fit. The gate is 1e-2 (vs 1e-3 for full-batch
        streaming, which is algebraically exact): stochastic block passes
        trade a bounded accuracy slack for fixed-memory epochs, and this
        test pins that slack so regressions surface."""
        fit_mem = self._estimator(False).fit(mem_data, mem_data)
        fit_st = self._estimator(False).fit_streaming(
            source, mode="stochastic", stochastic_epochs=20,
            stochastic_chunk_iters=8, blocks_per_update=3,
        )
        auc_mem = _auc(
            np.asarray(fit_mem.model.score(mem_data)), mem_data.labels
        )
        auc_st = _auc(
            np.asarray(fit_st.model.score(mem_data)), mem_data.labels
        )
        assert abs(auc_mem - auc_st) < 1e-2, (auc_mem, auc_st)

    def test_incompatible_modes_raise(self, source):
        est = self._estimator(False)
        est.compute_variance = True
        with pytest.raises(ValueError, match="variance"):
            est.fit_streaming(source)
        with pytest.raises(ValueError, match="mode"):
            self._estimator(False).fit_streaming(source, mode="minibatch")


# --------------------------------------------------- golden fixture (slow)
@pytest.mark.slow
class TestGoldenFixtureStreaming:
    """The CI streaming parity gate on the committed ratings fixture: the
    streamed trainer over the fixture split into blocks must land within
    1e-3 RMSE of the in-memory trainer, with zero extra retraces across
    blocks (same LBFGS config both arms; TRON cannot stream)."""

    HERE = os.path.join(os.path.dirname(__file__), "fixtures", "ratings")

    def _run(self, tmp_path, tag, extra):
        import json

        from photon_ml_tpu.cli.train_game import parse_args, run

        cfg = {
            "feature_shards": {
                "global": {"feature_bags": ["features"], "add_intercept": True},
                "per_user": {
                    "feature_bags": ["userFeatures"], "add_intercept": False,
                },
            },
            "coordinates": {
                "fixed": {
                    "type": "fixed",
                    "feature_shard": "global",
                    "optimizer": {
                        "optimizer": "LBFGS",
                        "regularization": "L2",
                        "regularization_weight": 10.0,
                    },
                },
                "per_user": {
                    "type": "random",
                    "feature_shard": "per_user",
                    "random_effect_type": "userId",
                    "optimizer": {
                        "regularization": "L2",
                        "regularization_weight": 1.0,
                    },
                },
            },
            "update_order": ["fixed", "per_user"],
        }
        cfg_path = tmp_path / f"game-{tag}.json"
        cfg_path.write_text(json.dumps(cfg))
        return run(parse_args([
            "--train-data-dirs", os.path.join(self.HERE, "train"),
            "--validation-data-dirs", os.path.join(self.HERE, "test"),
            "--coordinate-config", str(cfg_path),
            "--task", "LINEAR_REGRESSION",
            "--output-dir", str(tmp_path / f"out-{tag}"),
            "--evaluator", "RMSE",
            "--num-outer-iterations", "2",
            *extra,
        ]))

    def test_streamed_parity_and_zero_retraces(self, tmp_path):
        fit_mem = self._run(tmp_path, "mem", [])
        reset_stream_trace_counts()
        # explicit cache dir: the default would land next to the committed
        # fixture files; run 2 over identical inputs must hit it warm
        cache = ["--block-cache-dir", str(tmp_path / "blkcache")]
        fit_st = self._run(tmp_path, "st", [
            "--streaming", "--block-rows", "512", "--prefetch-depth", "2",
            *cache,
        ])
        traces1 = dict(stream_trace_counts())
        assert abs(fit_mem.validation_metric - fit_st.validation_metric) < 1e-3, (
            fit_mem.validation_metric, fit_st.validation_metric,
        )
        # every streamed program compiled exactly once over all blocks
        assert traces1 and all(v == 1 for v in traces1.values()), traces1
        # a second streamed run over the same shapes compiles nothing new,
        # and a cache-warm run lands on the identical metric
        fit_st2 = self._run(tmp_path, "st2", [
            "--streaming", "--block-rows", "512", "--prefetch-depth", "2",
            *cache,
        ])
        assert dict(stream_trace_counts()) == traces1
        assert fit_st2.validation_metric == pytest.approx(
            fit_st.validation_metric, abs=1e-6
        )
