"""Index-map tests: default map, off-heap PHIX store (native + pure-Python
readers over the same files), partitioning, and reverse lookup.

Mirrors reference DefaultIndexMapTest / PalDBIndexMapTest.
"""

import numpy as np
import pytest

from photon_ml_tpu.indexmap import (
    INTERCEPT_KEY,
    DefaultIndexMap,
    feature_key,
)
from photon_ml_tpu.indexmap import offheap
from photon_ml_tpu.indexmap.offheap import (
    OffHeapIndexMap,
    build_offheap_index_map,
    fnv1a_hashes,
    native_available,
)


class TestDefaultIndexMap:
    def test_from_names_deterministic(self):
        m = DefaultIndexMap.from_names(["b", "a", "b", "c"])
        assert len(m) == 3
        assert m.get_index("a") == 0  # sorted order
        assert m.get_index("b") == 1
        assert m.get_index("zzz") == -1
        assert m.get_feature_name(2) == "c"
        assert m.get_feature_name(99) is None

    def test_intercept(self):
        m = DefaultIndexMap.from_names(["x"], add_intercept=True)
        assert INTERCEPT_KEY in m

    def test_feature_key(self):
        assert feature_key("age") == "age"
        assert feature_key("age", "18-25") == "age\x0118-25"

    def test_vectorized_lookup(self):
        m = DefaultIndexMap.from_names(["a", "b"])
        np.testing.assert_array_equal(
            m.get_indices(["b", "missing", "a"]), [1, -1, 0]
        )

    def test_duplicate_indices_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            DefaultIndexMap({"a": 0, "b": 0})

    def test_content_digest_commits_to_assignment(self):
        """Same names, permuted indices -> different digest (the block
        cache relies on this to never serve blocks with wrong column
        ids); equal mappings digest equally, and the fast dict-backed
        override matches the generic dense-index walk byte-for-byte."""
        from photon_ml_tpu.indexmap import IndexMap

        m1 = DefaultIndexMap({"a": 0, "b": 1, "c": 2})
        m2 = DefaultIndexMap({"a": 0, "b": 1, "c": 2})
        perm = DefaultIndexMap({"a": 1, "b": 0, "c": 2})
        assert m1.content_digest() == m2.content_digest()
        assert m1.content_digest() != perm.content_digest()
        assert m1.content_digest() == IndexMap.content_digest(m1)


def _names(n=5000, seed=0):
    rng = np.random.default_rng(seed)
    return [
        feature_key(f"feat{i}", f"t{rng.integers(0, 10)}") for i in range(n)
    ]


class TestOffHeapIndexMap:
    @pytest.mark.parametrize("partitions", [1, 4])
    def test_build_and_lookup(self, tmp_path, partitions):
        names = _names()
        m = build_offheap_index_map(names, str(tmp_path / "im"), partitions)
        assert len(m) == len(set(names))
        # forward: every name maps to a unique in-range index
        idx = m.get_indices(sorted(set(names)))
        assert idx.min() == 0 and idx.max() == len(m) - 1
        assert len(np.unique(idx)) == len(m)
        assert m.get_index("missing-feature") == -1
        # reverse: round trip
        for probe in [0, 1, len(m) // 2, len(m) - 1]:
            name = m.get_feature_name(probe)
            assert name is not None
            assert m.get_index(name) == probe
        assert m.get_feature_name(len(m)) is None
        m.close()

    def test_python_reader_reads_native_files(self, tmp_path, monkeypatch):
        """Files are interchangeable between the C++ and Python paths."""
        names = _names(500)
        m = build_offheap_index_map(names, str(tmp_path / "im"), 2)
        expected = {n: m.get_index(n) for n in sorted(set(names))[:50]}
        m.close()
        # force the pure-Python reader on the same files
        monkeypatch.setattr(offheap, "_lib", None)
        monkeypatch.setattr(offheap, "_lib_failed", True)
        with OffHeapIndexMap(str(tmp_path / "im")) as m2:
            for n, i in expected.items():
                assert m2.get_index(n) == i
            name = m2.get_feature_name(3)
            assert name is not None and m2.get_index(name) == 3

    def test_python_writer_native_reader(self, tmp_path, monkeypatch):
        names = _names(300, seed=2)
        monkeypatch.setattr(offheap, "_lib", None)
        monkeypatch.setattr(offheap, "_lib_failed", True)
        m = build_offheap_index_map(names, str(tmp_path / "im"), 2)
        expected = {n: m.get_index(n) for n in sorted(set(names))[:50]}
        m.close()
        monkeypatch.setattr(offheap, "_lib", None)
        monkeypatch.setattr(offheap, "_lib_failed", False)
        if not native_available():
            pytest.skip("no g++ available")
        with OffHeapIndexMap(str(tmp_path / "im")) as m2:
            for n, i in expected.items():
                assert m2.get_index(n) == i

    def test_native_is_available_in_this_image(self):
        # the toolchain is baked into the image; catch silent fallback
        assert native_available()

    def test_content_digest_tracks_store_identity(self, tmp_path):
        """Off-heap digest is file-stat based (stores are immutable once
        built): stable across reopens of one store, different for a
        rebuilt store — spurious miss is the safe direction."""
        import os

        names = _names(300, seed=3)
        m = build_offheap_index_map(names, str(tmp_path / "im"), 2)
        d1 = m.content_digest()
        m.close()
        with OffHeapIndexMap(str(tmp_path / "im")) as m2:
            assert m2.content_digest() == d1
        # a rebuilt/touched store (even with identical bytes) digests anew
        part = str(tmp_path / "im" / offheap.PARTITION_FILE.format(i=0))
        st = os.stat(part)
        os.utime(part, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
        with OffHeapIndexMap(str(tmp_path / "im")) as m3:
            assert m3.content_digest() != d1

    def test_duplicate_keys_rejected(self, tmp_path):
        with pytest.raises((ValueError, OSError)):
            build_offheap_index_map.__wrapped__ if False else None
            offheap._build_partition(
                str(tmp_path / "p.bin"),
                [b"same", b"same"],
                np.array([0, 1], dtype=np.uint32),
            )

    def test_fnv_matches_reference_vectors(self):
        # FNV-1a 64 known vectors
        assert int(fnv1a_hashes([b""])[0]) == 0xCBF29CE484222325
        assert int(fnv1a_hashes([b"a"])[0]) == 0xAF63DC4C8601EC8C
        assert int(fnv1a_hashes([b"foobar"])[0]) == 0x85944171F73967E8
