"""Native columnar Avro reader vs the Python codec: exact agreement.

The C++ fast path (native/avrodecode.cpp) must be behaviorally invisible —
same GameData up to feature-index permutation, same errors — with the
Python record-at-a-time codec as the always-available fallback.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io import data_reader as dr
from photon_ml_tpu.io import native_reader as nr
from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    read_game_data,
    write_training_examples,
)


@pytest.fixture
def avro_dir(tmp_path, rng):
    recs = []
    for i in range(300):
        feats = [
            ("f", str(j), float(v))
            for j, v in zip(
                rng.choice(40, 4, replace=False), rng.standard_normal(4)
            )
        ]
        rec = {
            "uid": f"r{i}",
            "label": float(rng.integers(0, 2)),
            "features": feats,
            "userFeatures": [("u", "0", 1.0)],
            "metadataMap": {"userId": f"u{i % 7}"},
        }
        if i % 3 == 0:
            rec["weight"] = 2.0
        if i % 4 == 0:
            rec["offset"] = 0.5
        recs.append(rec)
    d = tmp_path / "data"
    d.mkdir()
    write_training_examples(str(d / "part-0.avro"), recs[:200])
    write_training_examples(str(d / "part-1.avro"), recs[200:])
    return str(d)


SHARDS = {
    "g": FeatureShardConfiguration(feature_bags=["features"], add_intercept=True),
    "u": FeatureShardConfiguration(
        feature_bags=["userFeatures"], add_intercept=False
    ),
}


def _densify(shard):
    m = np.zeros((int(shard.rows.max()) + 1, shard.dim), np.float32)
    np.add.at(m, (shard.rows, shard.cols), shard.vals)
    return m


class TestNativeReader:
    def test_native_path_is_taken(self, avro_dir):
        assert nr.native_available()
        got = dr._read_game_data_native(
            [avro_dir], SHARDS, None, ["userId"],
            "label", "offset", "weight", "uid", True,
        )
        assert got is not None

    def test_matches_python_codec(self, avro_dir, monkeypatch):
        native = read_game_data([avro_dir], SHARDS, id_tags=["userId"])
        monkeypatch.setattr(dr, "_read_game_data_native", lambda *a: None)
        python = read_game_data([avro_dir], SHARDS, id_tags=["userId"])

        dn, mn, un = native
        dp, mp, up = python
        np.testing.assert_array_equal(dn.labels, dp.labels)
        np.testing.assert_array_equal(dn.offsets, dp.offsets)
        np.testing.assert_array_equal(dn.weights, dp.weights)
        assert un == up
        np.testing.assert_array_equal(
            dn.id_tags["userId"], dp.id_tags["userId"]
        )
        for sid in SHARDS:
            # feature ids may be permuted between the paths; compare by name
            names_n = [mn[sid].get_feature_name(i) for i in range(len(mn[sid]))]
            names_p = [mp[sid].get_feature_name(i) for i in range(len(mp[sid]))]
            assert sorted(names_n) == sorted(names_p)
            dense_n = _densify(dn.feature_shards[sid])
            dense_p = _densify(dp.feature_shards[sid])
            perm = [names_n.index(k) for k in names_p]
            np.testing.assert_allclose(dense_n[:, perm], dense_p, atol=1e-6)

    def test_scoring_with_fixed_index_map(self, avro_dir):
        # train-style read builds the maps; scoring-style read reuses them
        # and must drop unmapped features identically on both paths
        _, maps, _ = read_game_data([avro_dir], SHARDS, id_tags=["userId"])
        native = read_game_data(
            [avro_dir], SHARDS, index_maps=maps, id_tags=["userId"]
        )
        assert native[0].feature_shards["g"].dim == len(maps["g"])

    def test_missing_tag_raises(self, avro_dir):
        with pytest.raises(ValueError, match="missing id tag"):
            read_game_data([avro_dir], SHARDS, id_tags=["itemId"])

    def test_missing_label_raises(self, tmp_path):
        # nullable-label schema (RESPONSE_PREDICTION-style input)
        from photon_ml_tpu.io.avro import write_avro_file

        schema = {
            "type": "record",
            "name": "ScoredExample",
            "fields": [
                {"name": "label", "type": ["null", "double"], "default": None},
                {
                    "name": "features",
                    "type": {
                        "type": "array",
                        "items": {
                            "type": "record",
                            "name": "FeatureAvro",
                            "fields": [
                                {"name": "name", "type": "string"},
                                {"name": "term", "type": "string"},
                                {"name": "value", "type": "double"},
                            ],
                        },
                    },
                },
            ],
        }
        path = str(tmp_path / "p.avro")
        write_avro_file(
            path, schema,
            [{"label": None,
              "features": [{"name": "f", "term": "1", "value": 1.0}]}],
        )
        with pytest.raises(ValueError, match="has no 'label'"):
            read_game_data([path], {"g": SHARDS["g"]})
        # and the same file reads fine when the response is optional
        data, _, _ = read_game_data(
            [path], {"g": SHARDS["g"]}, is_response_required=False
        )
        assert np.isnan(data.labels[0])

    def test_fallback_on_unsupported_schema(self, tmp_path, rng):
        # a record schema with a nested record field compiles to no program
        from photon_ml_tpu.io.avro import AvroSchema, write_avro_file

        schema = {
            "type": "record",
            "name": "Odd",
            "fields": [
                {"name": "label", "type": "double"},
                {
                    "name": "inner",
                    "type": {
                        "type": "record",
                        "name": "Inner",
                        "fields": [{"name": "x", "type": "double"}],
                    },
                },
                {
                    "name": "features",
                    "type": {
                        "type": "array",
                        "items": {
                            "type": "record",
                            "name": "FeatureAvro",
                            "fields": [
                                {"name": "name", "type": "string"},
                                {"name": "term", "type": "string"},
                                {"name": "value", "type": "double"},
                            ],
                        },
                    },
                },
            ],
        }
        path = str(tmp_path / "odd.avro")
        write_avro_file(
            path, schema,
            [{"label": 1.0, "inner": {"x": 2.0},
              "features": [{"name": "f", "term": "1", "value": 3.0}]}],
        )
        data, maps, _ = read_game_data([path], {"g": SHARDS["g"]})
        assert data.num_rows == 1  # python fallback handled it
        assert data.feature_shards["g"].vals.tolist().count(3.0) == 1

    def test_corrupt_record_count_no_crash(self, tmp_path):
        """A corrupted block record-count must surface as a fallback/skip,
        never a process abort (the decoder's never-UB contract)."""
        import photon_ml_tpu.io.native_reader as nrm
        from photon_ml_tpu.io.avro import AvroSchema, _Reader, _decode, MAGIC

        path = str(tmp_path / "c.avro")
        write_training_examples(
            path, [{"uid": "a", "label": 1.0, "features": [("f", "1", 2.0)]}]
        )
        with open(path, "rb") as f:
            raw = f.read()
        r = _Reader(raw)
        r.read(4)
        meta = _decode(r, {"type": "map", "values": "bytes"})
        root = AvroSchema(meta["avro.schema"].decode()).root
        plan = nr.compile_program(root, ["label"], [], ["features"])
        assert plan is not None
        # lie about the record count: the native decoder must reject, not die
        import ctypes

        lib = nrm._load_native()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        blob = b"\x00" * 4
        h = lib.avro_decode(
            ctypes.cast(ctypes.c_char_p(blob), u8p), len(blob), 1 << 55,
            np.ascontiguousarray(plan.program).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)
            ),
            len(plan.program) // 3, len(plan.num_fields), plan.n_str_cols,
            len(plan.bag_fields),
            ctypes.cast(ctypes.c_char_p(b""), u8p),
            np.zeros(0, np.int32).ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            0, plan.tag_col_base,
        )
        assert not h  # null handle, process alive
