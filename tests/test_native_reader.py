"""Native columnar Avro reader vs the Python codec: exact agreement.

The C++ fast path (native/avrodecode.cpp) must be behaviorally invisible —
same GameData up to feature-index permutation, same errors — with the
Python record-at-a-time codec as the always-available fallback.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io import data_reader as dr
from photon_ml_tpu.io import native_reader as nr
from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    read_game_data,
    write_training_examples,
)


@pytest.fixture
def avro_dir(tmp_path, rng):
    recs = []
    for i in range(300):
        feats = [
            ("f", str(j), float(v))
            for j, v in zip(
                rng.choice(40, 4, replace=False), rng.standard_normal(4)
            )
        ]
        rec = {
            "uid": f"r{i}",
            "label": float(rng.integers(0, 2)),
            "features": feats,
            "userFeatures": [("u", "0", 1.0)],
            "metadataMap": {"userId": f"u{i % 7}"},
        }
        if i % 3 == 0:
            rec["weight"] = 2.0
        if i % 4 == 0:
            rec["offset"] = 0.5
        recs.append(rec)
    d = tmp_path / "data"
    d.mkdir()
    write_training_examples(str(d / "part-0.avro"), recs[:200])
    write_training_examples(str(d / "part-1.avro"), recs[200:])
    return str(d)


SHARDS = {
    "g": FeatureShardConfiguration(feature_bags=["features"], add_intercept=True),
    "u": FeatureShardConfiguration(
        feature_bags=["userFeatures"], add_intercept=False
    ),
}


def _densify(shard):
    m = np.zeros((int(shard.rows.max()) + 1, shard.dim), np.float32)
    np.add.at(m, (shard.rows, shard.cols), shard.vals)
    return m


class TestNativeReader:
    def test_native_path_is_taken(self, avro_dir):
        assert nr.native_available()
        got = dr._read_game_data_native(
            [avro_dir], SHARDS, None, ["userId"],
            "label", "offset", "weight", "uid", True,
        )
        assert got is not None

    def test_matches_python_codec(self, avro_dir, monkeypatch):
        native = read_game_data([avro_dir], SHARDS, id_tags=["userId"])
        monkeypatch.setattr(dr, "_read_game_data_native", lambda *a: None)
        python = read_game_data([avro_dir], SHARDS, id_tags=["userId"])

        dn, mn, un = native
        dp, mp, up = python
        np.testing.assert_array_equal(dn.labels, dp.labels)
        np.testing.assert_array_equal(dn.offsets, dp.offsets)
        np.testing.assert_array_equal(dn.weights, dp.weights)
        assert un == up
        np.testing.assert_array_equal(
            dn.id_tags["userId"], dp.id_tags["userId"]
        )
        for sid in SHARDS:
            # feature ids may be permuted between the paths; compare by name
            names_n = [mn[sid].get_feature_name(i) for i in range(len(mn[sid]))]
            names_p = [mp[sid].get_feature_name(i) for i in range(len(mp[sid]))]
            assert sorted(names_n) == sorted(names_p)
            dense_n = _densify(dn.feature_shards[sid])
            dense_p = _densify(dp.feature_shards[sid])
            perm = [names_n.index(k) for k in names_p]
            np.testing.assert_allclose(dense_n[:, perm], dense_p, atol=1e-6)

    def test_scoring_with_fixed_index_map(self, avro_dir):
        # train-style read builds the maps; scoring-style read reuses them
        # and must drop unmapped features identically on both paths
        _, maps, _ = read_game_data([avro_dir], SHARDS, id_tags=["userId"])
        native = read_game_data(
            [avro_dir], SHARDS, index_maps=maps, id_tags=["userId"]
        )
        assert native[0].feature_shards["g"].dim == len(maps["g"])

    def test_missing_tag_raises(self, avro_dir):
        with pytest.raises(ValueError, match="missing id tag"):
            read_game_data([avro_dir], SHARDS, id_tags=["itemId"])

    def test_missing_label_raises(self, tmp_path):
        # nullable-label schema (RESPONSE_PREDICTION-style input)
        from photon_ml_tpu.io.avro import write_avro_file

        schema = {
            "type": "record",
            "name": "ScoredExample",
            "fields": [
                {"name": "label", "type": ["null", "double"], "default": None},
                {
                    "name": "features",
                    "type": {
                        "type": "array",
                        "items": {
                            "type": "record",
                            "name": "FeatureAvro",
                            "fields": [
                                {"name": "name", "type": "string"},
                                {"name": "term", "type": "string"},
                                {"name": "value", "type": "double"},
                            ],
                        },
                    },
                },
            ],
        }
        path = str(tmp_path / "p.avro")
        write_avro_file(
            path, schema,
            [{"label": None,
              "features": [{"name": "f", "term": "1", "value": 1.0}]}],
        )
        with pytest.raises(ValueError, match="has no 'label'"):
            read_game_data([path], {"g": SHARDS["g"]})
        # and the same file reads fine when the response is optional
        data, _, _ = read_game_data(
            [path], {"g": SHARDS["g"]}, is_response_required=False
        )
        assert np.isnan(data.labels[0])

    def test_fallback_on_unsupported_schema(self, tmp_path, rng):
        # a record schema with a nested record field compiles to no program
        from photon_ml_tpu.io.avro import AvroSchema, write_avro_file

        schema = {
            "type": "record",
            "name": "Odd",
            "fields": [
                {"name": "label", "type": "double"},
                {
                    "name": "inner",
                    "type": {
                        "type": "record",
                        "name": "Inner",
                        "fields": [{"name": "x", "type": "double"}],
                    },
                },
                {
                    "name": "features",
                    "type": {
                        "type": "array",
                        "items": {
                            "type": "record",
                            "name": "FeatureAvro",
                            "fields": [
                                {"name": "name", "type": "string"},
                                {"name": "term", "type": "string"},
                                {"name": "value", "type": "double"},
                            ],
                        },
                    },
                },
            ],
        }
        path = str(tmp_path / "odd.avro")
        write_avro_file(
            path, schema,
            [{"label": 1.0, "inner": {"x": 2.0},
              "features": [{"name": "f", "term": "1", "value": 3.0}]}],
        )
        data, maps, _ = read_game_data([path], {"g": SHARDS["g"]})
        assert data.num_rows == 1  # python fallback handled it
        assert data.feature_shards["g"].vals.tolist().count(3.0) == 1

    def test_corrupt_record_count_no_crash(self, tmp_path):
        """A corrupted block record-count must surface as a fallback/skip,
        never a process abort (the decoder's never-UB contract)."""
        import photon_ml_tpu.io.native_reader as nrm
        from photon_ml_tpu.io.avro import AvroSchema, _Reader, _decode, MAGIC

        path = str(tmp_path / "c.avro")
        write_training_examples(
            path, [{"uid": "a", "label": 1.0, "features": [("f", "1", 2.0)]}]
        )
        with open(path, "rb") as f:
            raw = f.read()
        r = _Reader(raw)
        r.read(4)
        meta = _decode(r, {"type": "map", "values": "bytes"})
        root = AvroSchema(meta["avro.schema"].decode()).root
        plan = nr.compile_program(root, ["label"], [], ["features"])
        assert plan is not None
        # lie about the record count: the native decoder must reject, not die
        import ctypes

        lib = nrm._load_native()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        blob = b"\x00" * 4
        h = lib.avro_decode(
            ctypes.cast(ctypes.c_char_p(blob), u8p), len(blob), 1 << 55,
            np.ascontiguousarray(plan.program).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)
            ),
            len(plan.program) // 3, len(plan.num_fields), plan.n_str_cols,
            len(plan.bag_fields),
            ctypes.cast(ctypes.c_char_p(b""), u8p),
            np.zeros(0, np.int32).ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            0, plan.tag_col_base,
        )
        assert not h  # null handle, process alive


class TestChunkedDecode:
    """Container-block-granular decode: the unit of out-of-core streaming.

    ``read_columnar_file(block_start, block_count)`` must decompress only
    the selected container blocks and produce columns bitwise-identical to
    the matching row range of a whole-file read."""

    def _write_multiblock(self, tmp_path, rng, n=400):
        """One Avro file with MANY container blocks (tiny sync interval)."""
        from photon_ml_tpu.io import schemas as _schemas
        from photon_ml_tpu.io.avro import write_avro_file

        recs = [
            {
                "uid": f"r{i}",
                "label": float(rng.integers(0, 2)),
                "weight": 1.0 + (i % 3),
                "features": [
                    {"name": "f", "term": str(j), "value": float(v)}
                    for j, v in zip(
                        rng.choice(30, 3, replace=False),
                        rng.standard_normal(3),
                    )
                ],
                "metadataMap": {"userId": f"u{i % 5}"},
            }
            for i in range(n)
        ]
        path = str(tmp_path / "multiblock.avro")
        write_avro_file(
            path, _schemas.TRAINING_EXAMPLE, recs, sync_interval=1024
        )
        return path, recs

    def _plan(self, path):
        from photon_ml_tpu.io.avro import AvroSchema, MAGIC, _Reader, _decode

        with open(path, "rb") as f:
            raw = f.read()
        r = _Reader(raw)
        assert r.read(4) == MAGIC
        meta = _decode(r, {"type": "map", "values": "bytes"})
        root = AvroSchema(meta["avro.schema"].decode()).root
        plan = nr.compile_program(
            root, ["label", "weight", "offset"], ["uid"], ["features"],
            ["userId"],
        )
        assert plan is not None
        return plan, raw

    def test_container_block_counts_sum_to_rows(self, tmp_path, rng):
        path, recs = self._write_multiblock(tmp_path, rng)
        counts = nr.container_block_counts(path)
        assert len(counts) > 4  # the tiny sync interval made many blocks
        assert sum(counts) == len(recs)
        assert all(c > 0 for c in counts)

    def test_chunked_decode_bitwise_identical(self, tmp_path, rng):
        path, _ = self._write_multiblock(tmp_path, rng)
        plan, raw = self._plan(path)
        counts = nr.container_block_counts(path, data=raw)
        whole = nr.read_columnar_file(path, plan, data=raw)
        assert whole is not None

        def _bag_rows(cf, lo_row):
            rec, val, koff, klen = cf.bags["features"]
            return rec + lo_row, val, koff, klen

        row = 0
        for start in range(len(counts)):
            for count in (1, 2):
                part = nr.read_columnar_file(
                    path, plan, data=raw,
                    block_start=start, block_count=count,
                )
                assert part is not None
                lo, hi = row, row + sum(counts[start:start + count])
                assert part.n_rows == hi - lo
                for name in ("label", "weight"):
                    np.testing.assert_array_equal(
                        part.num[name], whole.num[name][lo:hi]
                    )
                    np.testing.assert_array_equal(
                        part.num_present[name],
                        whole.num_present[name][lo:hi],
                    )
                # bag streams: same per-row features, values bitwise equal
                prec, pval, pkoff, pklen = part.bags["features"]
                wrec, wval, wkoff, wklen = whole.bags["features"]
                sel = (wrec >= lo) & (wrec < hi)
                np.testing.assert_array_equal(prec + lo, wrec[sel])
                np.testing.assert_array_equal(pval, wval[sel])
                # feature KEYS resolve identically through each arena
                pkeys = [
                    part.key_arena[o:o + l]
                    for o, l in zip(pkoff, pklen)
                ]
                wkeys = [
                    whole.key_arena[o:o + l]
                    for o, l in zip(wkoff[sel], wklen[sel])
                ]
                assert pkeys == wkeys
                # string columns (uid + metadataMap tag)
                for col_of in ("strs", "tag_strs"):
                    pcols = getattr(part, col_of)
                    wcols = getattr(whole, col_of)
                    for name in pcols:
                        pa, po, pl = pcols[name]
                        wa, wo, wl = wcols[name]
                        got = [
                            pa[o:o + l] for o, l in zip(po, pl)
                        ]
                        want = [
                            wa[o:o + l]
                            for o, l in zip(wo[lo:hi], wl[lo:hi])
                        ]
                        assert got == want
            row += counts[start]

    def test_chunked_decode_tail_and_bounds(self, tmp_path, rng):
        path, recs = self._write_multiblock(tmp_path, rng)
        plan, raw = self._plan(path)
        counts = nr.container_block_counts(path, data=raw)
        # open-ended read from mid-file covers exactly the tail
        part = nr.read_columnar_file(path, plan, data=raw, block_start=2)
        assert part.n_rows == sum(counts[2:])
        # block_count past the end clamps
        part = nr.read_columnar_file(
            path, plan, data=raw, block_start=len(counts) - 1,
            block_count=99,
        )
        assert part.n_rows == counts[-1]
        # out-of-range start raises (not a silent empty read)
        with pytest.raises(ValueError, match="out of range"):
            nr.read_columnar_file(
                path, plan, data=raw, block_start=len(counts) + 1
            )

    def test_unsupported_codec_counts_raise(self, tmp_path):
        """container_block_counts must refuse (not mis-count) codecs the
        framing scan cannot see through."""
        path = str(tmp_path / "weird.avro")
        # hand-write a container header claiming an unsupported codec
        from photon_ml_tpu.io.avro import MAGIC, SYNC_SIZE, _encode

        with open(path, "wb") as f:
            f.write(MAGIC)
            _encode(
                f, {"type": "map", "values": "bytes"},
                {"avro.schema": b'"null"', "avro.codec": b"snappy"},
            )
            f.write(b"\x00" * SYNC_SIZE)
        with pytest.raises(ValueError, match="unsupported avro codec"):
            nr.container_block_counts(path)


class TestPackedDecodeParallelism:
    """The packed decode entry point (avro_decode_packed) runs inflate +
    columnar decode as ONE foreign call, so the GIL is released for the
    whole per-file decode window — the property that makes the streaming
    decode pool's threads genuinely overlap."""

    def _write_big(self, tmp_path, rng, name, n=12000):
        from photon_ml_tpu.io import schemas as _schemas
        from photon_ml_tpu.io.avro import write_avro_file

        recs = [
            {
                "uid": f"r{i}",
                "label": float(i % 2),
                "weight": 1.0,
                "features": [
                    {"name": "f", "term": str(j), "value": float(v)}
                    for j, v in zip(
                        rng.choice(64, 6, replace=False),
                        rng.standard_normal(6),
                    )
                ],
                "metadataMap": {"userId": f"u{i % 50}"},
            }
            for i in range(n)
        ]
        path = str(tmp_path / name)
        write_avro_file(path, _schemas.TRAINING_EXAMPLE, recs)
        return path

    def _packed_args(self, path, raw, plan, lib):
        import ctypes

        scanned = nr._scan_container_offsets(path, raw)
        assert scanned is not None
        data, offsets, lengths, counts, codec = scanned
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        offs_a = np.asarray(offsets, dtype=np.int64)
        lens_a = np.asarray(lengths, dtype=np.int64)
        cnts_a = np.asarray(counts, dtype=np.int64)
        prog = np.ascontiguousarray(plan.program)
        tag_names = sorted(plan.tags, key=plan.tags.get)
        tag_bytes = b"".join(t.encode() for t in tag_names)
        tag_lens = np.asarray([len(t) for t in tag_names], dtype=np.int32)
        # keep every array alive via the returned closure's cell refs
        def call():
            return lib.avro_decode_packed(
                ctypes.cast(ctypes.c_char_p(data), u8p), len(data),
                offs_a.ctypes.data_as(i64p), lens_a.ctypes.data_as(i64p),
                cnts_a.ctypes.data_as(i64p), len(offsets),
                1 if codec == "deflate" else 0,
                prog.ctypes.data_as(i32p), len(plan.program) // 3,
                len(plan.num_fields), plan.n_str_cols, len(plan.bag_fields),
                ctypes.cast(ctypes.c_char_p(tag_bytes), u8p),
                tag_lens.ctypes.data_as(i32p), len(tag_names),
                plan.tag_col_base,
            )
        return call

    def test_packed_decode_releases_gil(self, tmp_path, rng):
        """Background-counter probe: a pure-Python thread makes progress
        DURING the native call iff the call dropped the GIL. Valid on any
        CPU count (on one core the OS preempts between the two threads
        only when the native thread isn't holding the lock)."""
        import sys
        import threading

        lib = nr._load_native()
        if lib is None or not getattr(lib, "has_packed", False):
            pytest.skip("native packed decoder unavailable")
        path = self._write_big(tmp_path, rng, "gilprobe.avro")
        with open(path, "rb") as f:
            raw = f.read()
        plan, _ = TestChunkedDecode._plan(TestChunkedDecode(), path)
        call = self._packed_args(path, raw, plan, lib)

        ticks = [0]
        stop = threading.Event()

        def counter():
            while not stop.is_set():
                ticks[0] += 1

        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(0.0005)
        t = threading.Thread(target=counter, daemon=True)
        t.start()
        try:
            # only the foreign call runs between the two snapshots, so any
            # counter progress happened while native code was executing
            progressed = 0
            for _ in range(4):
                before = ticks[0]
                handle = call()
                progressed += ticks[0] - before
                assert handle
                lib.res_free(handle)
        finally:
            stop.set()
            t.join(timeout=2.0)
            sys.setswitchinterval(old_interval)
        assert progressed > 0, "GIL held across avro_decode_packed"

    def test_two_thread_decode_overlap(self, tmp_path, rng):
        """Two files decoding on two threads must beat decoding them
        sequentially — the microbenchmark form of 'the pool is real'.
        Needs >= 2 cores to show wall-clock overlap."""
        import os as _os
        import threading
        import time as _time

        if (_os.cpu_count() or 1) < 2:
            pytest.skip("wall-clock overlap needs >= 2 cpus")
        lib = nr._load_native()
        if lib is None or not getattr(lib, "has_packed", False):
            pytest.skip("native packed decoder unavailable")
        calls = []
        for name in ("ovl-a.avro", "ovl-b.avro"):
            path = self._write_big(tmp_path, rng, name)
            with open(path, "rb") as f:
                raw = f.read()
            plan, _ = TestChunkedDecode._plan(TestChunkedDecode(), path)
            calls.append(self._packed_args(path, raw, plan, lib))

        def run(call, reps=3):
            for _ in range(reps):
                h = call()
                assert h
                lib.res_free(h)

        t0 = _time.perf_counter()
        for c in calls:
            run(c)
        seq = _time.perf_counter() - t0

        threads = [threading.Thread(target=run, args=(c,)) for c in calls]
        t0 = _time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        par = _time.perf_counter() - t0
        # generous bound: true serialization would give par ~= seq
        assert par < 0.85 * seq, f"no decode overlap: par={par:.3f} seq={seq:.3f}"
