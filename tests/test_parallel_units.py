"""Unit tests for the parallel plane's bottom layers (parallel/mesh.py,
parallel/multihost.py): mesh construction, batch padding + shard
placement, and the single-process degenerate paths of the multi-host
runtime seams. conftest forces 8 host-platform devices, so placement is
exercised on a real multi-device mesh without any cluster.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    add_fetch_observer,
    data_parallel_mesh,
    fetch_global,
    pad_batch_to_multiple,
    place,
    remove_fetch_observer,
    replicate,
    shard_batch,
    shard_map,
)
from photon_ml_tpu.parallel.multihost import (
    barrier,
    global_batch_from_host_rows,
    host_shard_files,
    initialize_distributed,
)


def _dense_batch(n=10, d=4, seed=3):
    rng = np.random.default_rng(seed)
    return LabeledData(
        features=DenseFeatures(
            matrix=jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        ),
        labels=jnp.asarray(rng.integers(0, 2, n).astype(np.float32)),
        offsets=jnp.zeros(n, jnp.float32),
        weights=jnp.ones(n, jnp.float32),
    )


# ===================================================================== mesh


class TestMeshConstruction:
    def test_default_mesh_spans_all_devices(self):
        mesh = data_parallel_mesh()
        assert mesh.axis_names == (DATA_AXIS,)
        assert mesh.shape[DATA_AXIS] == len(jax.devices())

    def test_num_devices_takes_a_prefix(self):
        mesh = data_parallel_mesh(num_devices=4)
        assert mesh.shape[DATA_AXIS] == 4
        assert list(mesh.devices.flat) == jax.devices()[:4]

    def test_single_device_mesh_is_valid(self):
        mesh = data_parallel_mesh(num_devices=1)
        assert mesh.shape[DATA_AXIS] == 1

    def test_shard_map_psum_is_global_sum(self):
        mesh = data_parallel_mesh(num_devices=4)
        x = jnp.arange(8, dtype=jnp.float32)
        xs = place(x, mesh, P(DATA_AXIS))

        def local_sum(block):
            return jax.lax.psum(jnp.sum(block), DATA_AXIS)

        got = shard_map(
            local_sum, mesh, in_specs=P(DATA_AXIS), out_specs=P()
        )(xs)
        assert float(got) == float(x.sum())


# ================================================================== padding


class TestPadBatch:
    def test_divisible_batch_is_untouched(self):
        data = _dense_batch(n=8)
        assert pad_batch_to_multiple(data, 4) is data

    def test_padding_rows_are_algebraic_noops(self):
        data = _dense_batch(n=10)
        padded = pad_batch_to_multiple(data, 4)
        assert padded.num_rows == 12
        np.testing.assert_array_equal(padded.weights[10:], 0.0)
        np.testing.assert_array_equal(padded.labels[10:], 0.0)
        np.testing.assert_array_equal(
            np.asarray(padded.features.matrix[10:]), 0.0
        )
        # the real rows are untouched
        np.testing.assert_array_equal(
            np.asarray(padded.features.matrix[:10]),
            np.asarray(data.features.matrix),
        )

    def test_ell_features_pad_values_and_indices(self):
        n, k = 6, 3
        data = LabeledData(
            features=EllFeatures(
                values=jnp.ones((n, k), jnp.float32),
                indices=jnp.zeros((n, k), jnp.int32),
                num_cols=5,
            ),
            labels=jnp.ones(n, jnp.float32),
            offsets=jnp.zeros(n, jnp.float32),
            weights=jnp.ones(n, jnp.float32),
        )
        padded = pad_batch_to_multiple(data, 4)
        assert padded.features.values.shape == (8, k)
        assert padded.features.indices.shape == (8, k)
        assert padded.features.num_cols == 5
        np.testing.assert_array_equal(
            np.asarray(padded.features.values[6:]), 0.0
        )


# ================================================================ placement


class TestPlacement:
    def test_place_rows_shards_over_data_axis(self):
        mesh = data_parallel_mesh(num_devices=4)
        x = np.arange(12, dtype=np.float32)
        placed = place(x, mesh, P(DATA_AXIS))
        assert placed.sharding.is_equivalent_to(
            NamedSharding(mesh, P(DATA_AXIS)), placed.ndim
        )
        # 3 rows per device
        assert {s.data.shape for s in placed.addressable_shards} == {(3,)}
        np.testing.assert_array_equal(np.asarray(placed), x)

    def test_replicate_puts_full_copy_on_every_device(self):
        mesh = data_parallel_mesh(num_devices=4)
        tree = {"w": np.arange(5, dtype=np.float32)}
        rep = replicate(tree, mesh)
        assert {s.data.shape for s in rep["w"].addressable_shards} == {(5,)}

    def test_shard_batch_pads_then_places(self):
        mesh = data_parallel_mesh(num_devices=4)
        data = _dense_batch(n=10, d=4)
        sharded = shard_batch(data, mesh)
        assert sharded.num_rows == 12
        assert sharded.labels.sharding.is_equivalent_to(
            NamedSharding(mesh, P(DATA_AXIS)), 1
        )
        assert sharded.features.matrix.sharding.is_equivalent_to(
            NamedSharding(mesh, P(DATA_AXIS, None)), 2
        )
        # weights of the pad rows stay exact zeros after placement
        np.testing.assert_array_equal(
            np.asarray(sharded.weights)[10:], 0.0
        )


# ============================================================== fetch_global


class TestFetchGlobal:
    def test_numpy_passthrough(self):
        x = np.arange(4.0)
        np.testing.assert_array_equal(fetch_global(x), x)

    def test_sharded_array_roundtrips(self):
        mesh = data_parallel_mesh(num_devices=4)
        x = np.arange(8, dtype=np.float32)
        placed = place(x, mesh, P(DATA_AXIS))
        np.testing.assert_array_equal(fetch_global(placed), x)

    def test_observer_sees_device_fetches_only(self):
        seen = []
        add_fetch_observer(seen.append)
        try:
            fetch_global(np.zeros(4))  # host input: not a device fetch
            assert seen == []
            fetch_global(jnp.zeros(4, jnp.float32))
            assert seen == [16]
        finally:
            remove_fetch_observer(seen.append)


# ======================================================== multihost seams


class TestMultihostDegeneratePaths:
    """Single-process: every seam must degrade to the identity (the
    multi-process branches are exercised by tests/test_multiprocess.py)."""

    def test_host_shard_files_returns_all_sorted(self):
        files = ["b.avro", "a.avro", "c.avro"]
        assert host_shard_files(files) == sorted(files)

    def test_barrier_is_noop(self):
        barrier("unit-test")  # must simply return

    def test_global_batch_is_plain_device_put(self):
        mesh = data_parallel_mesh(num_devices=4)
        rows = np.arange(8, dtype=np.float32).reshape(4, 2)
        got = global_batch_from_host_rows(rows, mesh, P(DATA_AXIS, None))
        assert got.sharding.is_equivalent_to(
            NamedSharding(mesh, P(DATA_AXIS, None)), 2
        )
        np.testing.assert_array_equal(np.asarray(got), rows)

    def test_initialize_after_backend_up_is_false(self):
        # the test process has long since initialized its CPU backend:
        # auto-detect init must degrade to single-process, not raise
        assert initialize_distributed() is False

    def test_explicit_cluster_request_after_backend_up_raises(self):
        with pytest.raises(RuntimeError, match="before any JAX call"):
            initialize_distributed(coordinator_address="127.0.0.1:1234")
