"""Generate the golden movie-ratings fixture (run once; outputs committed).

The reference ships a Yahoo! Music ratings fixture
(photon-client/src/integTest/resources/GameIntegTest/input/train/
yahoo-music-train.avro) and asserts captured RMSE thresholds against it
(DriverTest.scala:84-98 et al.). This is the equivalent: a deterministic
synthetic ratings problem with global (genre), per-user, and per-movie
structure, written as TrainingExampleAvro.

    python tests/fixtures/make_ratings_fixture.py

Regenerating changes nothing (seeded); thresholds live in
tests/test_golden_fixture.py.
"""

import os

import numpy as np

N_USERS = 40
N_MOVIES = 60
N_GENRES = 8
RATINGS_PER_USER = 30
NOISE = 0.3
SEED = 20260729


def generate(seed=SEED):
    rng = np.random.default_rng(seed)
    genre_w = rng.normal(size=N_GENRES) * 0.8              # global taste
    movie_genres = rng.dirichlet(np.ones(N_GENRES) * 0.5, size=N_MOVIES)
    movie_bias = rng.normal(size=N_MOVIES) * 0.6
    user_bias = rng.normal(size=N_USERS) * 0.5
    user_genre_w = rng.normal(size=(N_USERS, N_GENRES)) * 0.7  # per-user taste

    records = []
    for u in range(N_USERS):
        movies = rng.choice(N_MOVIES, size=RATINGS_PER_USER, replace=False)
        for m in movies:
            x = movie_genres[m]
            rating = (
                3.0
                + x @ genre_w
                + x @ user_genre_w[u]
                + movie_bias[m]
                + user_bias[u]
                + NOISE * rng.normal()
            )
            records.append(
                {
                    "uid": f"u{u:03d}-m{m:03d}",
                    "label": float(rating),
                    "features": [
                        ("genre", str(g), float(x[g]))
                        for g in range(N_GENRES)
                        if x[g] > 1e-6
                    ],
                    "userFeatures": [
                        ("genre", str(g), float(x[g]))
                        for g in range(N_GENRES)
                        if x[g] > 1e-6
                    ] + [("userBias", "", 1.0)],
                    "movieFeatures": [("movieBias", "", 1.0)],
                    "metadataMap": {"userId": f"u{u:03d}", "movieId": f"m{m:03d}"},
                }
            )
    rng.shuffle(records)
    return records


def main():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from photon_ml_tpu.io.data_reader import write_training_examples

    here = os.path.dirname(os.path.abspath(__file__))
    records = generate()
    n_train = int(0.8 * len(records))
    train_dir = os.path.join(here, "ratings", "train")
    test_dir = os.path.join(here, "ratings", "test")
    os.makedirs(train_dir, exist_ok=True)
    os.makedirs(test_dir, exist_ok=True)
    write_training_examples(
        os.path.join(train_dir, "part-00000.avro"), records[:n_train]
    )
    write_training_examples(
        os.path.join(test_dir, "part-00000.avro"), records[n_train:]
    )
    print(f"wrote {n_train} train / {len(records) - n_train} test records")


if __name__ == "__main__":
    main()
