"""Telemetry subsystem tests: span tracer, metrics registry, sinks and
validators (unit, fast lane), plus the driver-level smoke gate — tiny CPU
train/score/serve/update runs with --telemetry-out/--trace-out whose ledger
and Chrome trace are schema-validated (slow lane; CI runs this file whole
as the telemetry smoke gate)."""

import json
import threading

import numpy as np
import pytest

from photon_ml_tpu.telemetry import (
    MetricsRegistry,
    RunLedger,
    TelemetryEventListener,
    chrome_trace_events,
    format_summary_table,
    get_registry,
    get_tracer,
    jit_trace_counts,
    span_tree_summary,
    validate_chrome_trace,
    validate_ledger,
    write_chrome_trace,
)
from photon_ml_tpu.telemetry.span import (
    NOOP_SPAN,
    disable_tracing,
    enable_tracing,
    span,
    timed_span,
)


@pytest.fixture()
def tracer():
    """Enabled global tracer, wall-clock only; always disabled afterwards."""
    t = enable_tracing(device_sync=False, clear=True)
    get_registry().reset()
    yield t
    disable_tracing()


class TestSpans:
    def test_disabled_returns_noop_singleton(self):
        disable_tracing()
        s = span("anything", key=1)
        assert s is NOOP_SPAN
        with s:
            pass  # no-op context manager works and records nothing
        assert s.set_attrs(more=2) is s

    def test_nesting_parent_path_depth(self, tracer):
        with span("outer", a=1):
            with span("inner"):
                pass
        recs = {r.name: r for r in tracer.spans()}
        assert recs["inner"].parent_id == recs["outer"].span_id
        assert recs["inner"].path == "outer/inner"
        assert recs["inner"].depth == 2
        assert recs["outer"].parent_id is None
        assert recs["outer"].depth == 1
        assert recs["outer"].attrs == {"a": 1}
        assert recs["outer"].duration_s >= recs["inner"].duration_s >= 0

    def test_exception_tagged_not_swallowed(self, tracer):
        with pytest.raises(KeyError):
            with span("boom"):
                raise KeyError("x")
        (rec,) = tracer.spans()
        assert rec.failed and rec.error == "KeyError"

    def test_threads_nest_independently(self, tracer):
        def worker(i):
            with span(f"w{i}"):
                with span("child"):
                    pass

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        with span("main_parent"):
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        children = [r for r in tracer.spans() if r.name == "child"]
        assert len(children) == 4
        # thread spans chain to their own thread's root, never to the main
        # thread's open span (contextvars do not leak across threads)
        by_id = {r.span_id: r for r in tracer.spans()}
        for c in children:
            assert by_id[c.parent_id].name.startswith("w")

    def test_set_attrs_during_block(self, tracer):
        with span("s") as s:
            s.set_attrs(rows=10)
        (rec,) = tracer.spans()
        assert rec.attrs == {"rows": 10}

    def test_timed_span_measures_when_disabled(self):
        disable_tracing()
        sp = timed_span("phase")
        with sp:
            pass
        assert sp.duration_s >= 0.0 and not sp.failed
        assert len(get_tracer().spans()) == 0 or all(
            r.name != "phase" for r in get_tracer().spans()
        )


class TestTimerShims:
    def test_timer_accumulates_and_counts_failures(self):
        from photon_ml_tpu.utils.timer import Timer

        disable_tracing()
        timer = Timer()
        with timer.time("ok"):
            pass
        with timer.time("ok"):
            pass
        with pytest.raises(ValueError):
            with timer.time("bad"):
                raise ValueError("x")
        assert timer.durations["ok"] >= 0.0
        assert "bad" in timer.durations  # failed phases still accumulate
        assert timer.failures == {"bad": 1}
        assert timer.failed("bad") and not timer.failed("ok")

    def test_timer_thread_safe(self):
        from photon_ml_tpu.utils.timer import Timer

        timer = Timer()

        def work():
            for _ in range(50):
                with timer.time("p"):
                    pass

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert timer.durations["p"] >= 0.0 and not timer.failures

    def test_timed_lands_as_span_when_tracing(self, tracer):
        from photon_ml_tpu.utils.timer import Timed

        with Timed("load model"):
            pass
        assert [r.name for r in tracer.spans()] == ["load model"]


class TestMetricsRegistry:
    def test_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.count("c")
        reg.count("c", 4)
        reg.gauge("g", 2.0)
        reg.gauge("g", 1.0)  # peak stays at 2
        for v in range(100):
            reg.observe("h", float(v))
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 5
        assert snap["gauges"]["g"] == {"last": 1.0, "peak": 2.0}
        h = snap["histograms"]["h"]
        assert h["count"] == 100 and h["max"] == 99.0
        assert 40 <= h["p50"] <= 60
        json.dumps(snap)  # snapshot must be plain JSON
        reg.reset()
        assert reg.snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}
        }

    def test_absorbers_duck_typed(self):
        class Stats:
            num_entities = 7
            rounds = 2
            executed_lane_iterations = 30
            lockstep_lane_iterations = 90
            chunk_retraces = 1
            iterations_p99 = 12.0
            converged = False

        class Transfers:
            row_transfers_h2d = 3
            row_transfers_d2h = 1
            row_bytes_h2d = 300
            row_bytes_d2h = 100
            host_score_sums = 0
            device_plane_updates = 6
            coordinate_updates = 6
            outer_iterations = 2

        reg = MetricsRegistry()
        reg.record_solver_stats(Stats(), coordinate="per_user")
        reg.record_transfer_stats(Transfers())
        reg.record_serving_snapshot({"latency_p99_ms": 4.5, "caches": {}})
        snap = reg.snapshot()
        assert snap["counters"]["solver.per_user.entities"] == 7
        assert snap["counters"]["solver.per_user.unconverged_buckets"] == 1
        assert snap["counters"]["transfer.row_bytes_h2d"] == 300
        assert snap["gauges"]["serving.latency_p99_ms"]["last"] == 4.5
        assert "serving.caches" not in snap["gauges"]  # non-numeric skipped

    def test_note_jit_trace_counts_retraces_only(self):
        import jax

        reg = get_registry()
        reg.reset()
        from photon_ml_tpu.telemetry import note_jit_trace

        @jax.jit
        def f(x):
            note_jit_trace("test_prog", "unit")
            return x + 1

        f(np.float32(1.0))
        f(np.float32(2.0))  # cache hit: no retrace, no count
        assert jit_trace_counts()["test_prog/unit"] == 1
        f(np.ones((2,), np.float32))  # new shape → retrace
        assert jit_trace_counts()["test_prog/unit"] == 2
        assert reg.counter_value("jit.traces") == 2


class TestSinksAndValidators:
    def test_ledger_round_trip(self, tmp_path, tracer):
        with span("a"):
            with span("b"):
                pass
        path = tmp_path / "sub" / "ledger.jsonl"  # parent dir auto-created
        ledger = RunLedger(str(path))
        ledger.write("meta", phase="start", label="t")
        for rec in tracer.spans():
            ledger.write_span(rec, tracer.origin_unix)
        ledger.write("metrics", snapshot=get_registry().snapshot())
        ledger.write("meta", phase="finish", label="t")
        ledger.close()
        records = validate_ledger(str(path))
        assert [r["type"] for r in records] == [
            "meta", "span", "span", "metrics", "meta"
        ]
        spans = [r for r in records if r["type"] == "span"]
        assert {s["path"] for s in spans} == {"a", "a/b"}
        assert all(not s["failed"] for s in spans)

    def test_ledger_validator_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('{"type": "span", "ts": 1.0}\n')  # missing span fields
        with pytest.raises(ValueError, match="span"):
            validate_ledger(str(p))
        p.write_text("not json\n")
        with pytest.raises(ValueError, match="invalid JSON"):
            validate_ledger(str(p))
        p.write_text("")
        with pytest.raises(ValueError, match="empty"):
            validate_ledger(str(p))

    def test_chrome_trace_round_trip(self, tmp_path, tracer):
        with span("cd/run", plane="device"):
            with pytest.raises(RuntimeError):
                with span("cd/outer_iter"):
                    raise RuntimeError("x")
        out = tmp_path / "trace.json"
        n = write_chrome_trace(str(out), tracer.spans(), metadata={"k": 1})
        assert n == 2
        doc = validate_chrome_trace(str(out))
        events = {e["name"]: e for e in doc["traceEvents"]}
        assert events["cd/run"]["cat"] == "cd"
        assert events["cd/outer_iter"]["args"]["error"] == "RuntimeError"
        assert all(e["ph"] == "X" for e in doc["traceEvents"])

    def test_chrome_trace_validator_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"traceEvents": [{"name": "x"}]}))
        with pytest.raises(ValueError):
            validate_chrome_trace(str(p))
        p.write_text(json.dumps({"nope": []}))
        with pytest.raises(ValueError):
            validate_chrome_trace(str(p))

    def test_span_tree_summary_depth_filter(self, tracer):
        with span("cd/run"):  # slash in the NAME is not extra depth
            with span("cd/outer_iter"):
                with span("cd/coordinate"):
                    pass
        full = span_tree_summary(tracer.spans())
        assert set(full) == {
            "cd/run", "cd/run/cd/outer_iter",
            "cd/run/cd/outer_iter/cd/coordinate",
        }
        top2 = span_tree_summary(tracer.spans(), max_depth=2)
        assert set(top2) == {"cd/run", "cd/run/cd/outer_iter"}
        assert top2["cd/run"]["count"] == 1

    def test_format_summary_table(self, tracer):
        with span("fit"):
            pass
        get_registry().count("jit.traces.prog", 3)
        table = format_summary_table(
            tracer.spans(), get_registry().snapshot(), "unit"
        )
        assert "fit" in table and "prog" in table


class TestEventBridge:
    def test_events_land_in_ledger_and_registry(self, tmp_path):
        from photon_ml_tpu.event import (
            EventEmitter,
            ModelSwapEvent,
            ScoringFinishEvent,
            SolverStatsEvent,
            TrainingStartEvent,
        )

        reg = MetricsRegistry()
        ledger = RunLedger(str(tmp_path / "ledger.jsonl"))
        emitter = EventEmitter()
        emitter.register_listener(
            TelemetryEventListener(ledger=ledger, registry=reg)
        )
        emitter.send_event(TrainingStartEvent(task="LOGISTIC_REGRESSION"))
        emitter.send_event(SolverStatsEvent(
            coordinate_id="per_user", bucket=0, optimizer="lbfgs",
            num_entities=4, rounds=1, dispatch_widths=(4,),
            iterations_p50=3.0, iterations_p99=5.0,
            executed_lane_iterations=12, lockstep_lane_iterations=20,
            wasted_lane_fraction=0.4,
        ))
        emitter.send_event(ScoringFinishEvent(
            model_id="m", num_requests=10, wall_seconds=0.5,
            metrics={"latency_p99_ms": 3.0},
        ))
        emitter.send_event(ModelSwapEvent(
            model_id="m", generation=1, fingerprint=None,
            coordinates=("per_user",), rows_updated=5, blackout_s=0.01,
        ))
        emitter.clear_listeners()
        assert emitter.listener_errors == 0
        records = validate_ledger(str(ledger.path))
        events = [r["event"] for r in records if r["type"] == "event"]
        assert events == [
            "TrainingStartEvent", "SolverStatsEvent",
            "ScoringFinishEvent", "ModelSwapEvent",
        ]
        snap = reg.snapshot()
        assert snap["counters"]["events.TrainingStartEvent"] == 1
        assert snap["counters"]["solver.per_user.entities"] == 4
        assert snap["gauges"]["serving.latency_p99_ms"]["last"] == 3.0
        assert snap["counters"]["serving.swaps"] == 1

    def test_failing_listener_isolated_from_bridge(self, tmp_path):
        from photon_ml_tpu.event import EventEmitter, TrainingStartEvent
        from tests._listeners import CollectingListener, FailingListener

        CollectingListener.received = []
        FailingListener.raised = 0
        ledger = RunLedger(str(tmp_path / "ledger.jsonl"))
        emitter = EventEmitter()
        emitter.register_listener_class("tests._listeners.FailingListener")
        emitter.register_listener(
            TelemetryEventListener(ledger=ledger, registry=MetricsRegistry())
        )
        emitter.register_listener_class("tests._listeners.CollectingListener")
        emitter.send_event(TrainingStartEvent(task="T"))
        emitter.clear_listeners()
        # the failing listener raised on the event AND on close, yet both
        # other listeners saw everything
        assert FailingListener.raised == 1
        assert emitter.listener_errors == 2
        assert len(CollectingListener.received) == 1
        events = [
            r for r in validate_ledger(str(ledger.path))
            if r["type"] == "event"
        ]
        assert len(events) == 1

    def test_register_listener_class_error_paths(self):
        from photon_ml_tpu.event import EventEmitter

        emitter = EventEmitter()
        with pytest.raises(ValueError, match="dotted"):
            emitter.register_listener_class("NoDots")
        with pytest.raises(ValueError, match="failed to import"):
            emitter.register_listener_class("no.such.module.Listener")
        with pytest.raises(ValueError, match="no attribute"):
            emitter.register_listener_class("tests._listeners.Missing")
        with pytest.raises(ValueError, match="not an instantiable"):
            emitter.register_listener_class("tests._listeners.NOT_A_LISTENER")
        assert emitter._listeners == []


# ---------------------------------------------------------------------------
# Driver smoke gate: tiny CPU end-to-end runs through the real CLIs with
# telemetry on. CI runs this whole file as the telemetry gate.
# ---------------------------------------------------------------------------


class TestRegistryLifecycle:
    """Two telemetry sessions in one process must not bleed into each
    other, and --auto-tune's fresh-registry trials must not pollute the
    process-global registry (the isolation contract autotune.py documents)."""

    def test_two_start_run_sessions_isolated(self, tmp_path):
        from photon_ml_tpu.telemetry import note_jit_trace, start_run
        from photon_ml_tpu.telemetry.span import disable_tracing, span

        get_registry().reset()
        first = tmp_path / "first.jsonl"
        run1 = start_run("one", ledger_path=str(first), device_sync=False)
        try:
            with span("cd/first"):
                note_jit_trace("prog_a")
            run1.finish()
        finally:
            disable_tracing()
        assert jit_trace_counts() == {"prog_a": 1}

        # session 2 starts from a reset registry; start_run(clear=True)
        # already drops session 1's spans
        get_registry().reset()
        assert jit_trace_counts() == {}
        second = tmp_path / "second.jsonl"
        run2 = start_run("two", ledger_path=str(second), device_sync=False)
        try:
            with span("re/second"):
                note_jit_trace("prog_b", kind="fwd")
            run2.finish()
        finally:
            disable_tracing()
        assert jit_trace_counts() == {"prog_b/fwd": 1}

        records2 = validate_ledger(str(second))
        names2 = {r["name"] for r in records2 if r["type"] == "span"}
        assert names2 == {"re/second"}  # session 1's span did not carry over
        (metrics2,) = [r for r in records2 if r["type"] == "metrics"]
        counters2 = metrics2["snapshot"]["counters"]
        assert "jit.traces.prog_b/fwd" in counters2
        assert "jit.traces.prog_a" not in counters2  # no cross-session leak
        # session 1's ledger is intact and still its own
        records1 = validate_ledger(str(first))
        assert {r["name"] for r in records1 if r["type"] == "span"} == {
            "cd/first"
        }

    def test_fresh_trial_registry_cannot_leak(self):
        get_registry().reset()
        trial_a = MetricsRegistry()
        trial_a.count("serving.compile_count", 5)
        trial_b = MetricsRegistry()
        # trial A's counters are invisible to trial B AND to the global
        assert trial_b.counter_value("serving.compile_count") == 0.0
        assert get_registry().counter_value("serving.compile_count") == 0.0
        trial_b.gauge("judge", 1.0)
        assert "judge" not in trial_a.snapshot()["gauges"]

    def test_checkpoint_leaves_analyzable_prefix(self, tmp_path):
        """RunLedger.flush() via TelemetryRun.checkpoint(): the ledger is a
        valid prefix BEFORE finish, and finish does not re-write the
        checkpointed spans."""
        from photon_ml_tpu.telemetry import start_run
        from photon_ml_tpu.telemetry.span import disable_tracing, span

        get_registry().reset()
        path = tmp_path / "ledger.jsonl"
        run = start_run("ckpt", ledger_path=str(path), device_sync=False)
        try:
            with span("cd/outer_iter"):
                pass
            run.checkpoint("iter-0")
            mid = validate_ledger(str(path))  # readable pre-finish
            assert [r["name"] for r in mid if r["type"] == "span"] == [
                "cd/outer_iter"
            ]
            assert any(
                r["type"] == "meta" and r.get("phase") == "checkpoint"
                for r in mid
            )
            with span("cd/coordinate"):
                pass
            run.finish()
        finally:
            disable_tracing()
        final = validate_ledger(str(path))
        spans = [r["name"] for r in final if r["type"] == "span"]
        assert spans == ["cd/outer_iter", "cd/coordinate"]  # no double write

    def test_truncated_tail_tolerated_with_warning(self, tmp_path):
        from photon_ml_tpu.telemetry import TruncatedLedgerWarning, start_run
        from photon_ml_tpu.telemetry.span import disable_tracing, span

        get_registry().reset()
        path = tmp_path / "crash.jsonl"
        run = start_run("crash", ledger_path=str(path), device_sync=False)
        try:
            with span("cd/run"):
                pass
            run.finish()
        finally:
            disable_tracing()
        with open(path, "a") as f:
            f.write('{"type": "span", "name": "killed mid-wr')  # no newline
        with pytest.warns(TruncatedLedgerWarning, match="partial record"):
            records = validate_ledger(str(path))
        assert [r["name"] for r in records if r["type"] == "span"] == [
            "cd/run"
        ]
        # strict mode still treats the same tail as corruption
        with pytest.raises(ValueError, match="invalid JSON"):
            validate_ledger(str(path), allow_truncated_tail=False)

    def test_mid_file_garbage_still_hard_error(self, tmp_path):
        path = tmp_path / "corrupt.jsonl"
        path.write_text(
            '{"type": "meta", "ts": 1.0, "phase": "start"}\n'
            "not json at all\n"
            '{"type": "meta", "ts": 2.0, "phase": "finish"}\n'
        )
        with pytest.raises(ValueError, match="invalid JSON"):
            validate_ledger(str(path))


@pytest.fixture(scope="module")
def tiny_avro(tmp_path_factory):
    """Tiny GLMix logistic fixture (8 users) + a config whose RE coordinate
    opts into the adaptive driver with min_lanes small enough to engage on
    8 entities, so re/adaptive_round spans appear in the gate."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    root = tmp_path_factory.mktemp("telemetry_glmix")
    rng = np.random.default_rng(3)
    n_users, rows, dg, du = 8, 12, 5, 3
    wg = rng.normal(size=dg)
    wu = {f"user{i}": rng.normal(size=du) for i in range(n_users)}

    def make(n_rows, seed):
        r = np.random.default_rng(seed)
        records = []
        for i in range(n_rows):
            user = f"user{i % n_users}"
            xg = r.normal(size=dg)
            xu = r.normal(size=du)
            z = xg @ wg + xu @ wu[user]
            y = 1.0 if 1 / (1 + np.exp(-z)) > r.random() else 0.0
            records.append({
                "uid": f"r{i}",
                "label": y,
                "features": [("g", str(j), xg[j]) for j in range(dg)],
                "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                "metadataMap": {"userId": user},
            })
        return records

    train_dir = root / "train"
    test_dir = root / "test"
    train_dir.mkdir()
    test_dir.mkdir()
    write_training_examples(
        str(train_dir / "part-00000.avro"), make(n_users * rows, 1)
    )
    write_training_examples(
        str(test_dir / "part-00000.avro"), make(n_users * 4, 2)
    )
    config = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {
                "feature_bags": ["userFeatures"], "add_intercept": False,
            },
        },
        "coordinates": {
            "fixed": {
                "type": "fixed",
                "feature_shard": "global",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 0.1,
                },
            },
            "per_user": {
                "type": "random",
                "feature_shard": "per_user",
                "random_effect_type": "userId",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 1.0,
                    "adaptive": {
                        "enabled": True, "chunk_iters": 4, "min_lanes": 2,
                    },
                },
            },
        },
        "update_order": ["fixed", "per_user"],
    }
    cfg_path = root / "game.json"
    cfg_path.write_text(json.dumps(config))
    return {"root": root, "train": train_dir, "test": test_dir,
            "config": cfg_path}


@pytest.mark.slow
class TestDriverTelemetrySmoke:
    @pytest.fixture(scope="class")
    def trained(self, tiny_avro, tmp_path_factory):
        """One traced train_game run shared by the downstream driver tests:
        model dir + validated ledger/trace paths."""
        from tests._listeners import CollectingListener

        from photon_ml_tpu.cli.train_game import parse_args, run

        CollectingListener.received = []
        out = tmp_path_factory.mktemp("telemetry_out")
        ledger_path = out / "train.jsonl"
        trace_path = out / "train-trace.json"
        run(parse_args([
            "--train-data-dirs", str(tiny_avro["train"]),
            "--validation-data-dirs", str(tiny_avro["test"]),
            "--coordinate-config", str(tiny_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out / "model"),
            "--evaluator", "AUC",
            "--event-listeners", "tests._listeners.CollectingListener",
            "--telemetry-out", str(ledger_path),
            "--trace-out", str(trace_path),
        ]))
        return {
            "out": out,
            "model": out / "model" / "best",
            "ledger": ledger_path,
            "trace": trace_path,
            "events": list(CollectingListener.received),
        }

    def test_train_ledger_and_trace_schemas(self, trained):
        records = validate_ledger(str(trained["ledger"]))
        doc = validate_chrome_trace(str(trained["trace"]))
        span_paths = {r["path"] for r in records if r["type"] == "span"}
        # spans from coordinate descent AND the adaptive RE driver
        assert any("cd/outer_iter" in p for p in span_paths)
        assert any("cd/coordinate" in p for p in span_paths)
        assert any("re/adaptive_round" in p for p in span_paths)
        assert any("re/solve_bucket" in p for p in span_paths)
        assert len(doc["traceEvents"]) > 0
        # every existing Event was bridged into the ledger
        event_names = [r["event"] for r in records if r["type"] == "event"]
        assert "PhotonSetupEvent" in event_names
        assert "TrainingStartEvent" in event_names
        assert "TrainingFinishEvent" in event_names
        assert "SolverStatsEvent" in event_names
        # zero listener errors, recorded in the finish meta record
        finish = [
            r for r in records
            if r["type"] == "meta" and r.get("phase") == "finish"
        ]
        assert len(finish) == 1 and finish[0]["listener_errors"] == 0
        assert finish[0]["num_spans"] == len(
            [r for r in records if r["type"] == "span"]
        )
        # the user listener rode along untouched
        assert len(trained["events"]) > 0

    def test_train_failing_listener_isolated(self, tiny_avro, tmp_path):
        """A listener that raises on every event must not fail the driver;
        the swallowed count lands in the ledger's finish record."""
        from tests._listeners import FailingListener

        from photon_ml_tpu.cli.train_game import parse_args, run

        FailingListener.raised = 0
        ledger_path = tmp_path / "ledger.jsonl"
        run(parse_args([
            "--train-data-dirs", str(tiny_avro["train"]),
            "--coordinate-config", str(tiny_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "model"),
            "--event-listeners", "tests._listeners.FailingListener",
            "--telemetry-out", str(ledger_path),
        ]))
        assert FailingListener.raised > 0
        records = validate_ledger(str(ledger_path))
        finish = [
            r for r in records
            if r["type"] == "meta" and r.get("phase") == "finish"
        ][0]
        assert finish["listener_errors"] > 0

    def test_train_bad_listener_fails_fast(self, tiny_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        with pytest.raises(ValueError, match="no attribute"):
            run(parse_args([
                "--train-data-dirs", str(tiny_avro["train"]),
                "--coordinate-config", str(tiny_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "model"),
                "--event-listeners", "tests._listeners.Missing",
            ]))

    def test_score_game_telemetry_and_listeners(self, trained, tiny_avro,
                                                tmp_path):
        from tests._listeners import CollectingListener

        from photon_ml_tpu.cli.score_game import parse_args, run

        CollectingListener.received = []
        ledger_path = tmp_path / "score.jsonl"
        trace_path = tmp_path / "score-trace.json"
        run(parse_args([
            "--data-dirs", str(tiny_avro["test"]),
            "--model-dir", str(trained["model"]),
            "--output-dir", str(tmp_path / "scores"),
            "--evaluator", "AUC",
            "--event-listeners", "tests._listeners.CollectingListener",
            "--telemetry-out", str(ledger_path),
            "--trace-out", str(trace_path),
        ]))
        records = validate_ledger(str(ledger_path))
        validate_chrome_trace(str(trace_path))
        event_names = [r["event"] for r in records if r["type"] == "event"]
        assert "ScoringStartEvent" in event_names
        assert "ScoringFinishEvent" in event_names
        names = {n for n in (type(e).__name__
                             for e in CollectingListener.received)}
        assert "ScoringFinishEvent" in names
        # Timer phases land as spans (score, save scores, ...)
        span_names = {r["name"] for r in records if r["type"] == "span"}
        assert "score" in span_names

    def test_serve_game_telemetry(self, trained, tiny_avro, tmp_path):
        from photon_ml_tpu.cli.serve_game import parse_args, run

        ledger_path = tmp_path / "serve.jsonl"
        trace_path = tmp_path / "serve-trace.json"
        run(parse_args([
            "--model-dir", str(trained["model"]),
            "--data-dirs", str(tiny_avro["test"]),
            "--max-requests", "16",
            "--bucket-sizes", "1,2,4",
            "--metrics-output", str(tmp_path / "metrics.json"),
            "--telemetry-out", str(ledger_path),
            "--trace-out", str(trace_path),
        ]))
        records = validate_ledger(str(ledger_path))
        validate_chrome_trace(str(trace_path))
        span_paths = {r["path"] for r in records if r["type"] == "span"}
        assert any("serve/replay" in p for p in span_paths)
        assert any("serve/score_batch" in p for p in span_paths)
        event_names = [r["event"] for r in records if r["type"] == "event"]
        assert "ScoringFinishEvent" in event_names
        # the bridged snapshot landed as serving.* gauges in the metrics
        # record
        (metrics,) = [r for r in records if r["type"] == "metrics"]
        assert "serving.num_requests" in metrics["snapshot"]["gauges"]

    def test_update_game_telemetry(self, trained, tiny_avro, tmp_path):
        from photon_ml_tpu.cli.serve_game import (
            parse_args as serve_args,
            run as serve_run,
        )
        from photon_ml_tpu.cli.update_game import parse_args, run

        artifact_dir = tmp_path / "artifact"
        serve_run(serve_args([
            "--model-dir", str(trained["model"]),
            "--export-artifact-dir", str(artifact_dir),
        ]))
        ledger_path = tmp_path / "update.jsonl"
        run(parse_args([
            "--base-artifact-dir", str(artifact_dir),
            "--model-dir", str(trained["model"]),
            "--coordinate-config", str(tiny_avro["config"]),
            "--events-data-dirs", str(tiny_avro["test"]),
            "--output-dir", str(tmp_path / "deltas"),
            "--telemetry-out", str(ledger_path),
        ]))
        records = validate_ledger(str(ledger_path))
        span_paths = {r["path"] for r in records if r["type"] == "span"}
        assert any("incremental/update" in p for p in span_paths)
        assert any("incremental/resolve" in p for p in span_paths)
        finish = [
            r for r in records
            if r["type"] == "meta" and r.get("phase") == "finish"
        ][0]
        assert finish["listener_errors"] == 0

    def test_disabled_default_bitwise_identical(self, tiny_avro, tmp_path):
        """Telemetry must not perturb training: the same tiny fit with and
        without tracing produces bitwise-identical coefficients."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.model_io import load_game_model

        def train(tag, telemetry):
            out = tmp_path / tag
            argv = [
                "--train-data-dirs", str(tiny_avro["train"]),
                "--coordinate-config", str(tiny_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(out),
            ]
            if telemetry:
                argv += ["--telemetry-out", str(out / "ledger.jsonl")]
            run(parse_args(argv))
            model, _ = load_game_model(str(out / "best"))
            return model

        plain = train("plain", telemetry=False)
        traced = train("traced", telemetry=True)
        fixed_p = np.asarray(plain.models["fixed"].coefficients.means)
        fixed_t = np.asarray(traced.models["fixed"].coefficients.means)
        np.testing.assert_array_equal(fixed_p, fixed_t)
        re_p = dict(plain.models["per_user"].items())
        re_t = dict(traced.models["per_user"].items())
        assert re_p == re_t  # exact per-entity sparse coefficient equality


class TestCrossThreadSpanPropagation:
    """The async CD schedule's telemetry contract: spans opened inside a
    ScheduleExecutor worker parent under the span that was live at the
    DISPATCH site (contextvars are copied at submit), not under the
    worker thread's own (empty) context — and the resulting cross-thread
    span tree survives ledger validation."""

    def test_worker_span_parents_under_dispatch_site(self, tracer):
        from photon_ml_tpu.algorithm.schedule import ScheduleExecutor

        def work():
            with span("fe/solve"):
                return 7

        with ScheduleExecutor(max_in_flight=2, name="t-sched") as ex:
            with span("cd/outer_iter", outer=0):
                w = ex.submit("fe", work, coordinate="fe", outer=0)
                assert w.result() == 7
        by_name = {r.name: r for r in tracer.spans()}
        overlap = by_name["cd/overlap"]
        assert overlap.parent_id == by_name["cd/outer_iter"].span_id
        assert overlap.attrs == {"coordinate": "fe", "outer": 0}
        assert by_name["fe/solve"].parent_id == overlap.span_id
        # the overlap span really ran on the pool thread, not the driver
        assert overlap.thread_id != by_name["cd/outer_iter"].thread_id
        assert overlap.thread_name.startswith("t-sched")

    def test_plain_thread_still_isolated(self, tracer):
        """Bare threads (no executor) keep today's behavior: their spans
        root independently — propagation is an explicit submit-time copy,
        not a global change to span parenting."""
        def worker():
            with span("w/root"):
                pass

        with span("driver"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        by_name = {r.name: r for r in tracer.spans()}
        assert by_name["w/root"].parent_id is None

    def test_concurrent_worker_spans_survive_ledger_validation(
        self, tmp_path, tracer
    ):
        """Two workers dispatched from one iteration write interleaved,
        genuinely concurrent spans; the ledger schema and the analyzer both
        accept the result (validate, then analyze_records must attribute
        nonzero overlap)."""
        import time as _time

        from photon_ml_tpu.algorithm.schedule import ScheduleExecutor
        from photon_ml_tpu.telemetry.analyze import analyze_records

        def work(tag):
            def _run():
                with span(f"fe/solve_{tag}" if tag == "a" else f"re/train_{tag}"):
                    _time.sleep(0.05)
                return tag
            return _run

        with ScheduleExecutor(max_in_flight=2) as ex:
            with span("cd/outer_iter", outer=0):
                wa = ex.submit("a", work("a"), coordinate="a", outer=0)
                wb = ex.submit("b", work("b"), coordinate="b", outer=0)
                assert wa.result() == "a"
                assert wb.result() == "b"

        path = tmp_path / "ledger.jsonl"
        ledger = RunLedger(str(path))
        # the run window must bracket the spans (spans are flushed at run
        # finish in production; here they are replayed after the fact, so
        # pin the start record to the tracer origin)
        ledger.write("meta", phase="start", label="xthread",
                     ts=tracer.origin_unix)
        for rec in tracer.spans():
            ledger.write_span(rec, tracer.origin_unix)
        ledger.write("meta", phase="finish", label="xthread")
        ledger.close()
        records = validate_ledger(str(path))
        spans = [r for r in records if r["type"] == "span"]
        assert len(spans) == 5  # outer_iter + 2 overlap + 2 solves
        by_id = {s["span_id"]: s for s in spans}
        for s in spans:
            if s["name"] != "cd/outer_iter":
                assert s["parent_id"] in by_id
        report = analyze_records(records)
        # the two 50ms worker spans ran concurrently: the analyzer shares
        # the segment instead of double-counting it, and reports overlap
        assert report.coverage <= 1.05
        assert report.overlap_s > 0
