"""Ragged-entity stress: Zipf-tailed entity sizes through the RE dataset.

Reference parity: RandomEffectDataSet.scala:287-388 — production random
effects are heavily skewed (a few entities with ~1e5 samples, a long tail
with 1), and the reference bounds the imbalance with the active-data
reservoir cap and partition balancing. Here the analogs are the reservoir
cap + size-BUCKETING of the padded blocks; these tests drive both with a
realistic Zipf skew and assert (a) no row is lost or duplicated, (b) the
per-entity projection stays exact, and (c) the padding overhead of the
dense blocks stays bounded (<2x real cells at num_buckets=8).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)


def _zipf_problem(rng, n_entities=2500, max_size=100_000, total_cap=400_000,
                  d_global=2000, nnz_per_row=10):
    """Zipf(1.5)-tailed entity sizes clipped to [1, max_size], truncated at
    ~total_cap rows; sparse rows over a d_global feature space."""
    sizes = np.minimum(rng.zipf(1.5, n_entities), max_size)
    keep = np.cumsum(sizes) <= total_cap
    sizes = sizes[keep]
    ids = np.repeat([f"e{i:05d}" for i in range(len(sizes))], sizes)
    n = len(ids)
    rows = np.repeat(np.arange(n, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, d_global, n * nnz_per_row).astype(np.int64)
    vals = rng.standard_normal(n * nnz_per_row).astype(np.float32)
    labels = rng.standard_normal(n).astype(np.float32)
    return ids, sizes, rows, cols, vals, labels, d_global, n


class TestRaggedZipf:
    def test_rows_partition_exactly(self, rng):
        """Active slots + passive rows + cap-dropped rows partition the
        source rows; nothing is lost, duplicated, or fabricated."""
        ids, sizes, rows, cols, vals, labels, d, n = _zipf_problem(rng)
        cap, lb = 256, 8
        ds = build_random_effect_dataset(
            ids, rows, cols, vals, d, labels,
            RandomEffectDataConfiguration(
                random_effect_type="eid",
                active_data_upper_bound=cap,
                passive_data_lower_bound=lb,
                num_buckets=8,
            ),
        )
        active_pos = np.concatenate([
            np.asarray(b.sample_pos)[np.asarray(b.weights) > 0]
            for b in ds.buckets
        ])
        passive_pos = np.concatenate([
            np.asarray(p.sample_pos) for p in ds.passive if p is not None
        ]) if any(p is not None for p in ds.passive) else np.empty(0, np.int64)
        got = np.concatenate([active_pos, passive_pos])
        assert len(got) == len(np.unique(got)), "row duplicated across blocks"

        counts = sizes
        expect_active = int(np.minimum(counts, cap).sum())
        # passive rows exist only for entities at/above the lower bound
        expect_passive = int(
            np.where(counts >= lb, np.maximum(counts - cap, 0), 0).sum()
        )
        assert len(active_pos) == expect_active
        assert len(passive_pos) == expect_passive
        # per-entity active counts honor the cap exactly
        for b, idlist in zip(ds.buckets, ds.entity_ids):
            per_entity = (np.asarray(b.weights) > 0).sum(axis=1)
            assert per_entity.max() <= cap
            assert len(idlist) == b.num_entities

    def test_projection_exact_on_skewed_entities(self, rng):
        """Spot-check the per-entity INDEX_MAP projection on the largest and
        several tail entities: block rows must reproduce the original sparse
        rows exactly through proj_indices."""
        ids, sizes, rows, cols, vals, labels, d, n = _zipf_problem(
            rng, n_entities=400, total_cap=60_000
        )
        ds = build_random_effect_dataset(
            ids, rows, cols, vals, d, labels,
            RandomEffectDataConfiguration(random_effect_type="eid", num_buckets=4),
        )
        # dense source matrix for verification
        X_src = np.zeros((n, d), np.float32)
        X_src[rows, cols] += vals

        uniq = [f"e{i:05d}" for i in range(len(sizes))]
        check = {uniq[int(np.argmax(sizes))]} | set(
            np.random.default_rng(0).choice(uniq, 5)
        )
        for eid in check:
            bi, row = ds.entity_to_loc[eid]
            b = ds.buckets[bi]
            Xb = np.asarray(b.X)[row]
            wt = np.asarray(b.weights)[row]
            pos = np.asarray(b.sample_pos)[row]
            pidx = np.asarray(b.proj_indices)[row]
            pval = np.asarray(b.proj_valid)[row]
            for s in np.flatnonzero(wt > 0):
                dense = np.zeros(d, np.float32)
                dense[pidx[pval]] = Xb[s][pval]
                np.testing.assert_allclose(
                    dense, X_src[pos[s]], rtol=1e-6, atol=1e-6,
                    err_msg=f"entity {eid} sample {s}",
                )

    def test_padding_overhead_bounded(self, rng):
        """Measured padding accounting at realistic skew: padded block cells
        vs real (sample x local-feature) cells. Documented in
        docs/SCALING.md; the bucketing must keep the ratio under 2x."""
        ids, sizes, rows, cols, vals, labels, d, n = _zipf_problem(rng)
        cfg = RandomEffectDataConfiguration(
            random_effect_type="eid",
            active_data_upper_bound=1024,
            num_buckets=8,
        )
        ds = build_random_effect_dataset(ids, rows, cols, vals, d, labels, cfg)
        padded = sum(b.num_entities * b.max_samples * b.local_dim for b in ds.buckets)
        real = 0
        for b in ds.buckets:
            wt = np.asarray(b.weights) > 0
            dloc = np.asarray(b.proj_valid).sum(axis=1)  # [E]
            real += int((wt.sum(axis=1) * np.maximum(dloc, 1)).sum())
        overhead = padded / max(real, 1)
        print(f"\npadding overhead at Zipf(1.5), 8 buckets: {overhead:.2f}x "
              f"({padded} padded cells / {real} real cells)")
        assert overhead < 2.0, f"padding overhead {overhead:.2f}x >= 2x"

        # one bucket (no size bucketing) must be strictly worse — the
        # bucketing is what contains the skew
        ds1 = build_random_effect_dataset(
            ids, rows, cols, vals, d, labels,
            RandomEffectDataConfiguration(
                random_effect_type="eid",
                active_data_upper_bound=1024,
                num_buckets=1,
            ),
        )
        padded1 = sum(
            b.num_entities * b.max_samples * b.local_dim for b in ds1.buckets
        )
        assert padded1 > padded, "bucketing did not reduce padding"

    def test_solve_on_ragged_blocks(self, rng):
        """The vmap'd solver runs on the skewed blocks end to end (weights
        mask the padding; no NaNs leak from size-1 entities)."""
        from photon_ml_tpu.estimators.random_effect import train_random_effects
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        ids, sizes, rows, cols, vals, labels, d, n = _zipf_problem(
            rng, n_entities=300, total_cap=20_000, d_global=200
        )
        ds = build_random_effect_dataset(
            ids, rows, cols, vals, d, labels,
            RandomEffectDataConfiguration(
                random_effect_type="eid",
                active_data_upper_bound=128,
                max_local_features=32,
                num_buckets=4,
            ),
        )
        model, results = train_random_effects(
            ds, TaskType.LINEAR_REGRESSION,
            GlmOptimizationConfiguration(
                optimizer_config=OptimizerConfig.lbfgs(max_iterations=10),
                regularization=RegularizationContext(RegularizationType.L2),
                regularization_weight=1.0,
            ),
        )
        for coefs in model.coefficients:
            assert np.all(np.isfinite(np.asarray(coefs)))
