"""Multi-device Benes fixed-effect path on the 8-virtual-device harness.

The reference tests its distributed path on local[4] Spark
(SparkTestUtils.scala:61-77); the analog here is the 8-device CPU mesh from
tests/conftest.py. The sharded engine must agree with the single-device
engine exactly (same math, different placement + one psum).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.parallel.mesh import DATA_AXIS, data_parallel_mesh
from photon_ml_tpu.parallel.sharded_benes import sharded_from_coo
from photon_ml_tpu.ops.sparse_perm import from_coo


def _problem(rng, n=1024, d=256, k=6, intercept=True):
    rows = np.repeat(np.arange(n), k + int(intercept))
    blocks = [rng.integers(1, d, (n, k))]
    if intercept:
        blocks.append(np.zeros((n, 1), np.int64))
    cols = np.concatenate(blocks, axis=1).reshape(-1)
    vals = rng.standard_normal(rows.size).astype(np.float32)
    return rows, cols, vals, (n, d)


class TestShardedBenes:
    def test_matches_single_device(self, rng):
        rows, cols, vals, shape = _problem(rng)
        mesh = data_parallel_mesh()
        sf = sharded_from_coo(rows, cols, vals, shape, mesh)
        bf = from_coo(rows, cols, vals, shape)
        n, d = shape
        assert sf.num_rows == n  # 1024 divides 8 evenly: no padding
        w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
        c = jnp.asarray(rng.standard_normal(n).astype(np.float32))

        np.testing.assert_allclose(
            np.asarray(sf.matvec(w)), np.asarray(bf.matvec(w)), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(sf.rmatvec(c)), np.asarray(bf.rmatvec(c)), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(sf.rmatvec_sq(c)), np.asarray(bf.rmatvec_sq(c)), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(sf.row_norms_sq()), np.asarray(bf.row_norms_sq()), atol=1e-4
        )

    def test_row_padding(self, rng):
        # 1001 rows over 8 devices -> n_loc=126, padded to 1008
        n = 1001
        rows, cols, vals, shape = _problem(rng, n=n, intercept=False)
        mesh = data_parallel_mesh()
        sf = sharded_from_coo(rows, cols, vals, shape, mesh)
        assert sf.num_rows == 1008
        w = jnp.asarray(rng.standard_normal(shape[1]).astype(np.float32))
        z = np.asarray(sf.matvec(w))
        bf = from_coo(rows, cols, vals, shape)
        zs = np.asarray(bf.matvec(w))
        # device-d owns global rows [d*126, (d+1)*126); the last shard's
        # tail rows are padding and must score exactly 0
        n_loc = 126
        for dev in range(8):
            lo = dev * n_loc
            real = min(n_loc, max(0, n - lo))
            np.testing.assert_allclose(
                z[lo : lo + real], zs[lo : lo + real], atol=1e-4
            )
            np.testing.assert_allclose(
                z[lo + real : lo + n_loc], 0.0, atol=1e-6
            )

    def test_full_solve_under_jit(self, rng):
        """End-to-end sharded L-BFGS fit == single-device fit (the sharded
        engine slots into the standard objective/solver unchanged)."""
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.ops.data import LabeledData

        rows, cols, vals, shape = _problem(rng, n=512, d=96, k=4)
        n, d = shape
        dense = np.zeros(shape, np.float32)
        np.add.at(dense, (rows, cols), vals)
        w_true = (rng.standard_normal(d) * 0.3).astype(np.float32)
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-dense @ w_true))).astype(
            np.float32
        )

        mesh = data_parallel_mesh()
        objective = make_glm_objective(LogisticLoss)
        cfg = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=40),
            regularization_weight=1.0,
        )
        results = {}
        for name, feats in {
            "single": from_coo(rows, cols, vals, shape),
            "sharded": sharded_from_coo(rows, cols, vals, shape, mesh),
        }.items():
            data = LabeledData.create(feats, jnp.asarray(y))
            res = jax.jit(
                lambda dd, feats=feats: solve(
                    objective,
                    jnp.zeros(d, jnp.float32),
                    dd,
                    cfg,
                    l2_weight=jnp.float32(1.0),
                )
            )(data)
            results[name] = res
        assert np.allclose(
            float(results["single"].value), float(results["sharded"].value), rtol=1e-4
        )
        assert np.allclose(
            np.asarray(results["single"].w),
            np.asarray(results["sharded"].w),
            atol=2e-3,
        )
