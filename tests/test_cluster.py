"""Cluster plane (photon_ml_tpu.parallel.cluster).

The reference Photon-ML runs fixed-effect optimization data-parallel over
Spark executors; this plane is that topology on the streaming runtime: a
coordinator partitions the streamed blocks across hosts per pass
(gap-balanced LPT over PR 13's ledger scores), every host accumulates its
partial ``(f, g)`` over its slice, and the coordinator's float64 sum +
single ``finalize`` IS the allreduce — so the distributed trajectory
matches single-host up to fp reassociation. These tests pin:

- the assigner's partition algebra (bootstrap round-robin, gap-weighted
  balance, failure exclusion, decision dedupe);
- the wire protocol's framing (roundtrip, EOF-as-death);
- end-to-end parity: a 2-host thread-hosted cluster fit lands within fp
  noise of the same in-process single-host fit;
- the host-failure drill: a chaos-killed worker's blocks are reassigned
  mid-pass and the fit still completes (events + counters recorded).
"""

import numpy as np
import pytest

from photon_ml_tpu.parallel.cluster import (
    BlockAssigner,
    ClusterCoordinator,
    ClusterWorker,
    MessageSocket,
    serve_worker_in_thread,
)
from photon_ml_tpu.resilience import clear_failures, reset_faults
from photon_ml_tpu.telemetry.metrics import get_registry
from photon_ml_tpu.types import TaskType


@pytest.fixture(autouse=True)
def _clean_state():
    reset_faults()
    clear_failures()
    yield
    reset_faults()
    clear_failures()


# ================================================================= assigner


class TestBlockAssigner:
    def test_uniform_bootstrap_is_round_robin_balanced(self):
        a = BlockAssigner(16, hosts=range(4))
        got = a.assign()
        assert sorted(got) == [0, 1, 2, 3]
        assert all(len(b) == 4 for b in got.values())
        covered = sorted(b for blks in got.values() for b in blks)
        assert covered == list(range(16))
        # deterministic: same ledger, same partition
        assert BlockAssigner(16, hosts=range(4)).assign() == got

    def test_blocks_stream_in_index_order_per_host(self):
        a = BlockAssigner(12, hosts=(0, 1, 2))
        for blks in a.assign().values():
            assert blks == sorted(blks)

    def test_gap_weighted_lpt_balances_score_mass(self):
        a = BlockAssigner(8, hosts=(0, 1))
        # one hot block: LPT must not stack more mass next to it
        a.update({0: 100.0, **{b: 1.0 for b in range(1, 8)}})
        got = a.assign()
        eff = a.effective_scores()
        shares = {h: eff[blks].sum() for h, blks in got.items()}
        hot_host = next(h for h, blks in got.items() if 0 in blks)
        other = 1 - hot_host
        # the hot host gets the hot block and nothing else
        assert got[hot_host] == [0]
        assert len(got[other]) == 7
        assert shares[hot_host] >= shares[other]

    def test_unmeasured_blocks_decay_toward_zero_weight(self):
        a = BlockAssigner(4, hosts=(0,), decay=0.5)
        a.update({0: 8.0, 1: 8.0})  # blocks 2, 3 never measured
        a.update({0: 8.0, 1: 8.0})
        eff = a.effective_scores()
        assert eff[0] == eff[1] == 8.0
        assert eff[2] == eff[3] == pytest.approx(0.25)  # 1.0 * 0.5**2

    def test_rebalance_decision_only_on_partition_change(self):
        a = BlockAssigner(8, hosts=(0, 1))
        a.assign()
        a.assign()  # identical ledger -> identical partition -> no event
        events = [d["event"] for d in a.drain_decisions()]
        assert events == ["rebalance"]
        a.update({b: float(b + 1) for b in range(8)})
        a.assign()
        assert [d["event"] for d in a.drain_decisions()] == ["rebalance"]

    def test_mark_host_failed_removes_from_rotation(self):
        a = BlockAssigner(9, hosts=(0, 1, 2))
        a.assign()
        a.mark_host_failed(1)
        got = a.assign()
        assert sorted(got) == [0, 2]
        covered = sorted(b for blks in got.values() for b in blks)
        assert covered == list(range(9))
        events = [d["event"] for d in a.drain_decisions()]
        assert events == ["rebalance", "host_failed", "rebalance"]

    def test_reassign_splits_over_survivors_and_records(self):
        a = BlockAssigner(8, hosts=(0, 1, 2))
        a.mark_host_failed(0)
        targets = a.reassign([1, 5, 7])
        assert set(targets) <= {1, 2}
        assert sorted(b for blks in targets.values() for b in blks) == [
            1, 5, 7,
        ]
        reassigns = [
            d for d in a.drain_decisions() if d["event"] == "reassign"
        ]
        assert len(reassigns) == 1 and reassigns[0]["blocks"] == [1, 5, 7]

    def test_excluded_blocks_leave_the_rotation(self):
        a = BlockAssigner(6, hosts=(0, 1))
        a.mark_blocks_failed([2, 4])
        covered = sorted(b for blks in a.assign().values() for b in blks)
        assert covered == [0, 1, 3, 5]

    def test_no_live_hosts_raises(self):
        a = BlockAssigner(4, hosts=(0,))
        a.mark_host_failed(0)
        with pytest.raises(RuntimeError, match="no live hosts"):
            a.assign()
        with pytest.raises(RuntimeError, match="every host failed"):
            a.reassign([0, 1])

    def test_validation(self):
        with pytest.raises(ValueError, match="num_blocks"):
            BlockAssigner(0, hosts=(0,))
        with pytest.raises(ValueError, match="host"):
            BlockAssigner(4, hosts=())
        with pytest.raises(ValueError, match="decay"):
            BlockAssigner(4, hosts=(0,), decay=0.0)


# ================================================================= protocol


class TestProtocol:
    def _pair(self):
        import socket

        a, b = socket.socketpair()
        return MessageSocket(a), MessageSocket(b)

    def test_roundtrip_preserves_arrays(self):
        tx, rx = self._pair()
        try:
            g = np.arange(1000, dtype=np.float64)
            tx.send({"type": "partial", "f": 1.5, "g": g})
            got = rx.recv()
            assert got["type"] == "partial" and got["f"] == 1.5
            np.testing.assert_array_equal(got["g"], g)
        finally:
            tx.close()
            rx.close()

    def test_peer_close_is_eof_not_garbage(self):
        tx, rx = self._pair()
        tx.send({"type": "hello"})
        tx.close()
        assert rx.recv() == {"type": "hello"}
        with pytest.raises(EOFError):
            rx.recv()
        rx.close()

    def test_interleaved_sends_frame_cleanly(self):
        # heartbeats race data sends on the same socket; the send lock
        # must keep frames atomic
        import threading

        tx, rx = self._pair()
        try:
            msgs = [{"type": "heartbeat", "host": i} for i in range(50)]
            threads = [
                threading.Thread(target=tx.send, args=(m,)) for m in msgs
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            got = [rx.recv() for _ in range(50)]
            assert sorted(m["host"] for m in got) == list(range(50))
        finally:
            tx.close()
            rx.close()


# ============================================================== end to end

FILE_ROWS = (110, 90)
N_ROWS = sum(FILE_ROWS)
D = 8
BLOCK_ROWS = 64  # 200 rows -> 4 blocks, final one ragged

SHARDS = None  # populated by the fixture import below


@pytest.fixture(scope="module")
def cluster_dataset(tmp_path_factory):
    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration,
        build_index_maps,
        write_training_examples,
    )

    shards = {
        "global": FeatureShardConfiguration(
            feature_bags=("features",), add_intercept=True
        ),
    }
    rng = np.random.default_rng(71)
    root = tmp_path_factory.mktemp("cluster_stream")
    X = rng.normal(size=(N_ROWS, D)).astype(np.float32)
    w = rng.normal(size=D).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-(X @ w))) > rng.random(N_ROWS)).astype(
        np.float32
    )
    paths, row = [], 0
    for fi, n in enumerate(FILE_ROWS):
        recs = [
            {
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0,
                "features": [
                    ("g", str(j), float(X[i, j])) for j in range(D)
                ],
            }
            for i in range(row, row + n)
        ]
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    return {
        "paths": paths,
        "shards": shards,
        "index_maps": build_index_maps(paths, shards),
    }


def _open_source(ds):
    from photon_ml_tpu.streaming import StreamingSource

    return StreamingSource.open(
        ds["paths"], ds["shards"], index_maps=ds["index_maps"],
        block_rows=BLOCK_ROWS,
    )


def _estimator():
    from photon_ml_tpu.estimators.game import (
        FixedEffectCoordinateConfiguration,
        GameEstimator,
    )
    from photon_ml_tpu.opt import (
        GlmOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.types import RegularizationType

    return GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration(
                "global",
                GlmOptimizationConfiguration(
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                    regularization_weight=0.5,
                ),
            )
        },
        num_outer_iterations=1,
    )


def _plane(ds, hosts=2, chaos_kill_after=None):
    num_blocks = _open_source(ds).plan.num_blocks
    coord = ClusterCoordinator(hosts, num_blocks, heartbeat_timeout_s=60.0)
    for h in range(hosts):
        serve_worker_in_thread(
            ClusterWorker(
                host_id=h,
                source=_open_source(ds),
                shard_id="global",
                task=TaskType.LOGISTIC_REGRESSION,
                chaos_kill_after=(
                    chaos_kill_after if h == hosts - 1 else None
                ),
            ),
            coord.address,
        )
    coord.wait_for_workers(timeout_s=60.0)
    return coord


def _fe_weights(fit):
    return np.asarray(fit.model.models["fixed"].coefficients.means)


class TestClusterFitParity:
    def test_two_host_fit_matches_single_host_within_fp_noise(
        self, cluster_dataset
    ):
        from photon_ml_tpu.telemetry import ConvergenceTracker

        solo = _estimator().fit_streaming(
            _open_source(cluster_dataset), prefetch_depth=2
        )
        tracker = ConvergenceTracker(abort_on_divergence=False)
        plane = _plane(cluster_dataset, hosts=2)
        try:
            clustered = _estimator().fit_streaming(
                _open_source(cluster_dataset),
                prefetch_depth=2,
                cluster=plane,
                progress=tracker,
            )
        finally:
            plane.shutdown()
        tracker.finish()
        w_solo, w_cluster = _fe_weights(solo), _fe_weights(clustered)
        # same trajectory up to fp reassociation of the partial sums —
        # parity is allclose, not bitwise (docs/SCALING.md)
        np.testing.assert_allclose(w_cluster, w_solo, atol=2e-3)
        cluster_recs = [
            r for r in tracker.records if r.get("kind") == "cluster"
        ]
        assert any(r["event"] == "rebalance" for r in cluster_recs)
        # the workers' probe stats reach the same ledger seam the
        # single-host probe feeds
        block_recs = [
            r for r in tracker.records if r.get("kind") == "block"
        ]
        assert {r["block"] for r in block_recs} == set(range(4))
        assert all("gap_estimate" in r for r in block_recs)

    def test_workers_report_host_attributed_block_stats(
        self, cluster_dataset
    ):
        dim = _open_source(cluster_dataset).plan.shard_dims["global"]
        plane = _plane(cluster_dataset, hosts=2)
        try:
            _, _, gaps, stats = plane.distributed_pass(
                np.zeros(dim, dtype=np.float32)
            )
        finally:
            plane.shutdown()
        assert {s["block"] for s in stats} == set(range(4))
        assert {s["host"] for s in stats} == {0, 1}
        assert sorted(gaps) == list(range(4))
        assert all(
            {"partial_loss", "partial_grad_norm", "gap"} <= set(s)
            for s in stats
        )

    def test_cluster_requires_full_batch_mode(self, cluster_dataset):
        plane = _plane(cluster_dataset, hosts=2)
        try:
            with pytest.raises(ValueError, match="full"):
                _estimator().fit_streaming(
                    _open_source(cluster_dataset),
                    mode="stochastic",
                    cluster=plane,
                )
        finally:
            plane.shutdown()

    def test_cluster_rejects_block_plan_skew(self, cluster_dataset):
        plane = _plane(cluster_dataset, hosts=2)
        try:
            from photon_ml_tpu.streaming import StreamingSource

            skewed = StreamingSource.open(
                cluster_dataset["paths"], cluster_dataset["shards"],
                index_maps=cluster_dataset["index_maps"],
                block_rows=BLOCK_ROWS // 2,  # different plan
            )
            with pytest.raises(ValueError, match="blocks"):
                _estimator().fit_streaming(skewed, cluster=plane)
        finally:
            plane.shutdown()


class TestKilledHostRecovery:
    def test_fit_survives_chaos_killed_host(self, cluster_dataset):
        reg = get_registry()
        hf0 = reg.counter_value("cluster.host_failures")
        br0 = reg.counter_value("cluster.blocks_reassigned")

        solo = _estimator().fit_streaming(
            _open_source(cluster_dataset), prefetch_depth=2
        )
        # host 1 dies after 3 blocks: mid-pass-2 with 2 blocks/host/pass
        plane = _plane(cluster_dataset, hosts=2, chaos_kill_after=3)
        try:
            fit = _estimator().fit_streaming(
                _open_source(cluster_dataset),
                prefetch_depth=2,
                cluster=plane,
            )
            # post-failure passes partition over the survivor only
            dim = _open_source(cluster_dataset).plan.shard_dims["global"]
            _, _, _, stats = plane.distributed_pass(
                np.zeros(dim, dtype=np.float32)
            )
        finally:
            plane.shutdown()

        # completed, and on the surviving host's math the answer is the
        # same fit
        np.testing.assert_allclose(
            _fe_weights(fit), _fe_weights(solo), atol=2e-3
        )
        assert {s["host"] for s in stats} == {0}
        assert reg.counter_value("cluster.host_failures") == hf0 + 1
        assert reg.counter_value("cluster.blocks_reassigned") > br0

    def test_cluster_events_land_in_progress_ledger(self, cluster_dataset):
        from photon_ml_tpu.telemetry import ConvergenceTracker

        tracker = ConvergenceTracker(abort_on_divergence=False)
        plane = _plane(cluster_dataset, hosts=2, chaos_kill_after=3)
        try:
            _estimator().fit_streaming(
                _open_source(cluster_dataset),
                prefetch_depth=2,
                cluster=plane,
                progress=tracker,
            )
        finally:
            plane.shutdown()
        tracker.finish()
        recs = [r for r in tracker.records if r.get("kind") == "cluster"]
        assert recs, "cluster events must reach the progress ledger"
        kinds = {r["event"] for r in recs}
        assert "host_lost" in kinds and "blocks_reassigned" in kinds
        assert all(r["coordinate"] == "fixed" for r in recs)


class TestSkewProfile:
    """Coordinator telemetry: per-pass skew profiles, wire-level gating,
    stray-partial accounting, and heartbeat-check starvation."""

    def test_profile_decomposes_pass_wall_exactly(self, cluster_dataset):
        dim = _open_source(cluster_dataset).plan.shard_dims["global"]
        plane = _plane(cluster_dataset, hosts=2)
        plane.enable_telemetry()
        try:
            plane.distributed_pass(np.zeros(dim, dtype=np.float32))
            plane.distributed_pass(np.zeros(dim, dtype=np.float32))
        finally:
            plane.shutdown()
        profiles = plane.drain_pass_profiles()
        assert len(profiles) == 2
        assert plane.drain_pass_profiles() == []  # drained
        for p in profiles:
            # exact decomposition: busy + allreduce wait + bubble == wall
            assert p["busy_s"] + p["allreduce_wait_s"] + p["bubble_s"] == (
                pytest.approx(p["wall_s"], rel=1e-6)
            )
            assert sorted(p["hosts"]) == [0, 1]
            assert p["blocks"] == 4
            assert p["straggler_host"] in (0, 1)
            assert p["straggler_index"] >= 1.0
            for h in p["hosts"].values():
                assert h["busy_s"] > 0
                assert h["blocks"] == 2
                assert h["predicted_share"] == pytest.approx(0.5, abs=0.2)
                assert 0.0 < h["actual_share"] < 1.0
            frags = p["fragments"]
            assert {f["host"] for f in frags} == {0, 1}
            assert all(
                f["arrival_s"] >= f["dispatch_s"] >= 0.0 for f in frags
            )

    def test_disabled_path_sends_byte_identical_messages(self):
        import socket as _socket

        from photon_ml_tpu.parallel.cluster.coordinator import _WorkerHandle

        coord = ClusterCoordinator(1, 4)
        a, b = _socket.socketpair()
        handle = _WorkerHandle(0, MessageSocket(a))
        peer = MessageSocket(b)
        try:
            coord._pass_t0 = 0.0
            assert coord._send_fragment(
                handle, 1, 0, np.zeros(2, dtype=np.float32), [0, 1]
            )
            msg = peer.recv()
            # no telemetry key, nothing beyond the PR 17 vocabulary
            assert set(msg) == {"type", "pass_id", "frag", "w", "blocks"}
            assert coord._frag_meta == {}

            coord.enable_telemetry()
            import time as _time

            coord._pass_t0 = _time.monotonic()
            assert coord._send_fragment(
                handle, 1, 1, np.zeros(2, dtype=np.float32), [2, 3]
            )
            msg = peer.recv()
            assert msg["telemetry"] is True
            assert (0, 1) in coord._frag_meta
        finally:
            handle.msock.close()
            peer.close()
            coord.shutdown()

    def test_stray_partials_are_counted_not_silent(self, cluster_dataset):
        reg = get_registry()
        stray0 = reg.counter_value("cluster.stray_partials")
        dim = _open_source(cluster_dataset).plan.shard_dims["global"]
        plane = _plane(cluster_dataset, hosts=2)
        plane.enable_telemetry()
        # a reply from an abandoned pass sits in the inbox when the next
        # pass starts draining
        plane._inbox.put((0, {
            "type": "partial", "pass_id": -99, "frag": 0, "host": 0,
            "f": 0.0, "g": np.zeros(dim, dtype=np.float64),
            "block_stats": [],
        }))
        try:
            plane.distributed_pass(np.zeros(dim, dtype=np.float32))
        finally:
            plane.shutdown()
        assert reg.counter_value("cluster.stray_partials") == stray0 + 1
        (profile,) = plane.drain_pass_profiles()
        assert profile["stray_partials"] == 1

    def test_heartbeat_check_not_starved_by_busy_inbox(self):
        """A chatty inbox must not defer dead-host detection: host 1
        wedges (never replies, never heartbeats) while host 0 floods the
        inbox; the interval check must still lose host 1 and requeue."""
        import socket as _socket
        import threading
        import time as _time

        from photon_ml_tpu.parallel.cluster.coordinator import _WorkerHandle

        reg = get_registry()
        hb0 = reg.counter_value("cluster.host_failures")
        rq0 = reg.counter_value("cluster.requeued_blocks")
        coord = ClusterCoordinator(2, 4, heartbeat_timeout_s=0.3)
        peers = {}
        for h in range(2):
            a, b = _socket.socketpair()
            handle = _WorkerHandle(h, MessageSocket(a))
            coord.workers[h] = handle
            peers[h] = MessageSocket(b)
            threading.Thread(
                target=coord._reader, args=(handle,), daemon=True
            ).start()
        coord.workers[1].last_seen = _time.monotonic() - 10.0
        stop = threading.Event()

        def _host0():
            try:
                while not stop.is_set():
                    msg = peers[0].recv()
                    if msg.get("type") != "pass":
                        return
                    peers[0].send({
                        "type": "partial", "pass_id": msg["pass_id"],
                        "frag": msg["frag"], "host": 0,
                        "f": 0.0, "g": np.zeros(3, dtype=np.float64),
                        "block_stats": [
                            {"block": int(blk), "partial_loss": 0.0,
                             "partial_grad_norm": 0.0, "gap": 0.0}
                            for blk in msg["blocks"]
                        ],
                    })
            except (EOFError, OSError):
                pass

        def _flood():
            # keep the inbox non-empty so queue.Empty (the old, starved
            # check site) never fires
            try:
                while not stop.is_set():
                    peers[0].send({
                        "type": "partial", "pass_id": -1, "frag": 0,
                        "host": 0, "f": 0.0,
                        "g": np.zeros(3, dtype=np.float64),
                        "block_stats": [],
                    })
                    _time.sleep(0.002)
            except (EOFError, OSError):
                pass

        threading.Thread(target=_host0, daemon=True).start()
        threading.Thread(target=_flood, daemon=True).start()
        try:
            t0 = _time.monotonic()
            f, g, gaps, stats = coord.distributed_pass(
                np.zeros(3, dtype=np.float32)
            )
            elapsed = _time.monotonic() - t0
        finally:
            stop.set()
            coord.shutdown()
            for p in peers.values():
                p.close()
        assert not coord.workers[1].alive
        assert sorted(gaps) == [0, 1, 2, 3]
        assert reg.counter_value("cluster.host_failures") == hb0 + 1
        assert reg.counter_value("cluster.requeued_blocks") >= rq0 + 2
        # detection happened on the interval, not after the flood ended
        assert elapsed < 5.0

    def test_heartbeat_interarrival_gauge(self):
        import socket as _socket
        import threading
        import time as _time

        from photon_ml_tpu.parallel.cluster.coordinator import _WorkerHandle

        coord = ClusterCoordinator(1, 4)
        a, b = _socket.socketpair()
        handle = _WorkerHandle(0, MessageSocket(a))
        coord.workers[0] = handle
        peer = MessageSocket(b)
        threading.Thread(
            target=coord._reader, args=(handle,), daemon=True
        ).start()
        try:
            for _ in range(3):
                peer.send({"type": "heartbeat", "host": 0})
                _time.sleep(0.03)
            deadline = _time.monotonic() + 5.0
            while (
                len(handle.beat_deltas) < 2
                and _time.monotonic() < deadline
            ):
                _time.sleep(0.01)
        finally:
            peer.close()
            coord.shutdown()
        assert len(handle.beat_deltas) >= 2
        snap = get_registry().snapshot()
        name = 'cluster.heartbeat_interarrival_p99_s{host="0"}'
        assert name in snap["gauges"]
        assert snap["gauges"][name]["last"] > 0.0

    def test_profiles_reach_progress_ledger_and_cluster_json(
        self, cluster_dataset
    ):
        from photon_ml_tpu.telemetry import ConvergenceTracker

        tracker = ConvergenceTracker(abort_on_divergence=False)
        plane = _plane(cluster_dataset, hosts=2)
        plane.enable_telemetry()
        try:
            _estimator().fit_streaming(
                _open_source(cluster_dataset),
                prefetch_depth=2,
                cluster=plane,
                progress=tracker,
            )
        finally:
            plane.shutdown()
        tracker.finish()
        pass_recs = [
            r for r in tracker.records if r.get("kind") == "cluster_pass"
        ]
        host_recs = [
            r for r in tracker.records if r.get("kind") == "host_pass"
        ]
        assert pass_recs, "skew profiles must reach the progress ledger"
        assert {r["host"] for r in host_recs} == {0, 1}
        for r in pass_recs:
            assert r["busy_s"] + r["allreduce_wait_s"] + r["bubble_s"] == (
                pytest.approx(r["wall_s"], rel=1e-6)
            )
            assert r["hosts"] == 2
        doc = tracker.cluster_json()
        assert doc["num_passes"] == len(pass_recs)
        assert doc["straggler_index_last"] >= 1.0


class TestCoordinatorHandshake:
    def test_block_plan_skew_rejected_at_hello(self, cluster_dataset):
        import threading

        num_blocks = _open_source(cluster_dataset).plan.num_blocks
        coord = ClusterCoordinator(1, num_blocks + 1)
        worker = ClusterWorker(
            host_id=0,
            source=_open_source(cluster_dataset),
            shard_id="global",
            task=TaskType.LOGISTIC_REGRESSION,
        )
        t = threading.Thread(
            target=lambda: serve_worker_in_thread(worker, coord.address),
            daemon=True,
        )
        t.start()
        from photon_ml_tpu.parallel.cluster import ClusterError

        with pytest.raises(ClusterError):
            coord.wait_for_workers(timeout_s=5.0)
        coord.shutdown()
