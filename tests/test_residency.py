"""Hierarchical device residency (streaming/residency.py).

The CI "Residency parity gate" runs this module: the residency-disabled
path must stay bitwise identical to the historical streamed solver with
zero extra jit traces, and the enabled path must cut warm-pass H2D bytes
while leaving the solve trajectory untouched (identical visit order —
residency changes transfer volume, never arithmetic).
"""

import numpy as np
import pytest

from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    build_index_maps,
    read_game_data,
    write_training_examples,
)
from photon_ml_tpu.streaming import (
    GapScheduler,
    ResidencyManager,
    StreamingSource,
    residency_hierarchy,
    stream_trace_counts,
)
from photon_ml_tpu.telemetry import get_registry

FILE_ROWS = (250, 270, 180)
N_ROWS = sum(FILE_ROWS)
D_GLOBAL = 12
BLOCK_ROWS = 128  # 700 rows -> 6 blocks, final one ragged

SHARDS = {
    "global": FeatureShardConfiguration(
        feature_bags=("features",), add_intercept=True
    ),
}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    rng = np.random.default_rng(23)
    root = tmp_path_factory.mktemp("residency")
    X = rng.normal(size=(N_ROWS, D_GLOBAL)).astype(np.float32)
    w = rng.normal(size=D_GLOBAL).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-(X @ w))) > rng.random(N_ROWS)).astype(
        np.float32
    )
    paths = []
    row = 0
    for fi, n in enumerate(FILE_ROWS):
        recs = [
            {
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0 + (i % 2),
                "features": [
                    ("g", str(j), float(X[i, j])) for j in range(D_GLOBAL)
                ],
            }
            for i in range(row, row + n)
        ]
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    index_maps = build_index_maps(paths, SHARDS)
    return {"paths": paths, "index_maps": index_maps}


@pytest.fixture(scope="module")
def source(dataset):
    return StreamingSource.open(
        dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
        block_rows=BLOCK_ROWS,
    )


@pytest.fixture(scope="module")
def mem_data(dataset):
    data, _, _ = read_game_data(
        dataset["paths"], SHARDS, dataset["index_maps"]
    )
    return data


def _coordinate(source, **kw):
    from photon_ml_tpu.opt import (
        GlmOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.streaming.coordinate import (
        StreamingFixedEffectCoordinate,
    )
    from photon_ml_tpu.types import RegularizationType, TaskType

    cfg = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.5,
    )
    return StreamingFixedEffectCoordinate(
        source=source,
        shard_id="global",
        task=TaskType.LOGISTIC_REGRESSION,
        configuration=cfg,
        **kw,
    )


# ------------------------------------------------------------ manager unit
class TestResidencyManager:
    def test_budget_math(self):
        # byte budget divides by the uniform per-block upload size; the
        # tighter of blocks/bytes wins
        m = ResidencyManager(10, block_bytes=100, max_blocks=8, max_bytes=450)
        assert m.capacity == 4
        m = ResidencyManager(10, block_bytes=100, max_blocks=3, max_bytes=450)
        assert m.capacity == 3
        m = ResidencyManager(4, block_bytes=100, max_blocks=64)
        assert m.capacity == 4  # never more than the plan has
        with pytest.raises(ValueError, match="admits no blocks"):
            ResidencyManager(10, block_bytes=100, max_bytes=99)

    def test_bootstrap_then_gap_pinning(self):
        m = ResidencyManager(6, block_bytes=10, max_blocks=2)
        # bootstrap: first-come admission up to capacity
        assert m.offer(0, "e0") and m.offer(1, "e1")
        assert not m.offer(2, "e2")  # budget full
        assert m.resident_indices() == [0, 1]
        assert m.get(0) == "e0" and m.get(3) is None
        # measured gaps say blocks 4 and 5 matter: repin evicts 0 and 1
        m.update_gaps({0: 0.1, 1: 0.2, 2: 0.3, 3: 0.4, 4: 5.0, 5: 6.0})
        target = m.repin()
        assert target == [5, 4]
        assert m.resident_indices() == []  # evicted; re-pinned on visit
        assert not m.offer(2, "e2")  # not in target
        assert m.offer(5, "e5")
        assert m.resident_indices() == [5]
        assert m.stats.evicted_blocks == 2

    def test_repin_deterministic_under_fixed_gap_trajectory(self):
        trajectory = [
            {i: g for i, g in enumerate([3.0, 1.0, 4.0, 1.0, 5.0, 9.0])},
            {0: 0.5, 2: 8.0, 4: 0.5},
            {1: 7.0, 3: 7.0, 5: 0.1},  # exact tie -> stable index order
        ]
        runs = []
        for _ in range(2):
            m = ResidencyManager(6, block_bytes=10, max_blocks=3)
            targets = []
            for gaps in trajectory:
                m.update_gaps(gaps)
                targets.append(m.repin())
            runs.append(targets)
        assert runs[0] == runs[1]
        # ties broke by block index (stable argsort), deterministically
        assert runs[0][-1][0] == 1

    def test_gap_decay_evicts_stale_blocks(self):
        m = ResidencyManager(4, block_bytes=10, max_blocks=2, decay=0.5)
        m.update_gaps({0: 10.0, 1: 1.0, 2: 1.0, 3: 1.0})
        assert m.repin() == [0, 1]
        assert m.offer(0, "e0")
        # block 0 never re-measured: 10 * 0.5^age decays below the fresh
        # measurements and the pin flips
        for _ in range(4):
            m.update_gaps({1: 1.0, 2: 1.0, 3: 1.0})
        assert m.repin() == [1, 2]
        assert not m.is_resident(0)

    def test_mark_failed_evicts_and_excludes(self):
        m = ResidencyManager(4, block_bytes=10, max_blocks=2)
        assert m.offer(0, "e0")
        m.mark_failed([0])
        assert not m.is_resident(0)
        assert not m.offer(0, "e0")  # permanently excluded
        m.update_gaps({0: 99.0, 1: 1.0, 2: 2.0, 3: 3.0})
        assert 0 not in m.repin()  # even on a huge measured gap
        actions = [(d["action"], d["block"]) for d in m.drain_decisions()]
        assert ("evict", 0) in actions

    def test_decision_records_carry_score_and_byte_delta(self):
        m = ResidencyManager(4, block_bytes=10, max_blocks=2)
        m.offer(1, "e1")
        m.update_gaps({0: 1.0, 1: 0.1, 2: 2.0, 3: 3.0})
        m.repin()  # 1 falls out of the target -> evict
        recs = m.drain_decisions()
        pin = next(r for r in recs if r["action"] == "pin")
        ev = next(r for r in recs if r["action"] == "evict")
        assert pin["block"] == 1 and pin["byte_delta"] == 10
        assert pin["gap_score"] == -1.0  # bootstrap pin: no measurement
        assert ev["block"] == 1 and ev["byte_delta"] == -10
        assert ev["gap_score"] == pytest.approx(0.1)
        assert m.drain_decisions() == []  # drained

    def test_gap_scheduler_mark_failed_evicts_resident_block(self):
        sched = GapScheduler(6, seed=0)
        m = ResidencyManager(6, block_bytes=10, max_blocks=3)
        sched.attach_residency(m)
        assert m.offer(2, "e2")
        sched.mark_failed([2])
        assert not m.is_resident(2)
        assert bool(m.excluded[2]) and bool(sched.excluded[2])

    def test_gap_scheduler_update_drives_repin(self):
        sched = GapScheduler(4, seed=0)
        m = ResidencyManager(4, block_bytes=10, max_blocks=2)
        sched.attach_residency(m)
        sched.update({0: 1.0, 1: 9.0, 2: 8.0, 3: 0.5})
        # the scheduler's epoch-end feedback doubled as the repin signal
        assert m.epoch == 1
        assert m.offer(1, "e1") and not m.offer(0, "e0")


# ---------------------------------------------------------- streamed solve
class TestResidencyStreaming:
    def _fit_w(self, source, **kw):
        coord = _coordinate(source, **kw)
        model = coord.update_model(None, np.zeros(N_ROWS, np.float32))
        return coord, np.asarray(model.coefficients.means)

    def test_disabled_path_bitwise_and_zero_retrace(self, source):
        _, w_plain = self._fit_w(source)
        before = dict(stream_trace_counts())
        _, w_off = self._fit_w(source, resident_blocks=0)
        after = dict(stream_trace_counts())
        # residency off: the historical streamed path, bit for bit, and
        # not a single new jit trace
        np.testing.assert_array_equal(w_plain, w_off)
        assert after == before, {
            k: after[k] - before.get(k, 0)
            for k in after if after[k] != before.get(k, 0)
        }

    def test_enabled_matches_probe_path_bitwise(self, source):
        # residency serves identical device arrays in identical order; the
        # only program difference vs a probe-enabled solve is NONE — so the
        # trajectories must agree bit for bit
        _, w_probe = self._fit_w(source, collect_block_stats=True)
        coord, w_res = self._fit_w(source, resident_blocks=3)
        np.testing.assert_array_equal(w_probe, w_res)
        assert coord._residency.stats.hbm_hit_blocks > 0

    def test_resident_set_cuts_h2d_bytes(self, source):
        reg = get_registry()
        b0 = reg.counter_value("stream.h2d_bytes")
        coord, _ = self._fit_w(source, collect_block_stats=True)
        plain_bytes = reg.counter_value("stream.h2d_bytes") - b0
        passes = coord.last_solve_info.passes

        b1 = reg.counter_value("stream.h2d_bytes")
        coord_r, _ = self._fit_w(source, resident_blocks=4)
        res_bytes = reg.counter_value("stream.h2d_bytes") - b1
        passes_r = coord_r.last_solve_info.passes

        assert passes == passes_r  # same trajectory, same pass count
        # pass 1 uploads everything; every later pass skips the residents
        block_bytes = source.block_upload_bytes(("global",))
        num_blocks = source.plan.num_blocks
        assert plain_bytes == passes * num_blocks * block_bytes
        # exact conservation: every byte not re-uploaded was served from the
        # resident set (repin churn may re-upload a block once after an
        # eviction, so the saving is counted from actual HBM hits)
        mstats = coord_r._residency.stats
        assert plain_bytes - res_bytes == mstats.hbm_hit_bytes
        assert mstats.hbm_hit_bytes == mstats.hbm_hit_blocks * block_bytes
        # ...and the saving is substantial: at least 4 resident blocks per
        # pass once pinned, minus one pass of slack for bootstrap + churn
        assert mstats.hbm_hit_blocks >= (passes - 2) * 4
        stats = coord_r.last_prefetch_stats
        assert stats.resident_hit_blocks == 4
        assert stats.resident_hit_bytes == 4 * block_bytes

    def test_resident_buffers_survive_the_donation_seam(self, source):
        # acc_vg donates ONLY the f/g accumulators (argnums 2,3) — a pinned
        # block's arrays must stay alive across passes and solves
        coord, _ = self._fit_w(source, resident_blocks=3)
        entries = list(coord._residency._entries.values())
        assert entries
        for blk in entries:
            feats = blk.data["global"].features
            assert not feats.values.is_deleted()
            assert not feats.indices.is_deleted()
            np.asarray(feats.values)  # still materializable
        # and a second solve through the same pinned arrays still works
        model2 = coord.update_model(None, np.zeros(N_ROWS, np.float32))
        assert np.isfinite(np.asarray(model2.coefficients.means)).all()

    def test_resident_set_follows_gap_probe(self, source):
        coord, _ = self._fit_w(source, resident_blocks=2)
        mgr = coord._residency
        # after the solve the set equals the top-capacity blocks by
        # staleness-decayed measured gap — chosen, not static
        eff = mgr.effective_scores()
        want = sorted(np.argsort(-eff, kind="stable")[:2].tolist())
        assert sorted(mgr._target) == want
        assert (mgr.scores >= 0).all()  # every block was measured

    def test_residency_decisions_drain_for_the_ledger(self, source):
        from photon_ml_tpu.telemetry.validate import _PROGRESS_SCHEMAS

        coord, _ = self._fit_w(source, resident_blocks=2)
        decisions = coord.last_residency_decisions
        assert decisions and any(d["action"] == "pin" for d in decisions)
        required = set(_PROGRESS_SCHEMAS["residency"]) - {
            "outer", "coordinate"
        }
        for d in decisions:
            assert required <= set(d)

    def test_byte_budget_and_validation(self, source):
        block_bytes = source.block_upload_bytes(("global",))
        coord = _coordinate(source, resident_bytes=2 * block_bytes + 1)
        assert coord._residency.capacity == 2
        with pytest.raises(ValueError, match="admits no blocks"):
            _coordinate(source, resident_bytes=block_bytes - 1)
        with pytest.raises(ValueError, match="gap_schedule"):
            _coordinate(source, mode="stochastic", resident_blocks=2)

    def test_stochastic_residency_with_gap_schedule(self, source, mem_data):
        coord = _coordinate(
            source, mode="stochastic", gap_schedule=True, resident_blocks=2,
            epochs=8, chunk_iters=4,
        )
        model = coord.update_model(None, np.zeros(N_ROWS, np.float32))
        assert np.isfinite(np.asarray(model.coefficients.means)).all()
        mgr = coord._residency
        # epochs repinned through the scheduler's gap feedback
        assert mgr.stats.repins >= 1
        assert mgr.resident_blocks <= 2

    def test_hierarchy_accounting(self, source):
        coord, _ = self._fit_w(source, resident_blocks=3)
        levels = residency_hierarchy(source, coord._residency)
        assert set(levels) == {"disk", "ram", "hbm"}
        assert levels["hbm"]["hit_blocks"] > 0
        assert levels["hbm"]["saved_bytes"] == (
            levels["hbm"]["hit_blocks"]
            * source.block_upload_bytes(("global",))
        )
        # the decoded-file LRU (RAM level) served repeat visits
        assert levels["ram"]["file_cache_hits"] > 0
        assert levels["ram"]["files_decoded"] >= len(FILE_ROWS)


# --------------------------------------------------------------- estimator
class TestResidencyEstimator:
    def _estimator(self):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        cfg = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.1,
        )
        return GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration("global", cfg)
            },
            update_order=["fixed"],
            num_outer_iterations=1,
        )

    def test_fit_streaming_resident_auc_parity(self, source, mem_data):
        def auc(scores):
            order = np.argsort(scores)
            ranks = np.empty(len(scores))
            ranks[order] = np.arange(1, len(scores) + 1)
            pos = mem_data.labels > 0.5
            n_pos, n_neg = int(pos.sum()), int((~pos).sum())
            return (
                ranks[pos].sum() - n_pos * (n_pos + 1) / 2
            ) / (n_pos * n_neg)

        fit_plain = self._estimator().fit_streaming(source)
        fit_res = self._estimator().fit_streaming(source, resident_blocks=4)
        a_plain = auc(np.asarray(fit_plain.model.score(mem_data)))
        a_res = auc(np.asarray(fit_res.model.score(mem_data)))
        assert abs(a_plain - a_res) < 1e-6, (a_plain, a_res)

    def test_fit_streaming_validates_stochastic_residency(self, source):
        with pytest.raises(ValueError, match="gap_schedule"):
            self._estimator().fit_streaming(
                source, mode="stochastic", resident_blocks=2
            )
