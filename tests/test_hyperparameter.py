"""Hyperparameter-tuning tests, modeled on the reference's
hyperparameter/*Test suite: kernel math, slice-sampler distribution
recovery, GP regression quality, acquisition criteria direction, and
search loops (random + Bayesian) on cheap synthetic objectives, plus the
GameEstimator evaluation-function round trip."""

import numpy as np
import pytest

from photon_ml_tpu.hyperparameter import (
    RBF,
    ConfidenceBound,
    ExpectedImprovement,
    GaussianProcessEstimator,
    GaussianProcessSearch,
    Matern52,
    RandomSearch,
    SliceSampler,
)


class QuadraticEvalFn:
    """Maximize -(x-target)^2 summed over dims; records trial points."""

    def __init__(self, target):
        self.target = np.asarray(target, dtype=float)
        self.calls = []

    def __call__(self, h):
        value = -float(np.sum((h - self.target) ** 2))
        self.calls.append(np.asarray(h))
        return value, (np.asarray(h, dtype=float), value)

    def vectorize_params(self, result):
        return result[0]

    def get_evaluation_value(self, result):
        return result[1]


class TestKernels:
    def test_rbf_closed_form(self):
        x = np.array([[0.0], [1.0], [3.0]])
        k = RBF()(x)
        assert k[0, 0] == pytest.approx(1.0)
        assert k[0, 1] == pytest.approx(np.exp(-0.5))
        assert k[0, 2] == pytest.approx(np.exp(-4.5))
        assert np.allclose(k, k.T)

    def test_matern52_closed_form(self):
        x = np.array([[0.0], [2.0]])
        r2 = 4.0
        f = np.sqrt(5 * r2)
        expected = (1 + f + 5 * r2 / 3) * np.exp(-f)
        k = Matern52()(x)
        assert k[0, 1] == pytest.approx(expected)
        assert k[0, 0] == pytest.approx(1.0)

    def test_length_scale_and_cross(self):
        x1 = np.array([[0.0, 0.0]])
        x2 = np.array([[2.0, 2.0]])
        wide = RBF(length_scale=np.array([10.0]))(x1, x2)[0, 0]
        narrow = RBF(length_scale=np.array([0.5]))(x1, x2)[0, 0]
        assert wide > narrow  # larger scale → flatter kernel
        # ARD: per-dimension scales
        ard = RBF(length_scale=np.array([1.0, 1e6]))(x1, x2)[0, 0]
        assert ard == pytest.approx(np.exp(-0.5 * 4.0), rel=1e-3)

    def test_psd(self, rng):
        x = rng.normal(size=(12, 3))
        for kern in (RBF(), Matern52()):
            eigs = np.linalg.eigvalsh(kern(x))
            assert eigs.min() > -1e-8

    def test_log_param_round_trip(self):
        k = Matern52(length_scale=np.array([2.5]))
        k2 = k.with_params(k.get_params())
        assert np.allclose(k2.length_scale, [2.5])


class TestSliceSampler:
    def test_recovers_gaussian(self):
        logp = lambda x: -0.5 * float(np.sum((x - 1.5) ** 2) / 0.25)
        sampler = SliceSampler(
            logp, range_=(-10, 10), rng=np.random.default_rng(7)
        )
        x = np.zeros(1)
        draws = []
        for _ in range(600):
            x = sampler.draw(x)
            draws.append(x[0])
        draws = np.array(draws[100:])
        assert draws.mean() == pytest.approx(1.5, abs=0.1)
        assert draws.std() == pytest.approx(0.5, abs=0.12)

    def test_multidimensional(self):
        logp = lambda x: -0.5 * float(np.sum(x**2))
        sampler = SliceSampler(logp, rng=np.random.default_rng(3))
        x = sampler.draw(np.array([2.0, -2.0, 0.5]))
        assert x.shape == (3,)
        assert np.isfinite(logp(x))


class TestGaussianProcess:
    def test_regression_interpolates(self, rng):
        x = np.linspace(0, 2 * np.pi, 12)[:, None]
        y = np.sin(x[:, 0])
        est = GaussianProcessEstimator(
            kernel=Matern52(),
            normalize_labels=True,
            num_burn_in_samples=15,
            num_samples=15,
            rng=np.random.default_rng(0),
        )
        model = est.fit(x, y)
        xq = np.array([[1.0], [4.0]])
        mean, var = model.predict(xq)
        assert mean[0] == pytest.approx(np.sin(1.0), abs=0.15)
        assert mean[1] == pytest.approx(np.sin(4.0), abs=0.15)
        # variance at training points << variance far away
        _, var_train = model.predict(x[:1])
        _, var_far = model.predict(np.array([[20.0]]))
        assert var_train[0] < var_far[0]

    def test_log_likelihood_finite_and_peaked(self, rng):
        x = rng.normal(size=(8, 2))
        y = x[:, 0] * 0.5
        est = GaussianProcessEstimator(kernel=RBF())
        ll_good = est._log_likelihood(x, y, np.zeros(2))
        ll_bad = est._log_likelihood(x, y, np.full(2, -11.0))  # tiny scales
        assert np.isfinite(ll_good)
        assert ll_good > ll_bad


class TestCriteria:
    def test_expected_improvement(self):
        means = np.array([1.0, 2.0])
        variances = np.array([0.04, 0.04])
        ei = ExpectedImprovement(best_evaluation=1.5, larger_is_better=True)
        vals = ei(means, variances)
        assert vals[1] > vals[0]  # above best ≫ below best
        assert (vals >= 0).all()
        # minimizing flips the direction
        ei_min = ExpectedImprovement(best_evaluation=1.5, larger_is_better=False)
        vals_min = ei_min(means, variances)
        assert vals_min[0] > vals_min[1]

    def test_confidence_bound(self):
        means = np.array([1.0, 1.0])
        variances = np.array([0.0, 1.0])
        ucb = ConfidenceBound(larger_is_better=True)(means, variances)
        lcb = ConfidenceBound(larger_is_better=False)(means, variances)
        assert ucb[1] == pytest.approx(3.0)
        assert lcb[1] == pytest.approx(-1.0)
        assert ucb[0] == pytest.approx(1.0)


class TestSearch:
    def test_random_search_explores(self):
        fn = QuadraticEvalFn([0.5, 0.5])
        results = RandomSearch([(0, 1), (0, 1)], fn, seed=1).find(16)
        assert len(results) == 16
        pts = np.array([r[0] for r in results])
        assert pts.shape == (16, 2)
        assert (pts >= 0).all() and (pts <= 1).all()
        # Sobol coverage: both halves of each axis visited
        assert (pts[:, 0] < 0.5).any() and (pts[:, 0] > 0.5).any()

    def test_random_search_with_observations(self):
        fn = QuadraticEvalFn([0.0])
        seed_obs = [(np.array([0.3]), -0.09)]
        results = RandomSearch([(-1, 1)], fn, seed=2).find(3, seed_obs)
        assert len(results) == 3

    def test_gp_search_beats_random(self):
        """GP-guided search should concentrate later trials near the optimum."""
        target = [0.7, 0.3]
        fn = GaussianProcessSearch(
            [(0, 1), (0, 1)],
            QuadraticEvalFn(target),
            larger_is_better=True,
            candidate_pool_size=60,
            seed=5,
            num_mcmc_samples=8,
        )
        results = fn.find(12)
        evals = [r[1] for r in results]
        # best of the guided trials is close to optimal value 0
        assert max(evals) > -0.05
        assert fn.last_model is not None

    def test_gp_search_expected_improvement(self):
        fn = QuadraticEvalFn([0.4])
        search = GaussianProcessSearch(
            [(0, 1)], fn, larger_is_better=True, seed=9,
            candidate_pool_size=40, num_mcmc_samples=6, acquisition="EI",
        )
        results = search.find(8)
        assert max(r[1] for r in results) > -0.05

    def test_gp_search_minimize(self):
        fn = QuadraticEvalFn([0.5])

        class NegFn(QuadraticEvalFn):
            def __call__(self, h):
                v, r = QuadraticEvalFn.__call__(self, h)
                return -v, (r[0], -v)  # value = (x-t)^2, to minimize

        neg = NegFn([0.5])
        search = GaussianProcessSearch(
            [(0, 1)], neg, larger_is_better=False, seed=3,
            candidate_pool_size=40, num_mcmc_samples=6,
        )
        results = search.find(8)
        assert min(r[1] for r in results) < 0.02


class TestGameTuning:
    def test_vector_config_round_trip(self, rng):
        from photon_ml_tpu.data import RandomEffectDataConfiguration
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.estimators.tuning import GameEstimatorEvaluationFunction
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        l2 = lambda lam: GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=lam,
        )
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration(
                    feature_shard="global", optimizer=l2(10.0)
                ),
                "per_user": RandomEffectCoordinateConfiguration(
                    feature_shard="per_user",
                    data=RandomEffectDataConfiguration(random_effect_type="userId"),
                    optimizer=l2(1.0),
                ),
            },
        )
        fn = GameEstimatorEvaluationFunction(est, None, None)
        assert fn.num_params == 2
        vec = fn.configuration_to_vector(est.coordinate_configs)
        # sorted order: fixed, per_user → log10(10)=1, log10(1)=0
        assert vec == pytest.approx([1.0, 0.0])
        configs = fn.vector_to_configuration(np.array([2.0, -1.0]))
        assert configs["fixed"].optimizer.regularization_weight == pytest.approx(100.0)
        assert configs["per_user"].optimizer.regularization_weight == pytest.approx(0.1)

    def test_end_to_end_tuning_improves_bad_lambda(self, rng):
        """Random tuning from a terrible λ should find a better validation
        RMSE within a few trials (reference DriverTest hyperopt paths)."""
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.estimators.tuning import run_hyperparameter_tuning
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        n, d = 400, 10
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        y = X @ w + 0.05 * rng.normal(size=n).astype(np.float32)

        def coo(X):
            rows, cols = np.nonzero(X)
            return FeatureShard(rows=rows, cols=cols, vals=X[rows, cols], dim=d)

        data = GameData(labels=y[:300], feature_shards={"g": coo(X[:300])}, id_tags={})
        vdata = GameData(labels=y[300:], feature_shards={"g": coo(X[300:])}, id_tags={})

        bad = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1e4,  # crushes the model
        )
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("g", bad)},
        )
        base_fit = est.fit(data, validation_data=vdata)
        trials = run_hyperparameter_tuning(
            est, data, vdata, mode="RANDOM", num_iterations=4,
            log10_range=(-3.0, 1.0), prior_fits=[base_fit], seed=0,
        )
        assert len(trials) == 4
        best = min(t.value for t in trials)
        assert best < base_fit.validation_metric  # RMSE improved
