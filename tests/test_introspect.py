"""Live introspection tests: Prometheus text rendering (naming scheme,
sample types, summary quantiles) and the /metrics //healthz //varz
//quitquitquit HTTP endpoints on an ephemeral loopback port."""

import json
import urllib.error
import urllib.request

import pytest

from photon_ml_tpu.serving import IntrospectionServer, prometheus_text
from photon_ml_tpu.telemetry import MetricsRegistry


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode(), dict(resp.headers)


class TestPrometheusText:
    def test_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.count("jit.traces", 3)
        reg.gauge("serving.latency_p99_ms", 1.5)
        reg.gauge("serving.latency_p99_ms", 0.5)  # peak stays 1.5
        for v in range(100):
            reg.observe("solver.iterations_p99", float(v))
        text = prometheus_text(reg.snapshot())

        assert "# TYPE photon_jit_traces counter" in text
        assert "photon_jit_traces 3" in text
        # gauges: last value + a _peak companion
        assert "# TYPE photon_serving_latency_p99_ms gauge" in text
        assert "photon_serving_latency_p99_ms 0.5" in text
        assert "photon_serving_latency_p99_ms_peak 1.5" in text
        # histograms render as summaries with the three pinned quantiles
        assert "# TYPE photon_solver_iterations_p99 summary" in text
        for q in ("0.5", "0.95", "0.99"):
            assert f'photon_solver_iterations_p99{{quantile="{q}"}}' in text
        assert "photon_solver_iterations_p99_count 100" in text
        assert "photon_solver_iterations_p99_max 99" in text
        assert text.endswith("\n")

    def test_exposition_line_shape(self):
        """Every non-comment line is `name[{labels}] value` with a valid
        metric name — the curl-level format check CI runs."""
        import re

        reg = MetricsRegistry()
        reg.count("transfer.row_bytes_h2d", 1024)
        reg.gauge("mem.host_peak_rss_bytes", 2.5e9)
        reg.observe("lat", 0.25)
        name_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$"
        )
        for line in prometheus_text(reg.snapshot()).strip().splitlines():
            if line.startswith("#"):
                assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                                r"(counter|gauge|summary)$", line), line
            else:
                assert name_re.match(line), line

    def test_name_sanitization(self):
        reg = MetricsRegistry()
        reg.count("solver.per_user.buckets")
        reg.count("1weird-name!")
        text = prometheus_text(reg.snapshot())
        assert "photon_solver_per_user_buckets" in text
        # leading digit guarded, invalid chars replaced
        assert "photon__1weird_name_" in text

    def test_empty_snapshot(self):
        assert prometheus_text(MetricsRegistry().snapshot()) == "\n"

    def test_nonfinite_values(self):
        assert "NaN" in prometheus_text(
            {"counters": {"x": float("nan")}, "gauges": {}, "histograms": {}}
        )
        assert "+Inf" in prometheus_text(
            {"counters": {"x": float("inf")}, "gauges": {}, "histograms": {}}
        )


@pytest.fixture()
def server():
    reg = MetricsRegistry()
    reg.count("jit.traces", 2)
    reg.gauge("serving.num_requests", 42)
    state = {"healthy": True}
    srv = IntrospectionServer(
        registry=reg,
        varz=lambda: {"bucket_sizes": [1, 2, 4], "tuned": False},
        health=lambda: {"healthy": state["healthy"], "phase": "replaying"},
    ).start()
    yield srv, state, f"http://127.0.0.1:{srv.port}"
    srv.stop()


class TestEndpoints:
    def test_metrics_endpoint(self, server):
        _, _, base = server
        status, body, headers = _get(f"{base}/metrics")
        assert status == 200
        assert headers["Content-Type"] == (
            "text/plain; version=0.0.4; charset=utf-8"
        )
        assert "photon_jit_traces 2" in body
        assert "photon_serving_num_requests 42" in body

    def test_metrics_reflects_live_registry(self, server):
        srv, _, base = server
        srv.registry.gauge("serving.num_requests", 43)
        _, body, _ = _get(f"{base}/metrics")
        assert "photon_serving_num_requests 43" in body

    def test_healthz_flips_to_503(self, server):
        _, state, base = server
        status, body, _ = _get(f"{base}/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["healthy"] is True
        assert doc["phase"] == "replaying"
        state["healthy"] = False
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{base}/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read().decode())["healthy"] is False

    def test_varz_endpoint(self, server):
        _, _, base = server
        status, body, headers = _get(f"{base}/varz")
        assert status == 200
        assert headers["Content-Type"] == "application/json"
        assert json.loads(body) == {"bucket_sizes": [1, 2, 4],
                                    "tuned": False}

    def test_unknown_path_404(self, server):
        _, _, base = server
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{base}/nope")
        assert err.value.code == 404

    def test_quitquitquit_releases_hold(self, server):
        srv, _, base = server
        assert srv.wait_quit(timeout=0.01) is False
        status, _, _ = _get(f"{base}/quitquitquit")
        assert status == 200
        assert srv.wait_quit(timeout=5) is True

    def test_broken_handler_returns_500_not_crash(self):
        srv = IntrospectionServer(
            registry=MetricsRegistry(),
            varz=lambda: (_ for _ in ()).throw(RuntimeError("varz bug")),
        ).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/varz")
            assert err.value.code == 500
            # the server survives the endpoint bug
            status, _, _ = _get(f"{base}/metrics")
            assert status == 200
        finally:
            srv.stop()
