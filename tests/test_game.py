"""GAME / coordinate-descent tests, modeled on the reference's
GameEstimatorTest + CoordinateDescentTest + DriverTest structure: FE-only,
RE-only, FE+RE runs on synthetic GLMix data with metric thresholds, residual
algebra, best-model selection, scoring of unseen entities."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.data import RandomEffectDataConfiguration
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.evaluation import RMSE
from photon_ml_tpu.opt import GlmOptimizationConfiguration, RegularizationContext
from photon_ml_tpu.types import RegularizationType, TaskType

L2 = lambda lam: GlmOptimizationConfiguration(
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=lam,
)


def _glmix_problem(rng, n_users=20, rows_per_user=30, d_global=16, d_user=8, noise=0.1,
                   task="linear"):
    """y = x_g . w_fixed + x_u . w_user + noise — the canonical GLMix setup
    (global shard + per-user shard)."""
    n = n_users * rows_per_user
    Xg = rng.normal(size=(n, d_global)).astype(np.float32)
    w_fixed = rng.normal(size=d_global).astype(np.float32)
    user_ids = np.repeat([f"u{i:03d}" for i in range(n_users)], rows_per_user)
    Xu = rng.normal(size=(n, d_user)).astype(np.float32)
    w_users = {f"u{i:03d}": rng.normal(size=d_user).astype(np.float32) for i in range(n_users)}
    z = Xg @ w_fixed + np.array(
        [Xu[r] @ w_users[user_ids[r]] for r in range(n)], dtype=np.float32
    )
    if task == "linear":
        y = z + noise * rng.normal(size=n).astype(np.float32)
    else:
        y = (1 / (1 + np.exp(-z)) > rng.random(n)).astype(np.float32)

    def coo(X):
        rows, cols = np.nonzero(X)
        return FeatureShard(rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1])

    data = GameData(
        labels=y,
        feature_shards={"global": coo(Xg), "per_user": coo(Xu)},
        id_tags={"userId": user_ids},
    )
    return data, z


def test_fixed_effect_only(rng):
    data, z_true = _glmix_problem(rng, n_users=8, rows_per_user=40)
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
        },
    )
    fit = est.fit(data)
    scores = fit.model.score(data)
    # FE alone explains the global part; residual variance comes from RE part
    assert fit.objective_history[-1][1] < fit.objective_history[0][1] * 1.1
    assert np.corrcoef(scores, data.labels)[0, 1] > 0.5


def test_glmix_fe_plus_re_beats_fe_only(rng):
    """The KDD'16 GLMix claim in miniature: adding per-user random effects
    must cut validation RMSE well below the FE-only model."""
    data, _ = _glmix_problem(rng, n_users=20, rows_per_user=40)
    val, _ = _glmix_problem(rng, n_users=20, rows_per_user=40)
    # same users in validation: rebuild with the SAME per-user coefficients
    # -> easier: split one dataset 80/20
    n = data.num_rows
    perm = rng.permutation(n)
    tr, va = np.sort(perm[: int(0.8 * n)]), np.sort(perm[int(0.8 * n):])

    def subset(gd: GameData, idx):
        mask = np.zeros(n, dtype=bool)
        mask[idx] = True
        return GameData(
            labels=gd.labels[idx],
            feature_shards={k: s.slice_rows(mask) for k, s in gd.feature_shards.items()},
            id_tags={k: v[idx] for k, v in gd.id_tags.items()},
            offsets=gd.offsets[idx],
            weights=gd.weights[idx],
        )

    train, valid = subset(data, tr), subset(data, va)

    fe_only = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={"fixed": FixedEffectCoordinateConfiguration("global", L2(0.1))},
        evaluator=RMSE,
    ).fit(train, valid)

    glmix = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
            "per-user": RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId", num_buckets=2),
                optimizer=L2(1.0),
            ),
        },
        update_order=["fixed", "per-user"],
        num_outer_iterations=2,
        evaluator=RMSE,
    ).fit(train, valid)

    assert glmix.validation_metric < 0.6 * fe_only.validation_metric, (
        glmix.validation_metric,
        fe_only.validation_metric,
    )


def test_training_objective_decreases_across_coordinates(rng):
    data, _ = _glmix_problem(rng, n_users=10, rows_per_user=30)
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
            "per-user": RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId"),
                optimizer=L2(1.0),
            ),
        },
        num_outer_iterations=3,
    )
    fit = est.fit(data)
    objs = [v for _, v in fit.objective_history]
    assert objs[-1] <= objs[0]
    # CD must be (near-)monotone: allow tiny numeric wiggle
    for a, b in zip(objs, objs[1:]):
        assert b <= a * 1.01 + 1e-3, fit.objective_history


def test_scoring_unseen_entities_fall_back_to_fixed_effect(rng):
    data, _ = _glmix_problem(rng, n_users=10, rows_per_user=30)
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
            "per-user": RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId"),
                optimizer=L2(1.0),
            ),
        },
    )
    fit = est.fit(data)
    # new data with unseen users: RE contribution must be exactly 0
    n_new = 50
    d_g = data.feature_shards["global"].dim
    d_u = data.feature_shards["per_user"].dim
    Xg = rng.normal(size=(n_new, d_g)).astype(np.float32)
    Xu = rng.normal(size=(n_new, d_u)).astype(np.float32)

    def coo(X):
        rows, cols = np.nonzero(X)
        return FeatureShard(rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1])

    new_data = GameData(
        labels=np.zeros(n_new, dtype=np.float32),
        feature_shards={"global": coo(Xg), "per_user": coo(Xu)},
        id_tags={"userId": np.array(["unseen"] * n_new)},
    )
    re_scores = fit.model.score_coordinate("per-user", new_data)
    np.testing.assert_array_equal(re_scores, 0.0)
    fe_scores = fit.model.score_coordinate("fixed", new_data)
    total = fit.model.score(new_data)
    np.testing.assert_allclose(total, fe_scores, rtol=1e-6)


def test_best_model_tracking_with_validation(rng):
    data, _ = _glmix_problem(rng, n_users=10, rows_per_user=30)
    n = data.num_rows
    mask = np.zeros(n, dtype=bool)
    mask[: n // 5] = True

    val = GameData(
        labels=data.labels[mask],
        feature_shards={k: s.slice_rows(mask) for k, s in data.feature_shards.items()},
        id_tags={k: v[mask] for k, v in data.id_tags.items()},
    )
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
        },
        evaluator=RMSE,
        num_outer_iterations=2,
    )
    fit = est.fit(data, val)
    assert fit.validation_metric is not None
    # best metric is the min over history (RMSE: smaller is better)
    hist = [v for _, v in fit.validation_history]
    assert fit.validation_metric == pytest.approx(min(hist))


def test_fit_multiple_configs_and_best_selection(rng):
    """Reference GameEstimator.fit over Seq[GameModelOptimizationConfiguration]
    (GameEstimator.scala:175-217) + Driver.scala:356 selectBestModel: one
    model per config, best chosen by the validation evaluator. A crushing λ
    must lose to a reasonable λ."""
    data, _ = _glmix_problem(rng, n_users=12, rows_per_user=40)
    n = data.num_rows
    mask = np.zeros(n, dtype=bool)
    mask[: n // 5] = True
    val = GameData(
        labels=data.labels[mask],
        feature_shards={k: s.slice_rows(mask) for k, s in data.feature_shards.items()},
        id_tags={k: v[mask] for k, v in data.id_tags.items()},
    )
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.1)),
            "per-user": RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId"),
                optimizer=L2(1.0),
            ),
        },
        update_order=["fixed", "per-user"],
        evaluator=RMSE,
    )
    # cross-product style sweep: fixed λ in {0.01, 1e6}
    configs = [{"fixed": L2(0.01)}, {"fixed": L2(1e6)}]
    fits = est.fit_multiple(data, val, configs=configs)
    assert len(fits) == 2
    assert all(f.validation_metric is not None for f in fits)
    best = est.select_best_fit(fits)
    assert best == 0, [f.validation_metric for f in fits]
    # the crushed model must actually be worse (RMSE: larger)
    assert fits[1].validation_metric > fits[0].validation_metric

    # unknown coordinate ids fail fast
    with pytest.raises(ValueError, match="unknown coordinates"):
        est.fit_multiple(data, val, configs=[{"nope": L2(1.0)}])

    # no validation data -> no metric -> no selection (reference
    # reduceOption on empty evaluations)
    fits_nv = est.fit_multiple(data, configs=[{"fixed": L2(0.01)}])
    assert est.select_best_fit(fits_nv) is None


def test_objective_decomposition_and_model_summaries(rng, caplog):
    """The CD loop logs loss + regularization = objective per coordinate
    update (reference CoordinateDescent.scala:247-258), and every model /
    dataset exposes a toSummaryString equivalent."""
    import logging
    import re

    data, _ = _glmix_problem(rng, n_users=8, rows_per_user=30)
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.5)),
            "per-user": RandomEffectCoordinateConfiguration(
                "per_user",
                data=RandomEffectDataConfiguration("userId", num_buckets=1),
                optimizer=L2(1.0),
            ),
        },
        update_order=["fixed", "per-user"],
    )
    with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
        fit = est.fit(data)
    decomp = re.findall(
        r"loss ([\d.eE+-]+) \+ regularization ([\d.eE+-]+) = objective "
        r"([\d.eE+-]+)",
        caplog.text,
    )
    assert len(decomp) >= 2  # one per coordinate update
    for loss_s, reg_s, obj_s in decomp:
        assert abs(float(loss_s) + float(reg_s) - float(obj_s)) < 1e-4
    # a trained model has nonzero coefficients -> positive L2 term
    assert float(decomp[-1][1]) > 0
    # the history stores the SAME objective the log line names (loss + reg)
    assert abs(fit.objective_history[-1][1] - float(decomp[-1][2])) < 1e-4
    # dataset summary logged at build time (RandomEffectDataSet.scala:204-228)
    assert "random-effect dataset 'userId'" in caplog.text
    assert "active samples" in caplog.text

    s = fit.model.to_summary_string()
    assert "GAME model" in s and "[fixed]" in s and "[per-user]" in s
    assert "GLM" in s and "random effect 'userId'" in s


def test_sweep_override_weights_in_objective_decomposition(rng, caplog):
    """fit_multiple's logged loss+regularization decomposition must use the
    SWEPT configuration's lambda, not the estimator's base config."""
    import logging
    import re

    data, _ = _glmix_problem(rng, n_users=6, rows_per_user=25)
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("global", L2(0.5)),
        },
    )
    with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
        fits = est.fit_multiple(data, configs=[{"fixed": L2(0.0)}])
    assert len(fits) == 1
    decomp = re.findall(
        r"loss [\d.eE+-]+ \+ regularization ([\d.eE+-]+) = objective",
        caplog.text,
    )
    assert decomp, caplog.text
    # lambda=0 trained this fit: the logged regularization term must be 0
    # (the base config's 0.5 would give a clearly positive term)
    assert float(decomp[-1]) == 0.0
