"""Threaded native radix argsort (utils/nativesort.py) vs numpy ground truth.

The native path must match np.lexsort/np.argsort EXACTLY (including
stability of ties) — the routing layouts built on top of it encode slot
positions from rank arithmetic, so any ordering difference corrupts plans.
"""

import numpy as np
import pytest

from photon_ml_tpu.utils import nativesort
from photon_ml_tpu.utils.nativesort import lexsort_pairs


@pytest.fixture
def native_available():
    if nativesort._load_native() is None:
        pytest.skip("native sortperm unavailable (no toolchain)")


class TestLexsortPairs:
    @pytest.mark.parametrize(
        "n,hi_max,lo_max",
        [
            (1 << 16, 1 << 20, 1 << 10),   # packed path
            (1 << 17, 100, 100),           # heavy ties (stability)
            (1 << 16, 1, 1),               # all-equal keys
            (1 << 17, 1 << 40, 1 << 33),   # wide keys -> indirect fallback
            (70000, 7, 1 << 31),           # tiny major, wide minor
        ],
    )
    def test_matches_numpy(self, rng, native_available, n, hi_max, lo_max):
        hi = rng.integers(0, hi_max, n)
        lo = rng.integers(0, lo_max, n)
        assert np.array_equal(lexsort_pairs(hi, lo), np.lexsort((lo, hi)))

    def test_single_key(self, rng, native_available):
        k = rng.integers(0, 1 << 24, 1 << 17)
        assert np.array_equal(lexsort_pairs(k), np.argsort(k, kind="stable"))

    def test_small_input_uses_numpy(self, rng):
        # below the native threshold the numpy path runs; same contract
        hi = rng.integers(0, 50, 1000)
        lo = rng.integers(0, 50, 1000)
        assert np.array_equal(lexsort_pairs(hi, lo), np.lexsort((lo, hi)))

    def test_negative_keys_fall_back(self, rng):
        hi = rng.integers(-100, 100, 1 << 17)
        lo = rng.integers(0, 100, 1 << 17)
        assert np.array_equal(lexsort_pairs(hi, lo), np.lexsort((lo, hi)))

    def test_empty(self):
        assert lexsort_pairs(np.array([], dtype=np.int64)).size == 0
