"""Event-listener test double, importable by dotted name from
--event-listeners (must live outside the test module so the driver's
importlib load and the test share one module object)."""

from photon_ml_tpu.event import Event, EventListener


class CollectingListener(EventListener):
    received = []  # class-level on purpose: the driver instantiates the class
    closed = 0

    def on_event(self, event: Event) -> None:
        CollectingListener.received.append(event)

    def close(self) -> None:
        CollectingListener.closed += 1


class FailingListener(EventListener):
    """Raises on every event AND on close: drivers must isolate listener
    failures (run to completion, count them on ``emitter.listener_errors``)."""

    raised = 0

    def on_event(self, event: Event) -> None:
        FailingListener.raised += 1
        raise RuntimeError("listener boom")

    def close(self) -> None:
        raise RuntimeError("close boom")


NOT_A_LISTENER = object()  # register_listener_class must reject non-classes
