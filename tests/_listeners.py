"""Event-listener test double, importable by dotted name from
--event-listeners (must live outside the test module so the driver's
importlib load and the test share one module object)."""

from photon_ml_tpu.event import Event, EventListener


class CollectingListener(EventListener):
    received = []  # class-level on purpose: the driver instantiates the class
    closed = 0

    def on_event(self, event: Event) -> None:
        CollectingListener.received.append(event)

    def close(self) -> None:
        CollectingListener.closed += 1
