"""IO tests: Avro codec round trips (incl. binary-format invariants), data
reader feature-bag merging, GAME model save/load scoring equivalence, score
persistence — modeled on the reference's AvroUtilsTest /
ModelProcessingUtilsTest / AvroDataReaderTest / ScoreProcessingUtilsTest."""

import io as _io
import json
import os

import numpy as np
import pytest

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro import (
    AvroSchema,
    _Reader,
    _encode,
    read_avro_file,
    write_avro_file,
)


class TestAvroCodec:
    def test_zigzag_varint_spec_values(self):
        """Byte-level spec conformance: zigzag(-1)=1, zigzag(1)=2, 64→0x80 0x01."""
        for value, expected in [(0, b"\x00"), (-1, b"\x01"), (1, b"\x02"),
                                (-2, b"\x03"), (2, b"\x04"), (64, b"\x80\x01"),
                                (-64, b"\x7f")]:
            buf = _io.BytesIO()
            _encode(buf, "long", value)
            assert buf.getvalue() == expected, value
            assert _Reader(buf.getvalue()).read_long() == value

    def test_primitive_round_trip(self):
        schema = AvroSchema(
            {
                "type": "record",
                "name": "T",
                "fields": [
                    {"name": "s", "type": "string"},
                    {"name": "d", "type": "double"},
                    {"name": "f", "type": "float"},
                    {"name": "i", "type": "int"},
                    {"name": "l", "type": "long"},
                    {"name": "b", "type": "boolean"},
                    {"name": "y", "type": "bytes"},
                    {"name": "u", "type": ["null", "string"]},
                    {"name": "a", "type": {"type": "array", "items": "double"}},
                    {"name": "m", "type": {"type": "map", "values": "string"}},
                ],
            }
        )
        rec = {
            "s": "hélloworld", "d": -1.5e300, "f": 0.25, "i": -123456,
            "l": 2**60, "b": True, "y": b"\x00\xff", "u": None,
            "a": [1.0, -2.5], "m": {"k1": "v1", "k2": "v2"},
        }
        buf = _io.BytesIO()
        _encode(buf, schema.root, rec)
        out = _Reader(buf.getvalue())
        from photon_ml_tpu.io.avro import _decode

        got = _decode(out, schema.root)
        assert got["s"] == rec["s"]
        assert got["d"] == rec["d"]
        assert got["f"] == pytest.approx(0.25)
        assert got["i"] == rec["i"] and got["l"] == rec["l"]
        assert got["y"] == rec["y"] and got["u"] is None
        assert got["a"] == rec["a"] and got["m"] == rec["m"]

    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_container_file_round_trip(self, tmp_path, codec):
        schema = schemas.training_example_schema()
        records = [
            {
                "uid": f"u{i}",
                "label": float(i % 2),
                "features": [
                    {"name": "f", "term": str(j), "value": i + 0.5 * j}
                    for j in range(i % 4)
                ],
                "metadataMap": {"userId": f"user{i}"},
                "weight": 1.0 + i,
                "offset": None,
            }
            for i in range(257)
        ]
        path = str(tmp_path / "data.avro")
        n = write_avro_file(path, schema, records, codec=codec,
                            sync_interval=1024)  # force multiple blocks
        assert n == 257
        got = list(read_avro_file(path))
        assert len(got) == 257
        assert got[3]["uid"] == "u3"
        assert got[3]["features"][1]["value"] == pytest.approx(3.5)
        assert got[10]["metadataMap"]["userId"] == "user10"
        assert got[0]["offset"] is None

    def test_defaults_fill_missing_fields(self, tmp_path):
        path = str(tmp_path / "d.avro")
        write_avro_file(
            path, schemas.training_example_schema(),
            [{"label": 1.0, "features": []}],
        )
        (rec,) = read_avro_file(path)
        assert rec["uid"] is None and rec["weight"] is None

    def test_named_types_defined_once_in_emitted_schema(self):
        """Spec parsers reject duplicate named-type definitions; the second
        NameTermValueAvro occurrence must be a name reference."""
        js = schemas.bayesian_linear_model_schema().to_json()
        assert js.count('"name": "NameTermValueAvro"') <= 1
        # and the emitted JSON must round-trip through our own parser
        AvroSchema(js)

    def test_explicit_zero_weight_preserved(self, tmp_path):
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
            write_training_examples,
        )

        path = str(tmp_path / "w.avro")
        write_training_examples(
            path,
            [
                {"label": 1.0, "features": [("f", "", 1.0)], "weight": 0.0},
                {"label": 0.0, "features": [("f", "", 1.0)]},
            ],
        )
        data, _, _ = read_game_data(
            path, {"g": FeatureShardConfiguration(["features"], add_intercept=False)}
        )
        assert data.weights[0] == 0.0
        assert data.weights[1] == 1.0

    def test_schema_resolution_evolved_reader(self, tmp_path):
        """Avro spec schema resolution: reader with added (defaulted),
        removed, reordered, and promoted fields reads old files."""
        writer = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "a", "type": "int"},
                {"name": "gone", "type": "string"},
                {"name": "b", "type": ["null", "string"], "default": None},
            ],
        })
        reader = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "b", "type": ["null", "string"], "default": None},
                {"name": "a", "type": "double"},              # int -> double
                {"name": "added", "type": "long", "default": 7},
                {"name": "tags", "type": {"type": "array", "items": "string"},
                 "default": []},
            ],
        })
        path = str(tmp_path / "old.avro")
        write_avro_file(path, writer, [
            {"a": 3, "gone": "x", "b": "hello"},
            {"a": -1, "gone": "y", "b": None},
        ])
        got = list(read_avro_file(path, reader))
        assert got[0] == {"b": "hello", "a": 3.0, "added": 7, "tags": []}
        assert got[1] == {"b": None, "a": -1.0, "added": 7, "tags": []}
        assert isinstance(got[0]["a"], float)
        # container defaults must be fresh per record (mutating one record
        # must not leak into siblings or the schema)
        got[0]["tags"].append("oops")
        assert got[1]["tags"] == []
        assert list(read_avro_file(path, reader))[0]["tags"] == []

        # same schema -> fast path (no resolution), identical result
        same = list(read_avro_file(path, writer))
        assert same[0]["gone"] == "x"

    def test_schema_resolution_union_narrowing(self, tmp_path):
        """Narrowing ['null','string'] -> 'string' reads files whose data
        never used the removed branch; a datum that does use it raises."""
        writer = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "s", "type": ["null", "string"], "default": None},
            ],
        })
        reader = AvroSchema({
            "type": "record", "name": "Rec",
            "fields": [{"name": "s", "type": "string"}],
        })
        ok_path = str(tmp_path / "ok.avro")
        write_avro_file(ok_path, writer, [{"s": "x"}, {"s": "y"}])
        assert [r["s"] for r in read_avro_file(ok_path, reader)] == ["x", "y"]

        bad_path = str(tmp_path / "bad.avro")
        write_avro_file(bad_path, writer, [{"s": None}])
        with pytest.raises(ValueError, match="null"):
            list(read_avro_file(bad_path, reader))

    def test_schema_resolution_enum_default_symbol(self, tmp_path):
        """Avro spec (1.9+): a writer enum symbol unknown to the reader
        resolves to the reader's declared default symbol; without one it
        stays an error."""
        writer = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "e", "type": {
                    "type": "enum", "name": "Color",
                    "symbols": ["RED", "TEAL", "BLUE"],
                }},
            ],
        })
        reader_with_default = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "e", "type": {
                    "type": "enum", "name": "Color",
                    "symbols": ["RED", "BLUE", "OTHER"],
                    "default": "OTHER",
                }},
            ],
        })
        reader_no_default = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "e", "type": {
                    "type": "enum", "name": "Color",
                    "symbols": ["RED", "BLUE"],
                }},
            ],
        })
        path = str(tmp_path / "enum.avro")
        write_avro_file(path, writer, [{"e": "RED"}, {"e": "TEAL"}])
        got = [r["e"] for r in read_avro_file(path, reader_with_default)]
        assert got == ["RED", "OTHER"]
        with pytest.raises(ValueError, match="TEAL"):
            list(read_avro_file(path, reader_no_default))

    def test_schema_resolution_missing_default_raises(self, tmp_path):
        writer = AvroSchema({
            "type": "record", "name": "Rec",
            "fields": [{"name": "a", "type": "int"}],
        })
        reader = AvroSchema({
            "type": "record", "name": "Rec", "fields": [
                {"name": "a", "type": "int"},
                {"name": "required_new", "type": "string"},  # no default
            ],
        })
        path = str(tmp_path / "old.avro")
        write_avro_file(path, writer, [{"a": 1}])
        with pytest.raises(ValueError, match="required_new"):
            list(read_avro_file(path, reader))

    def test_schema_resolution_name_mismatch_raises(self, tmp_path):
        writer = AvroSchema({
            "type": "record", "name": "Rec",
            "fields": [{"name": "a", "type": "int"}],
        })
        other = AvroSchema({
            "type": "record", "name": "Other",
            "fields": [{"name": "a", "type": "int"}],
        })
        path = str(tmp_path / "old.avro")
        write_avro_file(path, writer, [{"a": 1}])
        with pytest.raises(ValueError, match="Rec"):
            list(read_avro_file(path, other))

    def test_corrupt_sync_marker_detected(self, tmp_path):
        path = str(tmp_path / "d.avro")
        write_avro_file(path, schemas.scoring_result_schema(),
                        [{"modelId": "m", "predictionScore": 1.0}])
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF  # flip a bit in the trailing sync marker
        open(path, "wb").write(raw)
        with pytest.raises(ValueError, match="sync"):
            list(read_avro_file(path))


class TestDataReader:
    def _write_fixture(self, tmp_path):
        from photon_ml_tpu.io.data_reader import write_training_examples

        records = []
        rng = np.random.default_rng(0)
        for i in range(40):
            records.append(
                {
                    "uid": f"uid{i}",
                    "label": float(i % 2),
                    "features": [("g", str(j), float(rng.normal())) for j in range(3)],
                    "userFeatures": [("u", "0", float(rng.normal()))],
                    "metadataMap": {"userId": f"user{i % 5}"},
                    "weight": 2.0,
                    "offset": 0.25,
                }
            )
        path = str(tmp_path / "train.avro")
        write_training_examples(path, records)
        return path

    def test_read_merged_shards(self, tmp_path):
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )

        path = self._write_fixture(tmp_path)
        data, index_maps, uids = read_game_data(
            path,
            {
                "global": FeatureShardConfiguration(
                    feature_bags=["features", "userFeatures"], add_intercept=True
                ),
                "per_user": FeatureShardConfiguration(
                    feature_bags=["userFeatures"], add_intercept=False
                ),
            },
            id_tags=["userId"],
        )
        assert data.num_rows == 40
        # global shard: 3 g-features + 1 u-feature + intercept
        assert len(index_maps["global"]) == 5
        assert len(index_maps["per_user"]) == 1
        assert data.feature_shards["global"].dim == 5
        # every row has an intercept nonzero in the global shard
        g = data.feature_shards["global"]
        from photon_ml_tpu.indexmap import INTERCEPT_KEY

        icpt = index_maps["global"].get_index(INTERCEPT_KEY)
        assert (g.cols == icpt).sum() == 40
        assert data.weights[0] == pytest.approx(2.0)
        assert data.offsets[0] == pytest.approx(0.25)
        assert list(data.id_tags["userId"][:5]) == [
            "user0", "user1", "user2", "user3", "user4"
        ]
        assert uids[7] == "uid7"

    def test_fixed_index_map_drops_unknown(self, tmp_path):
        from photon_ml_tpu.indexmap import DefaultIndexMap, feature_key
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )

        path = self._write_fixture(tmp_path)
        imap = DefaultIndexMap({feature_key("g", "0"): 0})
        data, _, _ = read_game_data(
            path,
            {"global": FeatureShardConfiguration(["features"], add_intercept=False)},
            index_maps={"global": imap},
        )
        assert data.feature_shards["global"].dim == 1
        assert set(data.feature_shards["global"].cols) == {0}


class TestModelIO:
    def _train_small_game(self, rng):
        from photon_ml_tpu.data import RandomEffectDataConfiguration
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.types import TaskType

        n_users, rows, dg, du = 6, 25, 8, 4
        n = n_users * rows
        Xg = rng.normal(size=(n, dg)).astype(np.float32)
        Xu = rng.normal(size=(n, du)).astype(np.float32)
        users = np.repeat([f"user{i}" for i in range(n_users)], rows)
        wg = rng.normal(size=dg).astype(np.float32)
        wu = {f"user{i}": rng.normal(size=du).astype(np.float32) for i in range(n_users)}
        y = Xg @ wg + np.array([Xu[i] @ wu[users[i]] for i in range(n)], np.float32)

        def coo(X):
            r, c = np.nonzero(X)
            return FeatureShard(rows=r, cols=c, vals=X[r, c], dim=X.shape[1])

        data = GameData(
            labels=y,
            feature_shards={"g": coo(Xg), "u": coo(Xu)},
            id_tags={"userId": users},
        )
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration("g"),
                "per_user": RandomEffectCoordinateConfiguration(
                    "u", RandomEffectDataConfiguration(random_effect_type="userId")
                ),
            },
        )
        return est.fit(data).model, data

    def test_save_load_with_zero_coefficients(self, tmp_path, rng):
        """Sparse model storage drops zero coefficients; reload must keep
        both the POSITIONS of the survivors (no-map loads previously
        renumbered by encounter order, silently permuting whenever any
        interior coefficient was zero) and the DIMENSION (a trailing zero
        previously shrank the model)."""
        from photon_ml_tpu.io.model_io import load_game_model, save_game_model
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.models.game import CoordinateMeta, GameModel
        from photon_ml_tpu.models.glm import GeneralizedLinearModel
        from photon_ml_tpu.types import TaskType
        import jax.numpy as jnp

        w = np.array([1.5, 0.0, -2.0, 0.0, 3.0, 0.0], dtype=np.float32)
        model = GameModel(
            models={
                "fixed": GeneralizedLinearModel(
                    coefficients=Coefficients(means=jnp.asarray(w)),
                    task=TaskType.LINEAR_REGRESSION,
                )
            },
            meta={"fixed": CoordinateMeta(feature_shard="g")},
            task=TaskType.LINEAR_REGRESSION,
        )
        out = str(tmp_path / "model")
        save_game_model(model, out)
        loaded, _ = load_game_model(out)
        got = np.asarray(loaded.models["fixed"].coefficients.means)
        np.testing.assert_array_equal(got, w)  # positions AND dim preserved

    def test_id_info_is_arity_exact_for_reference_loader(self, tmp_path, rng):
        """The reference destructures id-info with exact arity (1 line for
        fixed-effect, 2 for random-effect — ModelProcessingUtils.scala:156/
        182); any extra line throws scala.MatchError there. dim/positional
        facts must live in model-metadata.json instead."""
        import json
        from photon_ml_tpu.io.model_io import save_game_model

        model, _ = self._train_small_game(rng)
        out = str(tmp_path / "model")
        save_game_model(model, out)
        with open(os.path.join(out, "fixed-effect", "fixed", "id-info")) as f:
            assert f.read().split() == ["g"]
        with open(os.path.join(out, "random-effect", "per_user", "id-info")) as f:
            assert f.read().split() == ["userId", "u"]
        with open(os.path.join(out, "model-metadata.json")) as f:
            md = json.load(f)
        assert md["featureShards"]["g"]["dim"] == 8
        assert md["featureShards"]["u"]["dim"] == 4
        assert md["featureShards"]["g"]["positional"] is True

    def test_load_legacy_id_info_tokens(self, tmp_path, rng):
        """Models saved by the round-3 writer carried dim=N /
        names=positional as extra id-info tokens; the loader still honors
        them when metadata lacks featureShards."""
        import json
        from photon_ml_tpu.io.model_io import load_game_model, save_game_model
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.models.game import CoordinateMeta, GameModel
        from photon_ml_tpu.models.glm import GeneralizedLinearModel
        from photon_ml_tpu.types import TaskType
        import jax.numpy as jnp

        w = np.array([0.0, 2.5, 0.0, -1.0, 0.0], dtype=np.float32)
        model = GameModel(
            models={
                "fixed": GeneralizedLinearModel(
                    coefficients=Coefficients(means=jnp.asarray(w)),
                    task=TaskType.LINEAR_REGRESSION,
                )
            },
            meta={"fixed": CoordinateMeta(feature_shard="g")},
            task=TaskType.LINEAR_REGRESSION,
        )
        out = str(tmp_path / "model")
        save_game_model(model, out)
        # Rewrite artifacts into the legacy round-3 shape.
        md_path = os.path.join(out, "model-metadata.json")
        with open(md_path) as f:
            md = json.load(f)
        del md["featureShards"]
        with open(md_path, "w") as f:
            json.dump(md, f)
        with open(os.path.join(out, "fixed-effect", "fixed", "id-info"), "w") as f:
            f.write("g\ndim=5\nnames=positional\n")
        loaded, _ = load_game_model(out)
        got = np.asarray(loaded.models["fixed"].coefficients.means)
        np.testing.assert_array_equal(got, w)

    def test_save_load_scoring_equivalence(self, tmp_path, rng):
        from photon_ml_tpu.io.model_io import (
            load_game_model,
            load_game_model_metadata,
            save_game_model,
        )

        model, data = self._train_small_game(rng)
        out = str(tmp_path / "model")
        save_game_model(model, out)
        # layout
        assert os.path.isfile(os.path.join(out, "model-metadata.json"))
        assert os.path.isfile(
            os.path.join(out, "fixed-effect", "fixed", "id-info")
        )
        assert os.path.isfile(
            os.path.join(out, "fixed-effect", "fixed", "coefficients", "part-00000.avro")
        )
        assert os.path.isfile(
            os.path.join(out, "random-effect", "per_user", "id-info")
        )
        md = load_game_model_metadata(out)
        assert md["modelType"] == "LINEAR_REGRESSION"

        loaded, maps = load_game_model(out)
        s0 = model.score(data)
        s1 = loaded.score(data)
        np.testing.assert_allclose(s0, s1, rtol=1e-5, atol=1e-5)

    def test_save_load_with_index_maps_round_trip(self, tmp_path, rng):
        """With real feature-name index maps, names survive the round trip
        (reference: model files keyed by name+term, not position)."""
        from photon_ml_tpu.indexmap import DefaultIndexMap, feature_key
        from photon_ml_tpu.io.avro import read_avro_file
        from photon_ml_tpu.io.model_io import load_game_model, save_game_model

        model, data = self._train_small_game(rng)
        g_map = DefaultIndexMap(
            {feature_key("g", str(i)): i for i in range(8)}
        )
        u_map = DefaultIndexMap(
            {feature_key("u", str(i)): i for i in range(4)}
        )
        out = str(tmp_path / "model")
        save_game_model(model, out, index_maps={"g": g_map, "u": u_map})
        part = os.path.join(out, "fixed-effect", "fixed", "coefficients",
                            "part-00000.avro")
        (rec,) = read_avro_file(part)
        names = {(m["name"], m["term"]) for m in rec["means"]}
        assert ("g", "3") in names
        assert rec["modelClass"].endswith("LinearRegressionModel")

        loaded, _ = load_game_model(out, index_maps={"g": g_map, "u": u_map})
        np.testing.assert_allclose(
            model.score(data), loaded.score(data), rtol=1e-5, atol=1e-5
        )

    def test_matrix_factorization_round_trip(self, tmp_path, rng):
        from photon_ml_tpu.io.model_io import (
            load_matrix_factorization_model,
            save_matrix_factorization_model,
        )
        from photon_ml_tpu.models.matrix_factorization import (
            MatrixFactorizationModel,
        )

        m = MatrixFactorizationModel(
            row_effect_type="userId",
            col_effect_type="itemId",
            row_factors=rng.normal(size=(5, 3)).astype(np.float32),
            col_factors=rng.normal(size=(7, 3)).astype(np.float32),
            row_index={f"u{i}": i for i in range(5)},
            col_index={f"i{j}": j for j in range(7)},
        )
        out = str(tmp_path / "mf")
        save_matrix_factorization_model(m, out)
        loaded = load_matrix_factorization_model(out, "userId", "itemId")
        assert loaded.score("u2", "i3") == pytest.approx(m.score("u2", "i3"), rel=1e-6)
        np.testing.assert_allclose(loaded.row_factors, m.row_factors)


class TestScoresIO:
    def test_round_trip(self, tmp_path):
        from photon_ml_tpu.io.scores_io import ScoredItem, load_scores, save_scores

        items = [
            ScoredItem(prediction_score=0.9, label=1.0, weight=2.0, uid="a",
                       id_tags={"userId": "u1"}),
            ScoredItem(prediction_score=-0.1),
        ]
        out = str(tmp_path / "scores")
        n = save_scores(out, items, model_id="my-model")
        assert n == 2
        got = list(load_scores(out))
        assert got[0].prediction_score == pytest.approx(0.9)
        assert got[0].id_tags == {"userId": "u1"}
        assert got[1].label is None and got[1].uid is None

    def test_flush_cap_round_trip(self, tmp_path):
        """Writing more items than one file's flush cap must roll over to
        new part files without dropping/duplicating records; ids and scores
        reload exactly and in order."""
        from photon_ml_tpu.io.scores_io import ScoredItem, load_scores, save_scores

        cap = 7
        n_items = 3 * cap + 2  # crosses the cap boundary three times
        items = [
            ScoredItem(prediction_score=float(i) / 8.0, uid=f"uid-{i:03d}")
            for i in range(n_items)
        ]
        out = str(tmp_path / "scores")
        n = save_scores(out, items, model_id="m", records_per_file=cap)
        assert n == n_items
        parts = sorted(f for f in os.listdir(out) if f.endswith(".avro"))
        assert len(parts) == 4  # 7 + 7 + 7 + 2
        got = list(load_scores(out))
        assert [g.uid for g in got] == [f"uid-{i:03d}" for i in range(n_items)]
        np.testing.assert_array_equal(
            np.array([g.prediction_score for g in got], dtype=np.float32),
            np.array([i / 8.0 for i in range(n_items)], dtype=np.float32),
        )
