"""Decoded block cache: robustness, invalidation, and the warm-epoch
zero-decode contract.

The cache is a correctness-critical fast path — a wrong cache silently
trains a wrong model — so the gate here is bitwise equality between a
cached reload and a fresh decode, plus fallback-to-decode on every way an
entry can be bad (truncated, corrupted, stale fingerprint), plus exactly
one valid entry surviving concurrent writers.
"""

import glob
import os
import threading

import numpy as np
import pytest

from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    build_index_maps,
    write_training_examples,
)
from photon_ml_tpu.streaming import BlockCache, StreamingSource, plan_fingerprint

FILE_ROWS = (96, 80)
N_ROWS = sum(FILE_ROWS)
D = 6
BLOCK_ROWS = 48  # 176 rows -> 4 blocks, final one ragged (32 real rows)

SHARDS = {
    "global": FeatureShardConfiguration(
        feature_bags=("features",), add_intercept=True
    ),
}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    rng = np.random.default_rng(7)
    root = tmp_path_factory.mktemp("blkcache")
    X = rng.normal(size=(N_ROWS, D)).astype(np.float32)
    y = (rng.random(N_ROWS) > 0.5).astype(np.float32)
    paths = []
    row = 0
    for fi, n in enumerate(FILE_ROWS):
        recs = []
        for i in range(row, row + n):
            recs.append({
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0,
                "features": [("g", str(j), float(X[i, j])) for j in range(D)],
                "metadataMap": {"userId": f"u{i % 5}"},
            })
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    index_maps = build_index_maps(paths, SHARDS)
    return {"paths": paths, "index_maps": index_maps}


def _open_source(dataset, cache_dir=None):
    return StreamingSource.open(
        dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
        block_rows=BLOCK_ROWS, id_tags=("userId",), cache_dir=cache_dir,
    )


def _assert_blocks_equal(a, b):
    assert a.index == b.index
    assert a.start == b.start
    assert a.num_real == b.num_real
    np.testing.assert_array_equal(np.asarray(a.labels), np.asarray(b.labels))
    np.testing.assert_array_equal(np.asarray(a.offsets), np.asarray(b.offsets))
    np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
    assert set(a.shards) == set(b.shards)
    for sid in a.shards:
        np.testing.assert_array_equal(
            np.asarray(a.shards[sid][0]), np.asarray(b.shards[sid][0])
        )
        np.testing.assert_array_equal(
            np.asarray(a.shards[sid][1]), np.asarray(b.shards[sid][1])
        )
        assert np.asarray(a.shards[sid][0]).dtype == np.asarray(b.shards[sid][0]).dtype
        assert np.asarray(a.shards[sid][1]).dtype == np.asarray(b.shards[sid][1]).dtype
    assert set(a.id_tags) == set(b.id_tags)
    for t in a.id_tags:
        assert list(a.id_tags[t]) == list(b.id_tags[t])


def _entry_files(cache):
    return sorted(glob.glob(os.path.join(cache.dir, "block-*.blk")))


class TestCachedBitwiseEquality:
    def test_cached_block_bitwise_equal_to_decoded(self, dataset, tmp_path):
        src_plain = _open_source(dataset)
        src_cached = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        for i in range(src_plain.plan.num_blocks):
            decoded = src_plain.build_block(i)
            first = src_cached.build_block(i)   # decode + spill
            cached = src_cached.build_block(i)  # cache hit (memmap views)
            _assert_blocks_equal(decoded, first)
            _assert_blocks_equal(decoded, cached)
        assert src_cached.cache.stats.hits == src_plain.plan.num_blocks
        assert src_cached.cache.stats.writes == src_plain.plan.num_blocks

    def test_shard_subset_keyed_separately(self, dataset, tmp_path):
        # two shard configs over the same bag: a subset build must not
        # collide with the full build in the cache
        shards2 = dict(SHARDS)
        shards2["alt"] = FeatureShardConfiguration(
            feature_bags=("features",), add_intercept=False
        )
        src = StreamingSource.open(
            dataset["paths"], shards2,
            index_maps=build_index_maps(dataset["paths"], shards2),
            block_rows=BLOCK_ROWS, id_tags=("userId",),
            cache_dir=str(tmp_path / "c"),
        )
        full = src.build_block(0)
        sub = src.build_block(0, shards=("global",))
        assert set(full.shards) == {"global", "alt"}
        assert set(sub.shards) == {"global"}
        np.testing.assert_array_equal(
            np.asarray(full.shards["global"][0]),
            np.asarray(sub.shards["global"][0]),
        )
        assert len(_entry_files(src.cache)) == 2
        # each keyed entry hits independently
        assert src.build_block(0).shards.keys() == full.shards.keys()
        assert src.build_block(0, shards=("global",)).shards.keys() == {"global"}
        assert src.cache.stats.hits == 2


class TestRobustness:
    def test_truncated_entry_falls_back_and_rewrites(self, dataset, tmp_path):
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        good = src.build_block(1)
        path = src.cache.entry_path(1, tuple(SHARDS))
        with open(path, "rb") as f:
            blob = f.read()
        with open(path, "wb") as f:
            f.write(blob[: len(blob) // 2])
        src.cache._validated.discard(path)  # fresh process would re-validate
        work0 = src.work_seconds
        blk = src.build_block(1)  # must fall back to decode
        assert src.work_seconds > work0
        assert src.cache.stats.invalid == 1
        _assert_blocks_equal(good, blk)
        # the fallback rewrote a valid entry: next visit hits with no work
        hits0 = src.cache.stats.hits
        work1 = src.work_seconds
        again = src.build_block(1)
        assert src.cache.stats.hits == hits0 + 1
        assert src.work_seconds == work1
        _assert_blocks_equal(good, again)

    def test_corrupted_payload_fails_checksum(self, dataset, tmp_path):
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        good = src.build_block(0)
        path = src.cache.entry_path(0, tuple(SHARDS))
        with open(path, "r+b") as f:
            f.seek(-4, os.SEEK_END)  # flip bytes inside the last array
            f.write(b"\xde\xad\xbe\xef")
        src.cache._validated.discard(path)
        blk = src.build_block(0)
        assert src.cache.stats.invalid == 1
        _assert_blocks_equal(good, blk)

    def test_garbage_file_is_a_miss(self, dataset, tmp_path):
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        os.makedirs(src.cache.dir, exist_ok=True)
        path = src.cache.entry_path(2, tuple(SHARDS))
        with open(path, "wb") as f:
            f.write(b"not a block cache entry at all")
        blk = src.build_block(2)
        assert blk.num_real == BLOCK_ROWS
        assert src.cache.stats.invalid == 1

    def test_stale_fingerprint_invalidates(self, dataset, tmp_path):
        cache_dir = str(tmp_path / "c")
        src = _open_source(dataset, cache_dir=cache_dir)
        src.build_block(0)
        old_dir = src.cache.dir
        assert _entry_files(src.cache)
        # touching a part file changes mtime_ns -> new fingerprint, even
        # with identical bytes (a rewritten input must never hit stale)
        st = os.stat(dataset["paths"][0])
        os.utime(dataset["paths"][0], ns=(st.st_atime_ns, st.st_mtime_ns + 1))
        fp2 = plan_fingerprint(
            BLOCK_ROWS, src.plan.files, src.plan.shard_widths,
            src.plan.shard_dims, id_tags=src.id_tags,
            index_maps=dataset["index_maps"],
        )
        assert fp2 != src.cache.fingerprint
        src2 = _open_source(dataset, cache_dir=cache_dir)
        assert src2.cache.dir != old_dir
        # attach_cache swept the stale sibling directory
        assert not os.path.isdir(old_dir)
        work0 = src2.work_seconds
        src2.build_block(0)
        assert src2.work_seconds > work0  # re-decoded, no stale hit

    def test_index_map_permutation_invalidates(self, dataset, tmp_path):
        """Same files, same sizes, different name->index assignment MUST
        change the fingerprint — a stale hit here would silently train on
        wrong column ids (the --offheap-indexmap-dir hazard)."""
        from photon_ml_tpu.indexmap import DefaultIndexMap

        cache_dir = str(tmp_path / "c")
        src = _open_source(dataset, cache_dir=cache_dir)
        src.build_block(0)
        old_dir = src.cache.dir

        forward = dict(dataset["index_maps"]["global"].items())
        (a, ia), (b, ib) = sorted(forward.items())[:2]
        forward[a], forward[b] = ib, ia  # same size, permuted assignment
        permuted = {"global": DefaultIndexMap(forward)}

        src2 = StreamingSource.open(
            dataset["paths"], SHARDS, index_maps=permuted,
            block_rows=BLOCK_ROWS, id_tags=("userId",), cache_dir=cache_dir,
        )
        assert src2.cache.fingerprint != src.cache.fingerprint
        assert src2.cache.dir != old_dir
        work0 = src2.work_seconds
        src2.build_block(0)
        assert src2.work_seconds > work0  # re-decoded under the new map

    def test_blocks_read_only_on_both_paths(self, dataset, tmp_path):
        """Cold (decode) and warm (memmap) blocks must BOTH reject in-place
        writes — a consumer mutating blocks must fail on epoch 1, not only
        once the cache warms."""
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        cold = src.build_block(0)
        warm = src.build_block(0)
        assert src.cache.stats.hits == 1
        for blk in (cold, warm):
            for arr in (blk.labels, blk.offsets, blk.weights,
                        *(a for pair in blk.shards.values() for a in pair),
                        *blk.id_tags.values()):
                assert not arr.flags.writeable
            with pytest.raises(ValueError):
                blk.labels[0] = 99.0

    def test_concurrent_writers_one_valid_entry(self, dataset, tmp_path):
        src = _open_source(dataset)
        fp = plan_fingerprint(
            BLOCK_ROWS, src.plan.files, src.plan.shard_widths,
            src.plan.shard_dims, id_tags=src.id_tags,
            index_maps=dataset["index_maps"],
        )
        block = src.build_block(3)
        caches = [BlockCache(str(tmp_path / "c"), fp) for _ in range(4)]
        barrier = threading.Barrier(4)

        def writer(c):
            barrier.wait()
            assert c.store(block, tuple(SHARDS))

        threads = [threading.Thread(target=writer, args=(c,)) for c in caches]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # last rename wins: exactly one entry file, no leftover temp files
        entries = _entry_files(caches[0])
        assert len(entries) == 1
        assert not glob.glob(os.path.join(caches[0].dir, ".tmp-*"))
        reader = BlockCache(str(tmp_path / "c"), fp)
        loaded = reader.load(3, tuple(SHARDS))
        assert loaded is not None
        _assert_blocks_equal(block, loaded)


class TestReadaheadBudget:
    def test_env_override_and_floor(self, monkeypatch):
        from photon_ml_tpu.streaming import readahead_file_budget

        monkeypatch.delenv("PHOTON_STREAM_READAHEAD_FILES", raising=False)
        assert readahead_file_budget() == 4
        monkeypatch.setenv("PHOTON_STREAM_READAHEAD_FILES", "7")
        assert readahead_file_budget() == 7
        monkeypatch.setenv("PHOTON_STREAM_READAHEAD_FILES", "0")
        assert readahead_file_budget() == 1  # floor: always one file ahead
        monkeypatch.setenv("PHOTON_STREAM_READAHEAD_FILES", "junk")
        assert readahead_file_budget() == 4

    def test_prefetch_blocks_caps_scheduled_files(self, dataset, monkeypatch):
        """Decoded-file residency must stay bounded by the budget no matter
        how many blocks the caller names or how wide the pool is."""
        monkeypatch.setenv("PHOTON_STREAM_READAHEAD_FILES", "1")
        src = _open_source(dataset)
        scheduled = []
        monkeypatch.setattr(
            src, "prefetch_files", lambda fis: scheduled.append(list(fis))
        )
        src.prefetch_blocks(range(src.plan.num_blocks))
        assert scheduled and len(scheduled[0]) <= 2  # budget + in-use file


class TestWarmEpochZeroWork:
    def test_warm_iteration_does_zero_decode_work(self, dataset, tmp_path):
        """The headline contract: iterating a fully cached plan costs zero
        Avro decode/pack seconds and schedules nothing on the decode pool."""
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        for _ in src.iter_blocks():  # cold epoch: decode + spill
            pass
        assert src.work_seconds > 0
        work0 = src.work_seconds
        wall0 = src.decode_wall_seconds
        decoded0 = src.files_decoded
        blocks = list(src.iter_blocks())  # warm epoch
        assert len(blocks) == src.plan.num_blocks
        assert src.work_seconds - work0 == 0.0
        assert src.decode_wall_seconds - wall0 == 0.0
        assert src.files_decoded == decoded0
        assert src.cache.stats.hits == src.plan.num_blocks

    def test_warm_prefetch_blocks_schedules_nothing(self, dataset, tmp_path):
        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        for _ in src.iter_blocks():
            pass
        src.prefetch_blocks(range(src.plan.num_blocks))
        assert not src._pending  # cache consulted before the decode pool

    def test_warm_prefetcher_hide_ratio_is_one(self, dataset, tmp_path):
        from photon_ml_tpu.streaming import BlockPrefetcher

        src = _open_source(dataset, cache_dir=str(tmp_path / "c"))
        cold = BlockPrefetcher(src, depth=1)
        assert sum(1 for _ in cold) == src.plan.num_blocks
        warm = BlockPrefetcher(src, depth=1)
        assert sum(1 for _ in warm) == src.plan.num_blocks
        assert warm.stats.decode_s == 0.0
        assert warm.stats.decode_work_s == 0.0
        assert warm.stats.cache_hit_blocks == src.plan.num_blocks
        assert warm.stats.hide_ratio == 1.0
