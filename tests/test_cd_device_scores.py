"""Device-resident CD score plane: host/device parity, zero row transfers
in steady state, and the double-score-sum regression fix.

The two planes execute the same sequence of IEEE f32 elementwise ops (numpy
on host, XLA on device), so parity is expected to be EXACT on CPU — the
1e-6 assertions are the contract, the observed diff is 0.0.
"""

import numpy as np
import pytest

from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.event import EventEmitter, EventListener, TransferStatsEvent
from photon_ml_tpu.incremental.trainer import incremental_update
from photon_ml_tpu.parallel import mesh as mesh_mod
from photon_ml_tpu.types import TaskType

N_USERS, N_ITEMS, ROWS_PER_USER = 18, 7, 24
D_FE, D_RE = 10, 5
N_OUTER = 3


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    n = N_USERS * ROWS_PER_USER
    Xg = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xu = rng.normal(size=(n, D_RE)).astype(np.float32)
    Xi = rng.normal(size=(n, D_RE)).astype(np.float32)
    user_ids = np.repeat([f"u{i:03d}" for i in range(N_USERS)], ROWS_PER_USER)
    item_ids = np.array([f"i{int(v):03d}" for v in rng.integers(0, N_ITEMS, n)])
    w = rng.normal(size=D_FE).astype(np.float32)
    y = (Xg @ w + 0.1 * rng.normal(size=n)).astype(np.float32)

    def coo(X):
        rows, cols = np.nonzero(X)
        return FeatureShard(rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1])

    return GameData(
        labels=y,
        feature_shards={"global": coo(Xg), "per_user": coo(Xu), "per_item": coo(Xi)},
        id_tags={"userId": user_ids, "itemId": item_ids},
    )


def _coords():
    return {
        "fixed": FixedEffectCoordinateConfiguration("global"),
        "per-user": RandomEffectCoordinateConfiguration(
            feature_shard="per_user",
            data=RandomEffectDataConfiguration(random_effect_type="userId"),
        ),
        "per-item": RandomEffectCoordinateConfiguration(
            feature_shard="per_item",
            data=RandomEffectDataConfiguration(random_effect_type="itemId"),
        ),
    }


def _fit(plane, data, initial_models=None, emitter=None):
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates=_coords(),
        num_outer_iterations=N_OUTER,
        score_plane=plane,
        emitter=emitter,
    )
    fit = est.fit(data, initial_models=initial_models)
    return est, fit


class _Recorder(EventListener):
    def __init__(self):
        self.events = []

    def on_event(self, event):
        self.events.append(event)


def test_host_device_parity_fe_plus_two_re():
    """3 outer iterations over FE + 2 RE coordinates: final model scores
    from the two planes must agree to 1e-6 (they match bitwise on CPU)."""
    data = _problem()
    _, fit_h = _fit("host", data)
    _, fit_d = _fit("device", data)
    sh = np.asarray(fit_h.model.score(data))
    sd = np.asarray(fit_d.model.score(data))
    assert np.max(np.abs(sh - sd)) <= 1e-6
    # the training objective trajectories agree too — the device plane's
    # objective is computed from the running device total
    for (cid_h, oh), (cid_d, od) in zip(
        fit_h.objective_history, fit_d.objective_history
    ):
        assert cid_h == cid_d
        assert abs(oh - od) <= 1e-6 * max(1.0, abs(oh))


def test_host_device_parity_warm_start():
    """Warm-started fits (initial models from a previous fit) follow the
    initial-scoring path — parity must hold there as well."""
    data = _problem()
    _, first = _fit("device", data)
    warm = dict(first.model.models)
    _, fit_h = _fit("host", data, initial_models=warm)
    _, fit_d = _fit("device", data, initial_models=warm)
    sh = np.asarray(fit_h.model.score(data))
    sd = np.asarray(fit_d.model.score(data))
    assert np.max(np.abs(sh - sd)) <= 1e-6


def test_resolve_coordinate_device_parity():
    """resolve_coordinate on the device plane (fused residual upload +
    on-device offset regroup) matches the host re-solve."""
    data = _problem()
    est_d, fit_d = _fit("device", data)
    models = dict(fit_d.model.models)
    events = _problem(seed=7)

    est_h = GameEstimator(
        task=TaskType.LINEAR_REGRESSION, coordinates=_coords(),
        score_plane="host",
    )
    sub_h = est_h.resolve_coordinate("per-user", events, models)
    sub_d = est_d.resolve_coordinate("per-user", events, models)
    assert est_h.last_resolve_transfers.score_plane == "host"
    assert est_d.last_resolve_transfers.score_plane == "device"
    assert est_d.last_resolve_transfers.device_plane_updates == 1
    rows_h = {eid: coefs for eid, coefs in sub_h.items()}
    rows_d = {eid: coefs for eid, coefs in sub_d.items()}
    assert set(rows_h) == set(rows_d)
    for eid in rows_h:
        for j in set(rows_h[eid]) | set(rows_d[eid]):
            assert abs(rows_h[eid].get(j, 0.0) - rows_d[eid].get(j, 0.0)) <= 1e-6


def test_incremental_trainer_device_parity_and_transfer_stats():
    """The nearline incremental trainer produces the same touched-entity
    updates on either plane and surfaces per-coordinate TransferStats."""
    data = _problem()
    est_d, fit_d = _fit("device", data)
    events = _problem(seed=11)

    est_h = GameEstimator(
        task=TaskType.LINEAR_REGRESSION, coordinates=_coords(),
        score_plane="host",
    )
    upd_h = incremental_update(est_h, fit_d.model, events)
    upd_d = incremental_update(est_d, fit_d.model, events)
    assert upd_h.touched_entities == upd_d.touched_entities
    for cid in upd_h.re_updates:
        assert cid in upd_d.transfer_stats
        assert upd_d.transfer_stats[cid].score_plane == "device"
        assert upd_h.transfer_stats[cid].score_plane == "host"
        for eid, coefs_h in upd_h.re_updates[cid].items():
            coefs_d = upd_d.re_updates[cid][eid]
            for j in set(coefs_h) | set(coefs_d):
                assert abs(coefs_h.get(j, 0.0) - coefs_d.get(j, 0.0)) <= 1e-6


def test_device_plane_zero_row_transfers_steady_state():
    """On the device plane, NO row-length array crosses the host/device
    boundary during CD: TransferStats reads zero, and the fetch_global
    observer (which sees every device->host materialization) records no
    row-length pulls between the first and last coordinate update."""
    data = _problem()
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates=_coords(),
        num_outer_iterations=N_OUTER,
        score_plane="device",
    )
    # build the coordinates first: the observer must watch ONLY the CD run,
    # not the one-time dataset construction
    built = {
        cid: est._build_coordinate(cid, cfg, data)
        for cid, cfg in est.coordinate_configs.items()
    }
    fetched = []
    mesh_mod.add_fetch_observer(fetched.append)
    try:
        est._run_fit(built, data, None, None, None)
    finally:
        mesh_mod.remove_fetch_observer(fetched.append)
    t = est.last_transfer_stats
    assert t.score_plane == "device"
    assert t.row_transfers_h2d == 0
    assert t.row_transfers_d2h == 0
    assert t.coordinate_updates == 3 * N_OUTER
    assert t.device_plane_updates == 3 * N_OUTER
    # no full-row device->host pull has the plane's row length (scalar and
    # coefficient-sized fetches are fine; the score plane itself never moves)
    row_bytes = data.num_rows * 4
    assert row_bytes not in fetched

    # host plane, for contrast, moves 2 row arrays per update
    est_h, _ = _fit("host", data)
    th = est_h.last_transfer_stats
    assert th.row_transfers_h2d == 3 * N_OUTER
    assert th.row_transfers_d2h == 3 * N_OUTER


def test_single_plane_pass_per_update_regression():
    """Regression for the double total_score() evaluation: the legacy
    driver re-summed all C coordinates once for the residual and once for
    the objective (2 full host sums per update). Both planes now maintain a
    running total: host_score_sums must stay 0 while the objective history
    still records one entry per coordinate update."""
    data = _problem()
    for plane in ("host", "device"):
        est, fit = _fit(plane, data)
        t = est.last_transfer_stats
        assert t.host_score_sums == 0
        assert t.coordinate_updates == 3 * N_OUTER
        assert len(fit.objective_history) == 3 * N_OUTER
        per_iter = t.per_outer_iteration()
        assert per_iter["host_score_sums_per_iter"] == 0.0
        if plane == "device":
            assert per_iter["row_transfers_per_iter"] == 0.0
            assert per_iter["row_bytes_per_iter"] == 0.0


def test_transfer_stats_event_emitted_per_outer_iteration():
    data = _problem()
    emitter = EventEmitter()
    rec = _Recorder()
    emitter.register_listener(rec)
    _fit("device", data, emitter=emitter)
    tevents = [e for e in rec.events if isinstance(e, TransferStatsEvent)]
    assert len(tevents) == N_OUTER
    for i, e in enumerate(tevents):
        assert e.outer_iteration == i
        assert e.score_plane == "device"
        assert e.row_transfers_h2d == 0
        assert e.row_transfers_d2h == 0
        assert e.device_plane_updates == 3
        assert e.num_rows == data.num_rows


def test_score_plane_validation():
    with pytest.raises(ValueError, match="score_plane"):
        GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates=_coords(),
            score_plane="gpu",
        )
    with pytest.raises(ValueError, match="score_plane"):
        CoordinateDescent({"x": object()}, num_rows=4, score_plane="np")
