"""Regression gate for the driver entry points: the single-chip jittable
forward step and the full multi-chip sharded training step must compile and
run on the virtual 8-device mesh (conftest.py)."""

import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

import jax
import numpy as np


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    out = jax.block_until_ready(out)
    leaves = jax.tree_util.tree_leaves(out)
    assert leaves
    for leaf in leaves:
        assert np.isfinite(np.asarray(leaf)).all()


def test_dryrun_multichip_8_devices():
    import __graft_entry__ as g

    assert len(jax.devices()) == 8
    g.dryrun_multichip(8)
