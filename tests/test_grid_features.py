"""2-D (data x feat) sharded fixed-effect features on the 8-device harness.

The coefficient axis never materializes unsharded on one device — this is
the layout that carries the 1B-coefficient target (SURVEY.md §7 hard part
(d)); correctness is checked against the single-device engines.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.ops.sparse_perm import from_coo
from photon_ml_tpu.parallel.grid_features import (
    GridShardedFeatures,
    grid_from_coo,
    grid_mesh,
    shard_vector_data,
    shard_vector_feat,
)


def _problem(rng, n=512, d=384, k=6, intercept=True):
    rows = np.repeat(np.arange(n), k + int(intercept))
    blocks = [rng.integers(1, d, (n, k))]
    if intercept:
        blocks.append(np.zeros((n, 1), np.int64))
    cols = np.concatenate(blocks, axis=1).reshape(-1)
    vals = rng.standard_normal(rows.size).astype(np.float32)
    return rows, cols, vals, (n, d)


def _dense(rows, cols, vals, shape):
    m = np.zeros(shape, np.float32)
    np.add.at(m, (rows, cols), vals)
    return m


class TestGridFeatures:
    @pytest.mark.parametrize("engine", ["ell", "benes"])
    @pytest.mark.parametrize("grid", [(2, 4), (4, 2), (8, 1), (1, 8)])
    def test_matches_dense(self, rng, engine, grid):
        rows, cols, vals, shape = _problem(rng)
        mesh = grid_mesh(*grid)
        gf = grid_from_coo(rows, cols, vals, shape, mesh, engine=engine)
        n, d = shape
        dense = _dense(rows, cols, vals, shape)
        w = rng.standard_normal(gf.dim).astype(np.float32)
        c = rng.standard_normal(gf.num_rows).astype(np.float32)
        w[d:] = 0.0
        c[n:] = 0.0

        wd = shard_vector_feat(jnp.asarray(w), mesh)
        cd = shard_vector_data(jnp.asarray(c), mesh)
        z = np.asarray(gf.matvec(wd))
        np.testing.assert_allclose(z[:n], dense @ w[:d], atol=1e-3)
        np.testing.assert_allclose(z[n:], 0.0, atol=1e-6)
        g = np.asarray(gf.rmatvec(cd))
        np.testing.assert_allclose(g[:d], dense.T @ c[:n], atol=1e-3)
        np.testing.assert_allclose(g[d:], 0.0, atol=1e-6)
        gsq = np.asarray(gf.rmatvec_sq(cd))
        np.testing.assert_allclose(gsq[:d], (dense * dense).T @ c[:n], atol=1e-3)
        rn = np.asarray(gf.row_norms_sq())
        np.testing.assert_allclose(rn[:n], (dense * dense).sum(1), atol=1e-3)

    def test_full_solve_w_never_unsharded(self, rng):
        """End-to-end L-BFGS fit on the 2x4 grid == single-device fit; the
        coefficient vector stays feat-sharded through the whole solve."""
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.ops.data import LabeledData

        rows, cols, vals, shape = _problem(rng, n=512, d=128, k=4)
        n, d = shape
        dense = _dense(rows, cols, vals, shape)
        w_true = (rng.standard_normal(d) * 0.3).astype(np.float32)
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-dense @ w_true))).astype(
            np.float32
        )

        mesh = grid_mesh(2, 4)
        objective = make_glm_objective(LogisticLoss)
        cfg = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=40),
            regularization_weight=1.0,
        )

        single = from_coo(rows, cols, vals, shape)
        data_s = LabeledData.create(single, jnp.asarray(y))
        res_s = jax.jit(
            lambda dd: solve(
                objective, jnp.zeros(d, jnp.float32), dd, cfg,
                l2_weight=jnp.float32(1.0),
            )
        )(data_s)

        gf = grid_from_coo(rows, cols, vals, shape, mesh, engine="ell")
        y_pad = np.zeros(gf.num_rows, np.float32)
        y_pad[:n] = y
        wt_pad = np.zeros(gf.num_rows, np.float32)
        wt_pad[:n] = 1.0
        data_g = LabeledData.create(
            gf,
            shard_vector_data(jnp.asarray(y_pad), mesh),
            weights=shard_vector_data(jnp.asarray(wt_pad), mesh),
        )
        w0 = shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh)
        res_g = jax.jit(
            lambda w0, dd: solve(
                objective, w0, dd, cfg, l2_weight=jnp.float32(1.0)
            )
        )(w0, data_g)

        assert np.allclose(float(res_s.value), float(res_g.value), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(res_g.w)[:d], np.asarray(res_s.w), atol=2e-3
        )
        np.testing.assert_allclose(np.asarray(res_g.w)[d:], 0.0, atol=1e-5)


class TestGridPadding:
    def test_non_divisible_rows_and_cols(self, rng):
        # 1001 rows / 8-way data split -> n_loc=126, pad to 1008;
        # 100 cols on a (8,1) grid stays exact, on (2,4) pads to 104
        rows, cols, vals, shape = _problem(rng, n=1001, d=100, k=3)
        n, d = shape
        dense = _dense(rows, cols, vals, shape)
        for grid in [(8, 1), (2, 4)]:
            mesh = grid_mesh(*grid)
            gf = grid_from_coo(rows, cols, vals, shape, mesh, engine="benes")
            assert gf.num_rows % grid[0] == 0 and gf.num_rows >= n
            assert gf.dim % grid[1] == 0 and gf.dim >= d
            w = np.zeros(gf.dim, np.float32)
            w[:d] = rng.standard_normal(d)
            c = np.zeros(gf.num_rows, np.float32)
            c[:n] = rng.standard_normal(n)
            wd = shard_vector_feat(jnp.asarray(w), mesh)
            cd = shard_vector_data(jnp.asarray(c), mesh)
            z = np.asarray(gf.matvec(wd))
            np.testing.assert_allclose(z[:n], dense @ w[:d], atol=1e-3)
            np.testing.assert_allclose(z[n:], 0.0, atol=1e-6)
            g = np.asarray(gf.rmatvec(cd))
            np.testing.assert_allclose(g[:d], dense.T @ c[:n], atol=1e-3)
            np.testing.assert_allclose(g[d:], 0.0, atol=1e-6)


class TestGridSecondOrder:
    def test_tron_solve_on_grid(self, rng):
        """TRON's CG runs Hessian-vector products through the grid engine
        (matvec + rmatvec per CG step, psums on both axes); optimum must
        match the single-device TRON fit."""
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.ops.data import LabeledData

        rows, cols, vals, shape = _problem(rng, n=512, d=96, k=4)
        n, d = shape
        dense = _dense(rows, cols, vals, shape)
        w_true = (rng.standard_normal(d) * 0.3).astype(np.float32)
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-dense @ w_true))).astype(
            np.float32
        )
        objective = make_glm_objective(LogisticLoss)
        cfg = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.tron(max_iterations=12),
            regularization_weight=1.0,
        )

        single = from_coo(rows, cols, vals, shape)
        res_s = jax.jit(
            lambda dd: solve(
                objective, jnp.zeros(d, jnp.float32), dd, cfg,
                l2_weight=jnp.float32(1.0),
            )
        )(LabeledData.create(single, jnp.asarray(y)))

        mesh = grid_mesh(2, 4)
        gf = grid_from_coo(rows, cols, vals, shape, mesh, engine="benes")
        y_pad = np.zeros(gf.num_rows, np.float32)
        y_pad[:n] = y
        wt = np.zeros(gf.num_rows, np.float32)
        wt[:n] = 1.0
        data_g = LabeledData.create(
            gf,
            shard_vector_data(jnp.asarray(y_pad), mesh),
            weights=shard_vector_data(jnp.asarray(wt), mesh),
        )
        res_g = jax.jit(
            lambda w0, dd: solve(
                objective, w0, dd, cfg, l2_weight=jnp.float32(1.0)
            )
        )(shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh), data_g)

        np.testing.assert_allclose(
            np.asarray(res_g.w)[:d], np.asarray(res_s.w), atol=2e-3
        )
