"""Request-plane tests: lifecycle sampling, tail attribution, SLO budget,
scenario harness.

The load-bearing guarantees, per ISSUE acceptance criteria:

- **Disabled-path parity**: replaying the same stream with no plane, with a
  plane at ``sample_rate=0``, and with a fully-sampling plane produces
  BITWISE-identical scores — observation may never perturb the data path.
  (The matching CI step is the request-plane disabled-path parity gate.)
- **Attribution completeness**: stage boundaries telescope, so each sampled
  record's per-stage durations sum to its end-to-end latency and the tail
  breakdown's attribution coverage is ~1.0 (>= the 0.95 acceptance floor).
- **Sampler determinism**: the seeded hash tags the same request ids
  regardless of submission order, batch boundaries, or thread.
- **Ledger round trip**: sampled records written through RunLedger pass
  ``validate_ledger``'s ``request`` schema and reconstruct the same report
  through ``analyze_run --requests``'s ``request_report``.
- **SLO math**: burn rate = bad_fraction / (1 - objective); the budget
  exhausts at burn >= 1, degrades /healthz, and recovers as the rolling
  window ages violations out.
- **Scenario harness**: each named scenario deterministically reshapes the
  stream (preserving it), and ``run_scenario`` emits per-stage p50/p99,
  residency and an SLO verdict.
"""

import time

import numpy as np
import pytest

from photon_ml_tpu import testing
from photon_ml_tpu.serving import (
    GameScorer,
    MicroBatcher,
    RequestPlane,
    SLOTracker,
    ServingMetrics,
    build_scenario,
    pack_game_model,
    replay_requests,
    requests_from_game_data,
    run_scenario,
)
from photon_ml_tpu.serving.requestplane import (
    INTERFERENCE_KINDS,
    REQUEST_STAGES,
    sample_hash,
)
from photon_ml_tpu.serving.scenarios import SCENARIO_NAMES, make_row_swap_fn
from photon_ml_tpu.telemetry.analyze import (
    format_request_report,
    request_report,
)
from photon_ml_tpu.telemetry.sinks import RunLedger
from photon_ml_tpu.telemetry.validate import validate_ledger
from photon_ml_tpu.types import TaskType

TASK = TaskType.LOGISTIC_REGRESSION
COORDS = {
    "fixed": {"feature_shard": "global"},
    "per_user": {"feature_shard": "per_entity", "random_effect_type": "userId"},
}
BUCKETS = (1, 2, 4, 8, 16)


@pytest.fixture(scope="module")
def glmix():
    data, _ = testing.generate_glmix_data(
        task=TASK, n_entities=8, rows_per_entity=10, d_global=8, d_entity=4,
        seed=11,
    )
    model = testing.generate_game_model(data, TASK, COORDS, seed=3)
    return data, pack_game_model(model)


def _requests(glmix):
    data, artifact = glmix
    return artifact, requests_from_game_data(data, artifact)


class TestSampler:
    def test_deterministic_and_order_independent(self):
        ids = [f"req-{i}" for i in range(512)]
        plane = RequestPlane(sample_rate=8, seed=42)
        tagged = {rid for rid in ids if plane.sampled(rid)}
        # same ids, reversed submission order, different batch boundaries:
        # identical tag set
        rev = list(reversed(ids))
        via_batches = set()
        for lo in range(0, len(rev), 7):
            chunk = rev[lo:lo + 7]
            via_batches.update(
                chunk[i] for i in plane.sample_indices(chunk)
            )
        assert via_batches == tagged
        assert tagged  # rate 8 over 512 ids can't tag nothing

    def test_rate_semantics(self):
        ids = [f"r{i}" for i in range(1000)]
        assert RequestPlane(sample_rate=0).sample_indices(ids) == []
        assert RequestPlane(sample_rate=1).sample_indices(ids) == list(
            range(1000)
        )
        n = len(RequestPlane(sample_rate=16, seed=0).sample_indices(ids))
        # ~1/16 of 1000 = 62.5; the hash is uniform enough for loose bounds
        assert 20 <= n <= 130
        with pytest.raises(ValueError):
            RequestPlane(sample_rate=-1)

    def test_seed_changes_the_sample(self):
        ids = [f"r{i}" for i in range(1000)]
        a = set(RequestPlane(sample_rate=8, seed=1).sample_indices(ids))
        b = set(RequestPlane(sample_rate=8, seed=2).sample_indices(ids))
        assert a != b

    def test_hash_is_stable(self):
        # pinned: a changed hash would silently re-tag every deployment
        assert sample_hash("request-0", 0) == sample_hash("request-0", 0)
        assert sample_hash("request-0", 0) != sample_hash("request-1", 0)
        assert sample_hash("request-0", 0) != sample_hash("request-0", 7)


class TestRecordBatch:
    def test_stages_telescope_to_total(self):
        plane = RequestPlane(sample_rate=1)
        t0 = 100.0
        stages = {
            "featurize_done": t0 + 0.003,
            "route_done": t0 + 0.004,
            "dispatch_done": t0 + 0.006,
            "device_done": t0 + 0.009,
        }
        plane.record_batch(
            "sealed", 8, 5, [("a", t0 - 0.002), ("b", t0 - 0.001)],
            t0, stages, t0 + 0.010,
        )
        for rec in plane.records():
            assert set(rec["stages"]) == set(REQUEST_STAGES)
            assert all(v >= 0 for v in rec["stages"].values())
            assert sum(rec["stages"].values()) == pytest.approx(
                rec["total_s"], rel=1e-9
            )

    def test_out_of_order_boundaries_clamp_monotonic(self):
        plane = RequestPlane(sample_rate=1)
        t0 = 50.0
        # device_done BEFORE route_done (async clock skew): clamped, never
        # negative
        stages = {
            "featurize_done": t0 + 0.004,
            "route_done": t0 + 0.003,
            "dispatch_done": t0 + 0.002,
            "device_done": t0 + 0.001,
        }
        plane.record_batch("sealed", 4, 4, [("x", t0)], t0, stages, t0 + 0.005)
        (rec,) = plane.records()
        assert all(v >= 0 for v in rec["stages"].values())
        assert rec["total_s"] == pytest.approx(0.005, rel=1e-9)

    def test_missing_stage_clock_degrades_to_queue_reply(self):
        plane = RequestPlane(sample_rate=1)
        plane.record_batch("sealed", 4, 1, [("x", 10.0)], 10.002, None, 10.01)
        (rec,) = plane.records()
        assert rec["stages"]["queue"] == pytest.approx(0.002, rel=1e-9)
        assert rec["stages"]["reply"] == pytest.approx(0.008, rel=1e-9)
        for stage in ("featurize", "route", "dispatch", "device"):
            assert rec["stages"][stage] == 0.0

    def test_interference_overlap_is_windowed(self):
        plane = RequestPlane(sample_rate=1)
        plane.note_interference("swap_pause", 10.004, 10.006)
        plane.note_interference("admission", 20.0, 20.1)  # outside window
        plane.note_interference("swap_pause", 10.0, 10.0)  # empty: dropped
        plane.record_batch("sealed", 4, 1, [("x", 10.0)], 10.005, None, 10.01)
        (rec,) = plane.records()
        inter = rec["interference"]
        assert inter["swap_pause_s"] == pytest.approx(0.002, rel=1e-6)
        assert "admission_s" not in inter
        assert set(k[:-2] for k in inter) <= set(INTERFERENCE_KINDS)

    def test_ring_is_bounded(self):
        plane = RequestPlane(sample_rate=1, capacity=4)
        for i in range(10):
            plane.record_batch(
                "sealed", 1, 1, [(f"r{i}", 1.0)], 1.001, None, 1.002
            )
        assert len(plane.records()) == 4
        assert plane.sampled_total == 10
        plane.reset_records()
        assert plane.records() == []
        assert plane.sampled_total == 10


class TestSLOTracker:
    def test_healthy_budget(self):
        slo = SLOTracker(latency_threshold_s=0.05)
        slo.observe_many(np.full(1000, 0.001))
        st = slo.status()
        assert st["verdict"] == "ok"
        assert st["healthy"] is True
        assert st["availability"] == 1.0
        assert st["error_budget_remaining"] == 1.0

    def test_availability_burn_exhausts(self):
        slo = SLOTracker(availability_objective=0.999)
        slo.observe_many(np.full(99, 0.001), errors=1)
        st = slo.status()
        # 1/100 errors against a 0.1% budget: burn 10x
        assert st["availability_burn_rate"] == pytest.approx(10.0, rel=1e-6)
        assert st["error_budget_remaining"] == 0.0
        assert "availability" in st["verdict"]
        assert slo.health()["healthy"] is False
        assert "degraded" in slo.health()

    def test_latency_burn(self):
        slo = SLOTracker(latency_threshold_s=0.01, latency_objective=0.99)
        lat = np.full(100, 0.001)
        lat[:5] = 0.5  # 5% slow against a 1% allowance: burn 5x
        slo.observe_many(lat)
        st = slo.status()
        assert st["latency_burn_rate"] == pytest.approx(5.0, rel=1e-6)
        assert "latency" in st["verdict"]

    def test_window_ages_out_violations(self):
        now = [1000.0]
        slo = SLOTracker(
            availability_objective=0.9, window_s=30.0, num_buckets=3,
            clock=lambda: now[0],
        )
        slo.observe_many(np.full(2, 0.001), errors=2)
        assert slo.status()["healthy"] is False
        # advance past the whole window: the violation falls out, fresh
        # healthy traffic restores the budget
        now[0] += 40.0
        slo.observe_many(np.full(10, 0.001))
        st = slo.status()
        assert st["window_errors"] == 0
        assert st["healthy"] is True

    def test_gauges_exported(self):
        from photon_ml_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        slo = SLOTracker(registry=reg)
        slo.observe_many(np.full(10, 0.001))
        slo.status()
        gauges = reg.snapshot()["gauges"]
        for name in (
            "serving.slo.availability",
            "serving.slo.latency_ok_rate",
            "serving.slo.burn_rate",
            "serving.slo.error_budget_remaining",
            "serving.slo.budget_exhausted",
        ):
            assert name in gauges


class TestDisabledPathParity:
    """The CI request-plane disabled-path parity gate runs this class."""

    def test_scores_bitwise_identical_across_plane_modes(self, glmix):
        artifact, requests = _requests(glmix)

        def _scores(plane):
            scorer = GameScorer(artifact)
            results, _ = replay_requests(
                scorer, requests, bucket_sizes=BUCKETS, plane=plane
            )
            return np.array([r.score for r in results], dtype=np.float32)

        base = _scores(None)
        off = _scores(RequestPlane(sample_rate=0))
        sampled = _scores(RequestPlane(sample_rate=1))
        assert np.array_equal(base, off)
        assert np.array_equal(base, sampled)

    def test_continuous_scores_bitwise_identical(self, glmix):
        artifact, requests = _requests(glmix)

        def _scores(plane):
            scorer = GameScorer(artifact)
            results, _ = replay_requests(
                scorer, requests, bucket_sizes=BUCKETS, plane=plane,
                continuous=True, max_wait_s=0.001,
            )
            return np.array([r.score for r in results], dtype=np.float32)

        assert np.array_equal(
            _scores(None), _scores(RequestPlane(sample_rate=1))
        )


class TestPlaneIntegration:
    def test_sealed_replay_records_and_ledger_round_trip(
        self, glmix, tmp_path
    ):
        artifact, requests = _requests(glmix)
        ledger_path = str(tmp_path / "ledger.jsonl")
        ledger = RunLedger(ledger_path)
        ledger.write("meta", phase="start", label="plane-test")
        plane = RequestPlane(sample_rate=1, ledger=ledger)
        scorer = GameScorer(artifact)
        results, snapshot = replay_requests(
            scorer, requests, bucket_sizes=BUCKETS, plane=plane
        )
        ledger.write("meta", phase="finish")
        ledger.close()
        assert len(results) == len(requests)
        assert plane.sampled_total == len(requests)

        # schema round trip: every sampled record validates as a ledger
        # "request" kind and reconstructs the analyzer report
        records = validate_ledger(ledger_path)
        reqs = [r for r in records if r["type"] == "request"]
        assert len(reqs) == len(requests)
        report = request_report(records)
        assert report["num_records"] == len(requests)
        # acceptance: the per-stage tail breakdown explains >= 95% of the
        # end-to-end tail latency (telescoping makes it ~100%)
        assert report["tail"]["attribution_coverage"] >= 0.95
        assert report["tail"]["exemplars"]
        assert set(report["stages"]) == set(REQUEST_STAGES)
        text = format_request_report(report)
        for stage in REQUEST_STAGES:
            assert stage in text
        # the replay snapshot carries the live view of the same plane
        assert snapshot["request_plane"]["sampled_total"] == len(requests)

    def test_continuous_replay_records_stages(self, glmix):
        artifact, requests = _requests(glmix)
        # a generous latency budget: CPU smoke latencies must not flip the
        # verdict, this test is about stage attribution, not SLO tuning
        plane = RequestPlane(sample_rate=1, slo=SLOTracker(
            latency_threshold_s=60.0
        ))
        scorer = GameScorer(artifact)
        results, snapshot = replay_requests(
            scorer, requests, bucket_sizes=BUCKETS, plane=plane,
            continuous=True, max_wait_s=0.001,
        )
        assert len(results) == len(requests)
        recs = plane.records()
        assert len(recs) == len(requests)
        assert {r["batcher"] for r in recs} == {"continuous"}
        # device work happened, so sampled batches must attribute nonzero
        # scoring-side time (featurize..device), not lump it all in queue
        scoring = sum(
            r["stages"]["featurize"] + r["stages"]["route"]
            + r["stages"]["dispatch"] + r["stages"]["device"]
            for r in recs
        )
        assert scoring > 0
        assert snapshot["slo"]["verdict"] == "ok"

    def test_stage_less_scorer_still_records(self, glmix):
        artifact, requests = _requests(glmix)

        class NoStageScorer:
            """A scorer whose score_batch predates the stage clock."""

            def __init__(self, inner):
                self._inner = inner

            def score_batch(self, requests, bucket_size=None):
                return self._inner.score_batch(requests, bucket_size)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        plane = RequestPlane(sample_rate=1)
        batcher = MicroBatcher(
            NoStageScorer(GameScorer(artifact)), bucket_sizes=BUCKETS,
            plane=plane,
        )
        out = []
        for req in requests:
            out.extend(batcher.submit(req))
        out.extend(batcher.flush())
        assert len(out) == len(requests)
        recs = plane.records()
        assert len(recs) == len(requests)
        # no stage clock: scoring time lands in the terminal reply stage,
        # totals still telescope
        for rec in recs:
            assert sum(rec["stages"].values()) == pytest.approx(
                rec["total_s"], rel=1e-9
            )

    def test_swap_pause_interference_via_metrics(self, glmix):
        artifact, requests = _requests(glmix)
        plane = RequestPlane(sample_rate=1)
        metrics = ServingMetrics(request_plane=plane)
        scorer = GameScorer(artifact)
        batcher = MicroBatcher(
            scorer, bucket_sizes=BUCKETS, metrics=metrics, plane=plane
        )
        for req in requests[:4]:
            batcher.submit(req)
        # a hot-swap pause reported mid-flight must overlap the pending
        # requests' windows
        metrics.observe_swap(generation=1, rows_updated=8, blackout_s=0.01)
        batcher.flush()
        kinds = set()
        for rec in plane.records():
            kinds.update(k[:-2] for k in (rec.get("interference") or {}))
        assert "swap_pause" in kinds


class TestRequestReport:
    def test_empty_is_none(self):
        assert request_report([]) is None
        assert request_report([{"type": "span", "name": "x"}]) is None

    def test_worst_bucket_and_exemplars(self):
        recs = []
        for i in range(20):
            bucket = 16 if i < 18 else 64
            total = 0.001 if i < 18 else 0.5
            recs.append({
                "type": "request",
                "request_id": f"r{i}",
                "bucket": bucket,
                "stages": {
                    "queue": total, "featurize": 0.0, "route": 0.0,
                    "dispatch": 0.0, "device": 0.0, "reply": 0.0,
                },
                "total_s": total,
            })
        report = request_report(recs)
        assert report["tail"]["worst_bucket"] == 64
        assert report["tail"]["worst_stage"] == "queue"
        assert len(report["tail"]["exemplars"]) <= 3
        assert all(x.startswith("r") for x in report["tail"]["exemplars"])


class TestScenarios:
    def test_catalog_and_determinism(self, glmix):
        _, requests = _requests(glmix)
        for name in SCENARIO_NAMES:
            a = build_scenario(name, requests, seed=7, num_phases=6)
            b = build_scenario(name, requests, seed=7, num_phases=6)
            if name == "tenant_isolation":
                # the flooding tenant replays its mid-run share on top
                # of the full stream, so this scenario carries MORE
                # requests than the input; every other shape preserves
                # the stream exactly
                assert a.num_requests > len(requests), name
            else:
                assert a.num_requests == len(requests), name
            assert [len(p.requests) for p in a.phases] == [
                len(p.requests) for p in b.phases
            ], name
            assert [
                [r.request_id for r in p.requests] for p in a.phases
            ] == [
                [r.request_id for r in p.requests] for p in b.phases
            ], name

    def test_unknown_scenario_rejected(self, glmix):
        _, requests = _requests(glmix)
        with pytest.raises(ValueError):
            build_scenario("lunar_eclipse", requests)
        with pytest.raises(ValueError):
            build_scenario("steady", [])

    def test_cold_flood_remaps_to_cold_ids(self, glmix):
        _, requests = _requests(glmix)
        scn = build_scenario("cold_entity_flood", requests, num_phases=4)
        flood = scn.phases[-1].requests
        assert all(r.request_id.endswith("-cold") for r in flood)
        # remapped ids stay within the observed population (known to the
        # model, unlikely to be resident)
        observed = {
            eid for r in requests for eid in r.entity_ids.values()
        }
        for r in flood:
            assert set(r.entity_ids.values()) <= observed

    def test_hot_swap_phases_are_interior(self, glmix):
        _, requests = _requests(glmix)
        scn = build_scenario("hot_swap_under_load", requests, num_phases=6)
        flags = [p.swap for p in scn.phases]
        assert flags[0] is False and flags[-1] is False
        assert any(flags[1:-1])

    def test_run_scenario_emits_contract_fields(self, glmix):
        artifact, requests = _requests(glmix)
        scorer = GameScorer(artifact)
        metrics = ServingMetrics()
        slo = SLOTracker()
        plane = RequestPlane(sample_rate=1, slo=slo)
        scn = build_scenario(
            "steady", requests, num_phases=3, pause_s=0.0
        )
        doc = run_scenario(
            scn, scorer, bucket_sizes=BUCKETS, metrics=metrics,
            plane=plane, slo=slo, continuous=False,
        )
        assert doc["name"] == "steady"
        assert doc["num_requests"] == len(requests)
        assert doc["requests_per_s"] > 0
        stages = doc["request_plane"]["stages"]
        for stage in REQUEST_STAGES:
            assert "p50_s" in stages[stage] and "p99_s" in stages[stage]
        assert doc["request_plane"]["tail"]["attribution_coverage"] >= 0.95
        assert doc["slo_verdict"] in (doc["slo"]["verdict"],)

    def test_swap_fn_drives_generations(self, glmix):
        artifact, requests = _requests(glmix)
        scorer = GameScorer(artifact)
        metrics = ServingMetrics()
        swap_fn = make_row_swap_fn(scorer, metrics, rows_per_swap=2, seed=1)
        assert swap_fn is not None
        swap_fn()
        swap_fn()
        snap = metrics.snapshot()
        assert snap["swaps"]["num_swaps"] == 2
        assert snap["swaps"]["rows_updated_total"] == 4
