"""LibSVM input format + date-range path expansion tests (reference
LibSVMInputDataFormat / libsvm converter script / DateRangeTest +
pathsForDateRange)."""

import datetime
import os

import numpy as np
import pytest


A1A_SAMPLE = """\
-1 3:1 11:1 14:1 19:1 39:1
+1 5:1 7:1 14:1 19:1 39:1
-1 2:1 11:1
+1 5:1 11:1 14:1
"""


class TestLibSVM:
    def test_read_libsvm(self, tmp_path):
        from photon_ml_tpu.io.libsvm import read_libsvm

        p = tmp_path / "a1a.txt"
        p.write_text(A1A_SAMPLE)
        data, imap = read_libsvm(str(p), feature_dimension=123)
        assert data.num_rows == 4
        np.testing.assert_array_equal(data.labels, [0, 1, 0, 1])
        s = data.feature_shards["features"]
        assert s.dim == 124  # 123 + intercept
        # 1-based index 3 -> column 2
        r0 = s.cols[s.rows == 0]
        assert 2 in r0 and 123 in r0  # feature + intercept
        assert imap.get_index("2") == 2

    def test_zero_based_and_no_intercept(self, tmp_path):
        from photon_ml_tpu.io.libsvm import read_libsvm

        p = tmp_path / "d.txt"
        p.write_text("1 0:2.5 3:1\n-1 1:1\n")
        data, imap = read_libsvm(str(p), zero_based=True, use_intercept=False)
        s = data.feature_shards["features"]
        assert s.dim == 4
        assert s.vals[(s.rows == 0) & (s.cols == 0)][0] == pytest.approx(2.5)

    def test_regression_labels_kept(self, tmp_path):
        from photon_ml_tpu.io.libsvm import read_libsvm

        p = tmp_path / "r.txt"
        p.write_text("2.5 1:1\n-0.5 1:2\n")
        data, _ = read_libsvm(str(p), binarize_labels=False)
        np.testing.assert_allclose(data.labels, [2.5, -0.5])

    def test_converter_round_trip(self, tmp_path):
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )
        from photon_ml_tpu.io.libsvm import libsvm_to_training_example_avro

        p = tmp_path / "a1a.txt"
        p.write_text(A1A_SAMPLE)
        out = tmp_path / "a1a.avro"
        n = libsvm_to_training_example_avro(str(p), str(out))
        assert n == 4
        data, maps, _ = read_game_data(
            str(out),
            {"features": FeatureShardConfiguration(["features"], add_intercept=False)},
        )
        assert data.num_rows == 4
        np.testing.assert_array_equal(data.labels, [0, 1, 0, 1])

    def test_train_glm_libsvm_end_to_end(self, tmp_path, rng):
        """Legacy driver over LibSVM input — the BASELINE config-1 shape."""
        from photon_ml_tpu.cli.train_glm import parse_args, run

        n, d = 300, 20
        X = (rng.random((n, d)) < 0.15).astype(np.float32)
        w = rng.normal(size=d + 1).astype(np.float32)
        z = X @ w[:d] + w[d]
        y = np.where(1 / (1 + np.exp(-z)) > rng.random(n), 1, -1)

        def fmt(split):
            lines = []
            for i in split:
                items = " ".join(
                    f"{j + 1}:1" for j in np.flatnonzero(X[i])
                )
                lines.append(f"{y[i]:+d} {items}")
            return "\n".join(lines) + "\n"

        (tmp_path / "train.txt").write_text(fmt(range(0, 240)))
        (tmp_path / "test.txt").write_text(fmt(range(240, 300)))
        result = run(parse_args([
            "--training-data-dirs", str(tmp_path / "train.txt"),
            "--validation-data-dirs", str(tmp_path / "test.txt"),
            "--input-format", "LIBSVM",
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out"),
            "--regularization-weights", "0.1", "10",
        ]))
        assert result["metrics"][result["best_lambda"]] > 0.6  # AUC


class TestDateRange:
    def test_parse_and_iterate(self):
        from photon_ml_tpu.utils.date_range import DateRange

        r = DateRange.from_dates("20260128-20260202")
        days = list(r.days())
        assert len(days) == 6
        assert days[0] == datetime.date(2026, 1, 28)
        assert days[-1] == datetime.date(2026, 2, 2)
        with pytest.raises(ValueError):
            DateRange.from_dates("20260202-20260128")
        with pytest.raises(ValueError):
            DateRange.from_dates("garbage")

    def test_days_ago(self):
        from photon_ml_tpu.utils.date_range import DateRange

        today = datetime.date(2026, 7, 29)
        r = DateRange.from_days_ago("3-1", today=today)
        assert r.start_date == datetime.date(2026, 7, 26)
        assert r.end_date == datetime.date(2026, 7, 28)
        with pytest.raises(ValueError):
            DateRange.from_days_ago("1-3", today=today)  # inverted

    def test_path_expansion(self, tmp_path):
        from photon_ml_tpu.utils.date_range import paths_for_date_range

        base = tmp_path / "data"
        for day in ("2026/01/30", "2026/01/31", "2026/02/02"):
            (base / day).mkdir(parents=True)
        got = paths_for_date_range([str(base)], "20260129-20260202")
        assert [os.path.relpath(p, base) for p in got] == [
            os.path.join("2026", "01", "30"),
            os.path.join("2026", "01", "31"),
            os.path.join("2026", "02", "02"),
        ]
        # no spec: base dirs unchanged
        assert paths_for_date_range([str(base)]) == [str(base)]
        with pytest.raises(ValueError):
            paths_for_date_range([str(base)], "20260101-20260102", "3-1")

    def test_train_game_with_date_range(self, tmp_path, rng):
        from photon_ml_tpu.io.data_reader import write_training_examples
        import json

        day_dir = tmp_path / "data" / "2026" / "07" / "28"
        day_dir.mkdir(parents=True)
        recs = [
            {"label": float(i % 2),
             "features": [("f", str(j), float(rng.normal())) for j in range(4)]}
            for i in range(100)
        ]
        write_training_examples(str(day_dir / "part-00000.avro"), recs)
        cfg = tmp_path / "g.json"
        cfg.write_text(json.dumps({
            "feature_shards": {"g": {"feature_bags": ["features"]}},
            "coordinates": {"fixed": {"type": "fixed", "feature_shard": "g"}},
        }))
        from photon_ml_tpu.cli.train_game import parse_args, run

        fit = run(parse_args([
            "--train-data-dirs", str(tmp_path / "data"),
            "--train-date-range", "20260727-20260729",
            "--coordinate-config", str(cfg),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out"),
        ]))
        assert fit.model is not None
        with pytest.raises(FileNotFoundError):
            run(parse_args([
                "--train-data-dirs", str(tmp_path / "data"),
                "--train-date-range", "20250101-20250102",
                "--coordinate-config", str(cfg),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "out2"),
            ]))


class TestLibSVMEdgeCases:
    def test_validation_features_beyond_training_dim_dropped(self, tmp_path):
        """a1a-style: the test split has indices the train split never saw —
        they must be dropped, not crash (scoring-over-fixed-index
        semantics)."""
        from photon_ml_tpu.io.libsvm import read_libsvm

        p = tmp_path / "v.txt"
        p.write_text("+1 1:1 500:1\n-1 2:1\n")
        data, _ = read_libsvm(str(p), feature_dimension=10)
        s = data.feature_shards["features"]
        assert s.dim == 11
        assert s.cols.max() == 10  # intercept; 500 dropped

    def test_directory_with_marker_files(self, tmp_path):
        from photon_ml_tpu.io.libsvm import read_libsvm

        d = tmp_path / "data"
        d.mkdir()
        (d / "part-0").write_text("+1 1:1\n")
        (d / "_SUCCESS").write_text("")
        (d / "subdir").mkdir()
        data, _ = read_libsvm(str(d))
        assert data.num_rows == 1

    def test_svm_task_binarizes(self, tmp_path, rng):
        from photon_ml_tpu.cli.train_glm import parse_args, run

        p = tmp_path / "t.txt"
        lines = [f"{'+1' if rng.random() > 0.5 else '-1'} {i % 5 + 1}:1"
                 for i in range(60)]
        p.write_text("\n".join(lines) + "\n")
        result = run(parse_args([
            "--training-data-dirs", str(p),
            "--input-format", "LIBSVM",
            "--task", "SMOOTHED_HINGE_LOSS_LINEAR_SVM",
            "--output-dir", str(tmp_path / "out"),
            "--regularization-weights", "1.0",
        ]))
        assert result["fits"]
