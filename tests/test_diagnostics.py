"""Diagnostics tests, modeled on photon-diagnostics' test suite:
EvaluationTest (metric correctness vs hand computations / sklearn-style
references), BootstrapTrainingTest, FittingDiagnosticIntegTest,
HosmerLemeshowDiagnostic tests, KendallTauAnalysisTest, feature-importance
tests, and reporting render tests."""

import numpy as np
import pytest

from photon_ml_tpu.types import TaskType


class TestEvaluationMetrics:
    def test_regression_metrics(self):
        from photon_ml_tpu.diagnostics.evaluation import evaluate_metrics

        scores = np.array([1.0, 2.0, 3.0])
        labels = np.array([1.5, 2.0, 2.0])
        m = evaluate_metrics(scores, labels, TaskType.LINEAR_REGRESSION)
        assert m["MSE"] == pytest.approx((0.25 + 0 + 1) / 3)
        assert m["RMSE"] == pytest.approx(np.sqrt((0.25 + 0 + 1) / 3))
        assert m["MAE"] == pytest.approx((0.5 + 0 + 1) / 3)

    def test_logistic_metrics_perfect_separation(self):
        from photon_ml_tpu.diagnostics.evaluation import evaluate_metrics

        scores = np.array([-5.0, -3.0, 3.0, 5.0])
        labels = np.array([0.0, 0.0, 1.0, 1.0])
        m = evaluate_metrics(scores, labels, TaskType.LOGISTIC_REGRESSION)
        assert m["Area under ROC"] == pytest.approx(1.0)
        assert m["Area under precision/recall"] == pytest.approx(1.0)
        assert m["Peak F1 score"] == pytest.approx(1.0)

    def test_pr_auc_known_value(self):
        from photon_ml_tpu.diagnostics.evaluation import area_under_pr_curve

        # ordering: pos, neg, pos, neg; PR points at the 4 thresholds:
        # (R,P) = (.5,1), (.5,.5), (1,2/3), (1,.5); MLlib-style trapezoid
        # over ALL threshold points anchored at (0, P_first):
        # (0->.5)*avg(1,1) + 0 + (.5->1)*avg(.5,2/3) + 0
        scores = np.array([4.0, 3.0, 2.0, 1.0])
        labels = np.array([1.0, 0.0, 1.0, 0.0])
        v = area_under_pr_curve(scores, labels)
        assert v == pytest.approx(0.5 * 1.0 + 0.5 * (0.5 + 2 / 3) / 2)

    def test_peak_f1_known_value(self):
        from photon_ml_tpu.diagnostics.evaluation import peak_f1

        scores = np.array([4.0, 3.0, 2.0, 1.0])
        labels = np.array([0.0, 1.0, 1.0, 0.0])
        # best threshold keeps top-3: P=2/3, R=1 -> F1=0.8
        assert peak_f1(scores, labels) == pytest.approx(0.8)


class TestBootstrap:
    def test_coefficient_cis_cover_truth(self, rng):
        from photon_ml_tpu.diagnostics.bootstrap import bootstrap_training

        n, d = 400, 4
        X = rng.normal(size=(n, d))
        w_true = np.array([2.0, -1.0, 0.0, 0.5])
        y = X @ w_true + 0.1 * rng.normal(size=n)

        def train(idx):
            Xi, yi = X[idx], y[idx]
            w = np.linalg.lstsq(Xi, yi, rcond=None)[0]
            mse = float(np.mean((Xi @ w - yi) ** 2))
            return w, {"MSE": mse}

        report = bootstrap_training(train, n, num_samples=20, seed=0)
        assert len(report.coefficient_summaries) == d
        for j, s in enumerate(report.coefficient_summaries):
            assert s.min <= w_true[j] <= s.max
        # the zero coefficient is flagged, the strong ones are not
        assert 2 in report.zero_crossing_indices
        assert 0 not in report.zero_crossing_indices
        assert report.metric_summaries["MSE"].mean < 0.02

    def test_quartile_ordering(self):
        from photon_ml_tpu.diagnostics.bootstrap import CoefficientSummary

        s = CoefficientSummary.from_samples(np.arange(101, dtype=float))
        assert s.q1 <= s.median <= s.q3
        assert s.median == pytest.approx(50.0)


class TestFitting:
    def test_learning_curves_shrink_gap(self, rng):
        from photon_ml_tpu.diagnostics.fitting import fitting_diagnostic

        n, d = 2000, 5
        X = rng.normal(size=(n, d))
        w_true = rng.normal(size=d)
        y = X @ w_true + 0.5 * rng.normal(size=n)

        def train(idx, warm):
            out = {}
            for lam in [1.0]:
                A = X[idx].T @ X[idx] + lam * np.eye(d)
                out[lam] = np.linalg.solve(A, X[idx].T @ y[idx])
            return out

        def evaluate(w, idx):
            err = X[idx] @ w - y[idx]
            return {"MSE": float(np.mean(err**2))}

        reports = fitting_diagnostic(train, evaluate, n, d, seed=1)
        assert set(reports) == {1.0}
        portions, train_vals, test_vals = reports[1.0].metrics["MSE"]
        assert len(portions) == 9  # NUM_TRAINING_PARTITIONS - 1 points
        assert portions == sorted(portions)
        # holdout error at full data ≲ holdout error at small data
        assert test_vals[-1] <= test_vals[0] + 0.05

    def test_too_small_returns_empty(self):
        from photon_ml_tpu.diagnostics.fitting import fitting_diagnostic

        out = fitting_diagnostic(
            lambda idx, warm: {}, lambda m, idx: {}, num_rows=3, dim=10
        )
        assert out == {}


class TestHosmerLemeshow:
    def test_calibrated_model_passes(self, rng):
        from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow_diagnostic

        n = 5000
        p = rng.uniform(0.05, 0.95, size=n)
        y = (rng.random(n) < p).astype(float)  # perfectly calibrated
        rep = hosmer_lemeshow_diagnostic(p, y, num_dimensions=8)
        assert rep.prob_at_chi_squared < 0.99  # not flagged as miscalibrated
        assert rep.degrees_of_freedom == len(rep.bins) - 2
        assert sum(b.count for b in rep.bins) == n

    def test_miscalibrated_model_flagged(self, rng):
        from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow_diagnostic

        n = 5000
        p = rng.uniform(0.05, 0.95, size=n)
        y = (rng.random(n) < np.clip(p + 0.25, 0, 1)).astype(float)
        rep = hosmer_lemeshow_diagnostic(p, y, num_dimensions=8)
        assert rep.prob_at_chi_squared > 0.999
        assert rep.p_value < 1e-3

    def test_bin_count_heuristic(self):
        from photon_ml_tpu.diagnostics.hl import default_bin_count

        assert default_bin_count(10_000, 8) == 10  # dim-bound: 8+2
        assert default_bin_count(100, 100) == 9    # data-bound: .9*10+.1*log1p
        assert default_bin_count(10, 1) == 3       # floor


class TestKendallTau:
    def test_matches_scipy(self, rng):
        from scipy.stats import kendalltau

        from photon_ml_tpu.diagnostics.independence import kendall_tau_analysis

        a = rng.normal(size=200)
        b = 0.5 * a + rng.normal(size=200)
        rep = kendall_tau_analysis(a, b)
        ref_tau, _ = kendalltau(a, b)
        assert rep.tau_beta == pytest.approx(ref_tau, abs=1e-9)
        assert rep.tau_alpha == pytest.approx(ref_tau, abs=1e-9)  # no ties
        assert rep.prob_dependent > 0.99  # strong dependence detected
        assert rep.p_value < 0.01

    def test_independent_low_p(self, rng):
        from photon_ml_tpu.diagnostics.independence import kendall_tau_analysis

        a = rng.normal(size=300)
        b = rng.normal(size=300)
        rep = kendall_tau_analysis(a, b)
        assert abs(rep.tau_alpha) < 0.1
        assert rep.prob_dependent < 0.95

    def test_ties_counted(self):
        from photon_ml_tpu.diagnostics.independence import kendall_tau_analysis

        a = np.array([1.0, 1.0, 2.0, 3.0])
        b = np.array([1.0, 2.0, 2.0, 3.0])
        rep = kendall_tau_analysis(a, b)
        # pairs: (12):tieA, (13):C,(14):C,(23):tieB,(24):C,(34):C
        assert rep.num_concordant == 4
        assert rep.num_discordant == 0
        assert "ties" in rep.message

    def test_error_independence_wrapper(self, rng):
        from photon_ml_tpu.diagnostics.independence import (
            prediction_error_independence,
        )

        scores = rng.normal(size=150)
        labels = scores + rng.normal(size=150)  # error independent of score
        rep = prediction_error_independence(scores, labels)
        assert abs(rep.tau_alpha) < 0.15


class TestFeatureImportance:
    def test_rankings(self):
        from photon_ml_tpu.indexmap import DefaultIndexMap
        from photon_ml_tpu.diagnostics.feature_importance import (
            expected_magnitude_importance,
            variance_importance,
        )

        imap = DefaultIndexMap({f"f{i}": i for i in range(4)})
        coefs = np.array([0.1, -5.0, 2.0, 0.0])
        mean_abs = np.array([10.0, 0.1, 1.0, 1.0])
        rep = expected_magnitude_importance(coefs, mean_abs, imap)
        # importances: 1.0, 0.5, 2.0, 0 -> top = f2
        assert rep.ranked_features[0][0] == "f2"
        assert rep.ranked_features[0][3] == pytest.approx(2.0)

        var = np.array([1.0, 1.0, 1.0, 1.0])
        rep2 = variance_importance(coefs, var, imap)
        assert rep2.ranked_features[0][0] == "f1"  # |-5|*1

    def test_without_summary_falls_back_to_magnitude(self):
        from photon_ml_tpu.diagnostics.feature_importance import (
            expected_magnitude_importance,
        )

        rep = expected_magnitude_importance(np.array([1.0, -3.0]))
        assert rep.ranked_features[0][2] == 1  # index of -3
        assert "Magnitude" in rep.importance_description


class TestReporting:
    def _document(self):
        from photon_ml_tpu.diagnostics.reporting import (
            BulletedList,
            Chapter,
            Document,
            Plot,
            Section,
            SimpleText,
            Table,
        )

        return Document(
            title="Model diagnostics",
            chapters=[
                Chapter("Metrics", [Section("Summary", [
                    SimpleText("All good & well <tested>"),
                    Table(headers=["Metric", "Value"], rows=[("AUC", 0.9)]),
                    BulletedList(["point one", "point two"]),
                ])]),
                Chapter("Curves", [Section("Learning", [
                    Plot("MSE vs portion", "% data", "MSE",
                         series=[("train", [10, 50, 90], [1.0, 0.6, 0.5]),
                                 ("holdout", [10, 50, 90], [1.5, 0.8, 0.6])]),
                ])]),
            ],
        )

    def test_html_rendering(self):
        from photon_ml_tpu.diagnostics.reporting import render_html

        html = render_html(self._document())
        assert "<h2>1. Metrics</h2>" in html
        assert "<h3>2.1. Learning</h3>" in html
        assert "&amp; well &lt;tested&gt;" in html  # escaping
        assert "<svg" in html and "polyline" in html
        assert "<table" in html

    def test_text_rendering(self):
        from photon_ml_tpu.diagnostics.reporting import render_text

        text = render_text(self._document())
        assert "1. Metrics" in text
        assert "[plot: MSE vs portion]" in text

    def test_full_report_assembly(self, tmp_path, rng):
        """End-to-end: all diagnostics on a small logistic fit → HTML file
        (legacy Driver diagnose() parity)."""
        from photon_ml_tpu.diagnostics import (
            bootstrap_training,
            evaluate_metrics,
            expected_magnitude_importance,
            hosmer_lemeshow_diagnostic,
            prediction_error_independence,
        )
        from photon_ml_tpu.diagnostics.report import (
            build_diagnostic_document,
            write_diagnostic_report,
        )

        n, d = 600, 4
        X = rng.normal(size=(n, d))
        w = rng.normal(size=d)
        z = X @ w
        y = (1 / (1 + np.exp(-z)) > rng.random(n)).astype(float)

        def train(idx):
            from scipy.optimize import minimize

            def nll(wv):
                zz = X[idx] @ wv
                return float(np.mean(np.logaddexp(0, zz) - y[idx] * zz))

            res = minimize(nll, np.zeros(d), method="L-BFGS-B")
            m = evaluate_metrics(X[idx] @ res.x, y[idx],
                                 TaskType.LOGISTIC_REGRESSION)
            return res.x, m

        what, metrics = train(np.arange(n))
        scores = X @ what
        probs = 1 / (1 + np.exp(-scores))
        doc = build_diagnostic_document(
            "diag",
            metrics=metrics,
            bootstrap=bootstrap_training(train, n, num_samples=4, seed=2),
            hosmer_lemeshow=hosmer_lemeshow_diagnostic(probs, y, d),
            independence=prediction_error_independence(scores, y,
                                                       max_items=150),
            importance=expected_magnitude_importance(what),
        )
        out = write_diagnostic_report(str(tmp_path / "report"), doc)
        html = open(out).read()
        assert "Hosmer-Lemeshow" in html
        assert "Bootstrap" in html
        assert "Feature importance" in html
