"""Extra external-oracle gates against sklearn (the same assumed-correct-
implementation discipline as tests/test_oracle.py, reference
DriverTest.scala:84-85): the GP posterior math and the weighted-AUC
evaluator are checked value-for-value against independent sklearn
implementations of the identical definitions."""

import numpy as np
import pytest

pytest.importorskip("sklearn")


class TestGpPosteriorOracle:
    """GaussianProcessModel (GPML Alg 2.1, hyperparameter/gp.py) vs
    sklearn.gaussian_process with a FIXED kernel (no hyperparameter
    sampling on either side): posterior mean and variance must agree to
    float tolerance."""

    def _problem(self, seed=0, n=24, d=3, nq=17):
        rng = np.random.default_rng(seed)
        x = rng.uniform(-2, 2, (n, d))
        y = np.sin(x).sum(axis=1) + 0.05 * rng.standard_normal(n)
        xq = rng.uniform(-2, 2, (nq, d))
        return x, y, xq

    @pytest.mark.parametrize("ls", [0.7, 1.5])
    def test_matern52_posterior_matches_sklearn(self, ls):
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern

        from photon_ml_tpu.hyperparameter.gp import (
            _JITTER,
            GaussianProcessModel,
        )
        from photon_ml_tpu.hyperparameter.kernels import Matern52

        x, y, xq = self._problem()
        ours = GaussianProcessModel(
            x, y, y_mean=0.0, kernels=[Matern52(length_scale=np.array([ls]))]
        )
        mean, var = ours.predict(xq)

        sk = GaussianProcessRegressor(
            kernel=Matern(length_scale=ls, nu=2.5),
            alpha=_JITTER, optimizer=None,
        ).fit(x, y)
        sk_mean, sk_std = sk.predict(xq, return_std=True)
        np.testing.assert_allclose(mean, sk_mean, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(var, sk_std**2, rtol=1e-4, atol=1e-8)

    def test_rbf_posterior_matches_sklearn(self):
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import RBF as SkRBF

        from photon_ml_tpu.hyperparameter.gp import (
            _JITTER,
            GaussianProcessModel,
        )
        from photon_ml_tpu.hyperparameter.kernels import RBF

        x, y, xq = self._problem(seed=3)
        ours = GaussianProcessModel(
            x, y, y_mean=0.0, kernels=[RBF(length_scale=np.array([1.1]))]
        )
        mean, var = ours.predict(xq)
        sk = GaussianProcessRegressor(
            kernel=SkRBF(length_scale=1.1), alpha=_JITTER, optimizer=None,
        ).fit(x, y)
        sk_mean, sk_std = sk.predict(xq, return_std=True)
        np.testing.assert_allclose(mean, sk_mean, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(var, sk_std**2, rtol=1e-4, atol=1e-8)

    def test_anisotropic_matern_matches_sklearn(self):
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern

        from photon_ml_tpu.hyperparameter.gp import (
            _JITTER,
            GaussianProcessModel,
        )
        from photon_ml_tpu.hyperparameter.kernels import Matern52

        x, y, xq = self._problem(seed=5)
        ls = np.array([0.6, 1.3, 2.2])
        ours = GaussianProcessModel(
            x, y, y_mean=0.0, kernels=[Matern52(length_scale=ls)]
        )
        mean, var = ours.predict(xq)
        sk = GaussianProcessRegressor(
            kernel=Matern(length_scale=ls, nu=2.5),
            alpha=_JITTER, optimizer=None,
        ).fit(x, y)
        sk_mean, sk_std = sk.predict(xq, return_std=True)
        np.testing.assert_allclose(mean, sk_mean, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(var, sk_std**2, rtol=1e-4, atol=1e-8)


class TestKendallTauScipyOracle:
    """diagnostics/independence.py's tau-beta vs scipy.stats.kendalltau
    (the standard tie-corrected tau-b), with and without ties."""

    def test_continuous_no_ties(self):
        from scipy.stats import kendalltau

        from photon_ml_tpu.diagnostics.independence import (
            kendall_tau_analysis,
        )

        rng = np.random.default_rng(0)
        a = rng.standard_normal(300)
        b = 0.6 * a + 0.8 * rng.standard_normal(300)
        rep = kendall_tau_analysis(a, b)
        ref = kendalltau(a, b)
        np.testing.assert_allclose(rep.tau_beta, ref.statistic, atol=1e-12)
        # without ties tau-alpha == tau-beta
        np.testing.assert_allclose(rep.tau_alpha, ref.statistic, atol=1e-12)

    def test_heavy_ties(self):
        from scipy.stats import kendalltau

        from photon_ml_tpu.diagnostics.independence import (
            kendall_tau_analysis,
        )

        rng = np.random.default_rng(1)
        a = rng.integers(0, 5, 400).astype(float)
        b = (a + rng.integers(0, 3, 400)).astype(float)
        rep = kendall_tau_analysis(a, b)
        ref = kendalltau(a, b)  # scipy default is tau-b
        np.testing.assert_allclose(rep.tau_beta, ref.statistic, atol=1e-12)


class TestPrCurveSklearnOracle:
    """diagnostics/evaluation.py's PR sweep vs
    sklearn.metrics.precision_recall_curve: the (precision, recall) points
    at each distinct threshold must coincide (our PR-AUC then integrates
    them with MLlib trapezoid semantics, which sklearn's
    average_precision deliberately does not — the POINTS are the
    comparable object). Peak F1 is additionally checked against a
    brute-force sklearn f1_score sweep."""

    def test_pr_points_match_sklearn(self):
        from sklearn.metrics import precision_recall_curve

        from photon_ml_tpu.diagnostics.evaluation import (
            _precision_recall_points,
        )

        rng = np.random.default_rng(3)
        n = 300
        y = (rng.random(n) < 0.35).astype(np.float64)
        s = np.round(rng.standard_normal(n), 1)  # ties
        p_ours, r_ours = _precision_recall_points(s, y, None)
        p_sk, r_sk, thr = precision_recall_curve(y, s)
        # sklearn returns ascending thresholds + a final (1, 0) anchor,
        # and (release-dependent) may truncate at full recall; ours
        # returns ALL descending distinct thresholds. Align on the
        # thresholds sklearn kept.
        p_sk, r_sk, thr = p_sk[:-1][::-1], r_sk[:-1][::-1], thr[::-1]
        uniq_desc = np.unique(s)[::-1]
        keep = np.isin(uniq_desc, thr)
        np.testing.assert_allclose(p_ours[keep], p_sk, atol=1e-12)
        np.testing.assert_allclose(r_ours[keep], r_sk, atol=1e-12)

    def test_peak_f1_matches_brute_force(self):
        from sklearn.metrics import f1_score

        from photon_ml_tpu.diagnostics.evaluation import peak_f1

        rng = np.random.default_rng(4)
        n = 200
        y = (rng.random(n) < 0.4).astype(np.float64)
        s = rng.standard_normal(n)
        ours = peak_f1(s, y, None)
        best = max(
            f1_score(y, (s >= t).astype(int)) for t in np.unique(s)
        )
        np.testing.assert_allclose(ours, best, atol=1e-12)


class TestAucSklearnOracle:
    """Both AUC implementations (the on-device rank-sum and its numpy
    twin) vs sklearn.metrics.roc_auc_score, including ties and sample
    weights (evaluation/evaluators.py AUC semantics)."""

    @pytest.mark.parametrize("seed", [0, 1])
    def test_weighted_auc_matches_sklearn(self, seed):
        from sklearn.metrics import roc_auc_score

        from photon_ml_tpu.evaluation.evaluators import (
            _np_auc,
            area_under_roc_curve,
        )

        rng = np.random.default_rng(seed)
        n = 500
        y = (rng.random(n) < 0.4).astype(np.float32)
        # quantized scores force tie groups
        s = np.round(rng.standard_normal(n), 1).astype(np.float32)
        w = rng.uniform(0.1, 3.0, n).astype(np.float32)
        ref = roc_auc_score(y, s, sample_weight=w)
        np.testing.assert_allclose(_np_auc(s, y, w), ref, atol=1e-6)
        np.testing.assert_allclose(
            float(area_under_roc_curve(s, y, w)), ref, atol=1e-5
        )

    def test_unweighted_auc_matches_sklearn(self):
        from sklearn.metrics import roc_auc_score

        from photon_ml_tpu.evaluation.evaluators import (
            _np_auc,
            area_under_roc_curve,
        )

        rng = np.random.default_rng(2)
        n = 400
        y = (rng.random(n) < 0.5).astype(np.float32)
        s = rng.standard_normal(n).astype(np.float32)
        w = np.ones(n, np.float32)
        ref = roc_auc_score(y, s)
        np.testing.assert_allclose(_np_auc(s, y, w), ref, atol=1e-6)
        np.testing.assert_allclose(
            float(area_under_roc_curve(s, y, w)), ref, atol=1e-5
        )
