"""Evaluator tests: AUC vs brute-force pair counting (with ties and weights),
losses, grouped metrics, precision@k, better_than direction."""

import numpy as np
import pytest

from photon_ml_tpu.evaluation import (
    AUC,
    RMSE,
    MultiEvaluator,
    PrecisionAtK,
    area_under_roc_curve,
)


def _auc_brute(scores, labels, weights=None):
    w = np.ones_like(scores) if weights is None else weights
    pos = labels > 0.5
    num = den = 0.0
    for i in np.where(pos)[0]:
        for j in np.where(~pos)[0]:
            pair_w = w[i] * w[j]
            den += pair_w
            if scores[i] > scores[j]:
                num += pair_w
            elif scores[i] == scores[j]:
                num += 0.5 * pair_w
    return num / den


def test_auc_matches_brute_force(rng):
    scores = rng.normal(size=60).astype(np.float32)
    labels = (rng.random(60) > 0.4).astype(np.float32)
    np.testing.assert_allclose(
        AUC.evaluate(scores, labels), _auc_brute(scores, labels), rtol=1e-5
    )


def test_auc_with_ties_and_weights(rng):
    scores = np.round(rng.normal(size=80), 1).astype(np.float32)  # many ties
    labels = (rng.random(80) > 0.5).astype(np.float32)
    weights = (rng.random(80) * 2 + 0.1).astype(np.float32)
    np.testing.assert_allclose(
        AUC.evaluate(scores, labels, weights),
        _auc_brute(scores, labels, weights),
        rtol=1e-4,
    )


def test_auc_perfect_and_degenerate():
    assert AUC.evaluate([0.1, 0.2, 0.8, 0.9], [0, 0, 1, 1]) == pytest.approx(1.0)
    assert AUC.evaluate([0.9, 0.8, 0.2, 0.1], [0, 0, 1, 1]) == pytest.approx(0.0)
    assert np.isnan(AUC.evaluate([0.1, 0.2], [1, 1]))  # one class


def test_rmse_weighted():
    s = np.array([1.0, 3.0], dtype=np.float32)
    y = np.array([0.0, 0.0], dtype=np.float32)
    w = np.array([3.0, 1.0], dtype=np.float32)
    # weighted mse = (3*1 + 1*9)/4 = 3
    np.testing.assert_allclose(RMSE.evaluate(s, y, w), np.sqrt(3.0), rtol=1e-6)


def test_better_than_direction_and_nan():
    assert AUC.better_than(0.8, 0.7)
    assert not AUC.better_than(0.6, 0.7)
    assert RMSE.better_than(1.0, 2.0)
    assert AUC.better_than(0.5, float("nan"))
    assert not AUC.better_than(float("nan"), 0.5)


def test_precision_at_k():
    scores = np.array([0.9, 0.8, 0.7, 0.1], dtype=np.float32)
    labels = np.array([1, 0, 1, 1], dtype=np.float32)
    assert PrecisionAtK(2).evaluate(scores, labels) == pytest.approx(0.5)
    assert PrecisionAtK(3).evaluate(scores, labels) == pytest.approx(2 / 3)


def test_multi_evaluator_grouped_auc(rng):
    n = 120
    groups = rng.integers(0, 4, size=n)
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.random(n) > 0.5).astype(np.float32)
    # make group 3 single-class -> skipped
    labels[groups == 3] = 1.0
    ev = MultiEvaluator(base=AUC, group_ids=tuple(groups.tolist()))
    got = ev.evaluate(scores, labels)
    expected = np.mean(
        [
            _auc_brute(scores[groups == g], labels[groups == g])
            for g in range(3)
        ]
    )
    np.testing.assert_allclose(got, expected, rtol=1e-5)
