"""Sharded device-resident serving tests.

The load-bearing guarantees, per ISSUE acceptance criteria:

- the sharded scorer is BITWISE equal to the single-table ``GameScorer``
  on the same requests, for any shard count — the stacked ``[S, cap+1]``
  gather must reproduce the exact rows, and the accumulation order is
  shared, so scores match bit for bit, not to a tolerance;
- cold entities (beyond the device budget, or absent from the model)
  degrade to the FE-only left-join score through the zero cold slot;
- one compiled XLA program per (bucket, shard-layout) signature: replaying
  traffic after warmup adds ZERO retraces, including while the admission
  tier scatters rows in the background;
- routing publication ordering: a row is never routable before its bytes
  are written on every replica, and eviction unpublishes first;
- the continuous microbatcher forms buckets to a deadline, backpressures
  at ``max_queue``, and resolves stranded handles on stop;
- multi-scorer mode: replicas share one routing index and agree on every
  score; a coordinated hot swap keeps all replicas on one generation.
"""

import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.indexmap import DefaultIndexMap
from photon_ml_tpu.serving import (
    AdmissionController,
    ContinuousBatcher,
    CoordinatedHotSwap,
    GameScorer,
    HotSwapManager,
    ScoreRequest,
    ServingArtifact,
    ServingTable,
    ShardedGameScorer,
    build_routing,
    replay_requests,
)
from photon_ml_tpu.types import TaskType

N_ENT = 64
D_RE = 4
D_FE = 16


def _artifact(n_ent=N_ENT, seed=5):
    rng = np.random.default_rng(seed)
    return ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=(rng.standard_normal(D_FE) * 0.1).astype(np.float32),
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=(
                    rng.standard_normal((n_ent, D_RE)) * 0.3
                ).astype(np.float32),
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(n_ent)}
                ),
            ),
        },
        model_name="sharded-test",
    )


def _requests(n, n_ent=N_ENT, seed=9, ghost_every=0, missing_every=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        if missing_every and i % missing_every == 0:
            ids = {}
        elif ghost_every and i % ghost_every == 0:
            ids = {"userId": f"ghost-{i}"}
        else:
            ids = {"userId": f"u{int(rng.integers(0, n_ent))}"}
        out.append(
            ScoreRequest(
                request_id=f"r{i}",
                features={
                    "global": {
                        int(c): float(v)
                        for c, v in zip(
                            rng.integers(0, D_FE, 6), rng.standard_normal(6)
                        )
                    },
                    "per_user": {
                        j: float(v)
                        for j, v in enumerate(rng.standard_normal(D_RE))
                    },
                },
                entity_ids=ids,
                offset=float(rng.standard_normal() * 0.1),
            )
        )
    return out


MAX_NNZ = {"global": 6, "per_user": D_RE}


class TestShardedParity:
    @pytest.mark.parametrize("num_shards", [1, 2, 4])
    def test_bitwise_parity_with_single_table(self, num_shards):
        """Acceptance: sharded gather == single-table gather bit for bit,
        including ghost entities (FE-only) and id-less requests."""
        artifact = _artifact()
        reqs = _requests(48, ghost_every=7, missing_every=11)
        want = GameScorer(artifact, max_nnz=MAX_NNZ).score_batch(
            reqs, bucket_size=48
        )
        sharded = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=num_shards
        )
        got = sharded.score_batch(reqs, bucket_size=48)
        for g, w in zip(got, want):
            assert g.request_id == w.request_id
            assert g.score == w.score  # bitwise, not allclose
            assert g.mean == w.mean
            assert g.cold_coordinates == w.cold_coordinates

    def test_cold_entities_degrade_to_fe_only(self):
        """A ghost entity's score equals the same request scored with no
        entity id at all (the zero cold slot contributes nothing)."""
        artifact = _artifact()
        base = _requests(8)
        ghost = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": "nobody"}, offset=r.offset,
            )
            for r in base
        ]
        bare = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={}, offset=r.offset,
            )
            for r in base
        ]
        scorer = ShardedGameScorer(artifact, max_nnz=MAX_NNZ, num_shards=2)
        got_ghost = scorer.score_batch(ghost, bucket_size=8)
        got_bare = scorer.score_batch(bare, bucket_size=8)
        for g, b in zip(got_ghost, got_bare):
            assert g.score == b.score
            assert g.cold_coordinates == ("per_user",)

    def test_budget_limited_scorer_serves_tail_fe_only_then_admits(self):
        """Beyond-budget entities score FE-only until admission copies
        their rows on-device; after a drain they match the full table."""
        artifact = _artifact()
        reqs = _requests(32, seed=3)
        want = GameScorer(artifact, max_nnz=MAX_NNZ).score_batch(
            reqs, bucket_size=32
        )
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=32
        )
        admission = AdmissionController([scorer], admit_batch=8)
        scorer.attach_admission(admission)
        admission.warmup()
        first = scorer.score_batch(reqs, bucket_size=32)
        deferred_ids = {
            i for i, r in enumerate(first) if r.cold_coordinates
        }
        assert deferred_ids, "fixture must exercise the cold tail"
        admission.drain()
        second = scorer.score_batch(reqs, bucket_size=32)
        for i, (g, w) in enumerate(zip(second, want)):
            if not g.cold_coordinates:
                assert g.score == w.score, i
        # the drain made at least part of the tail resident
        assert sum(1 for r in second if r.cold_coordinates) < len(
            deferred_ids
        )


class TestRouting:
    def test_cyclic_layout_and_cold_slot(self):
        routing = build_routing({"c": 10}, num_shards=2)["c"]
        shards, slots, deferred = routing.route(
            np.array([0, 1, 2, 3, -1], dtype=np.int64)
        )
        # row r -> (shard r % S, slot r // S)
        assert shards.tolist()[:4] == [0, 1, 0, 1]
        assert slots.tolist()[:4] == [0, 0, 1, 1]
        assert slots[4] == routing.cold_slot and shards[4] == 0
        assert deferred.size == 0
        assert routing.cold_lookups == 1 and routing.resident_lookups == 4

    def test_budget_splits_resident_and_deferred(self):
        routing = build_routing(
            {"c": 100}, num_shards=2, device_budget_rows=16
        )["c"]
        rows = np.arange(40, dtype=np.int64)
        _, slots, deferred = routing.route(rows)
        resident = slots != routing.cold_slot
        assert int(resident.sum()) == routing.base_rows
        assert set(deferred.tolist()) == set(
            rows[~resident].tolist()
        )

    def test_allocate_publish_evict_ordering(self):
        routing = build_routing(
            {"c": 100}, num_shards=2, device_budget_rows=16
        )["c"]
        free0 = routing.free_slots
        assert free0 > 0
        # admit `free0` rows: all slots come from the free list
        rows = np.arange(50, 50 + free0, dtype=np.int64)
        shards, slots, evicted = routing.allocate(free0)
        assert evicted == []
        # not routable until published
        _, s2, _ = routing.route(rows)
        assert (s2 == routing.cold_slot).all()
        routing.publish(rows, shards, slots)
        _, s3, _ = routing.route(rows)
        assert (s3 != routing.cold_slot).all()
        # next allocate must evict the OLDEST admitted rows, unpublishing
        # them before their slots are handed out
        _, _, evicted = routing.allocate(2)
        assert evicted == [50, 51]
        assert not routing.is_resident(50)
        assert not routing.is_resident(51)

    def test_allocate_raises_without_headroom(self):
        routing = build_routing({"c": 4}, num_shards=2)["c"]
        # full-residency layout: every slot holds a base row
        if routing.free_slots == 0 and not routing._admitted:
            with pytest.raises(RuntimeError, match="headroom"):
                routing.allocate(1)


class TestAdmission:
    def _pair(self, budget=32, admit=8, n_ent=N_ENT):
        artifact = _artifact(n_ent=n_ent)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2,
            device_budget_rows=budget,
        )
        admission = AdmissionController([scorer], admit_batch=admit)
        scorer.attach_admission(admission)
        admission.warmup()
        return scorer, admission

    def test_note_deferred_dedups_and_keeps_order(self):
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.array([40, 41, 40, 42]))
        admission.note_deferred("per_user", np.array([41, 43]))
        assert admission.queue_depth == 4
        assert admission.deferred_total == 6

    def test_queue_overflow_drops(self):
        scorer, _ = self._pair()
        admission = AdmissionController(
            [scorer], admit_batch=8, max_queue=4
        )
        admission.note_deferred("per_user", np.arange(40, 50))
        assert admission.queue_depth == 4
        assert admission.dropped_total == 6

    def test_capacity_cap_requeues_overflow_at_head(self):
        """A step can only claim free+evictable slots; overflow rows go
        back to the queue head so the next step admits them first."""
        scorer, admission = self._pair(budget=32, admit=32)
        routing = scorer.routing["per_user"]
        capacity = routing.free_slots + len(routing._admitted)
        over = np.arange(
            routing.base_rows, routing.base_rows + capacity + 3,
            dtype=np.int64,
        )
        admission.note_deferred("per_user", over)
        admitted = admission.step()
        assert admitted == capacity
        assert admission.queue_depth == 3
        # requeued rows are the ones beyond capacity, in order
        q = list(admission._queues["per_user"])
        assert q == over[capacity:].tolist()

    def test_warmup_precompiles_the_scatter(self):
        """The fixed-shape admission scatter compiles during warmup, not
        during the first live admit (which must stay copy-only)."""
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.array([40, 41]))
        before = scorer.compile_count
        admitted = admission.step()
        assert admitted == 2
        assert scorer.compile_count == before  # score fn untouched
        assert scorer.routing["per_user"].is_resident(40)

    def test_background_thread_drains(self):
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.arange(40, 56))
        admission.start(interval_s=0.001)
        try:
            deadline = time.time() + 5.0
            while admission.queue_depth and time.time() < deadline:
                time.sleep(0.005)
        finally:
            admission.stop()
        assert admission.queue_depth == 0
        assert admission.admitted_total == 16

    def test_multi_replica_rows_written_everywhere_before_publish(self):
        """Multi-scorer mode: an admitted row gathers identical (real)
        bytes from every replica — content lands on all devices before
        routing publishes it."""
        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2,
                device_budget_rows=32, routing=routing,
            )
            routing = s.routing
            scorers.append(s)
        admission = AdmissionController(scorers, admit_batch=8)
        for s in scorers:
            s.attach_admission(admission)
        admission.warmup()
        reqs = _requests(32, seed=3)
        scorers[0].score_batch(reqs, bucket_size=32)
        admission.drain()
        a = scorers[0].score_batch(reqs, bucket_size=32)
        b = scorers[1].score_batch(reqs, bucket_size=32)
        for x, y in zip(a, b):
            assert x.score == y.score


class TestCompileDiscipline:
    def test_zero_post_warmup_retraces_with_admission(self):
        """Acceptance: after one warmup pass per bucket, replaying traffic
        (with background admission scattering rows) adds zero compiles."""
        artifact = _artifact()
        reqs = _requests(96, seed=21)
        buckets = (1, 4, 16, 32)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=32
        )
        for b in buckets:
            scorer.score_batch(reqs[:b], bucket_size=b)
        warm = scorer.compile_count
        assert warm == len(buckets)
        admission = AdmissionController([scorer], admit_batch=8)
        scorer.attach_admission(admission)
        admission.warmup()
        results, snapshot = replay_requests(
            [scorer], reqs, bucket_sizes=buckets,
            model_id="sharded-test", continuous=True,
            max_wait_s=0.001, max_queue=64, admission=admission,
        )
        assert len(results) == len(reqs)
        assert scorer.compile_count == warm
        assert snapshot["residency"]["per_user"]["resident_lookups"] > 0


class TestContinuousBatcher:
    def _scorer(self):
        return ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )

    def test_full_bucket_drains_without_deadline(self):
        scorer = self._scorer()
        reqs = _requests(16, seed=2)
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=10.0, max_queue=32
        ) as batcher:
            handles = batcher.submit_many(reqs)
            got = [h.result(timeout=10.0) for h in handles]
        want = scorer.score_batch(reqs, bucket_size=16)
        assert [g.score for g in got] == [w.score for w in want]

    def test_deadline_drains_partial_bucket(self):
        scorer = self._scorer()
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=0.005, max_queue=32
        ) as batcher:
            h = batcher.submit(_requests(1, seed=4)[0])
            got = h.result(timeout=10.0)
        assert got.request_id == "r0"

    def test_backpressure_bounds_queue(self):
        scorer = self._scorer()
        reqs = _requests(24, seed=6)
        batcher = ContinuousBatcher(
            scorer, bucket_sizes=(8,), max_wait_s=0.001, max_queue=8
        )
        with batcher:
            handles = batcher.submit_many(reqs)  # blocks internally, no error
            assert len(handles) == 24
            for h in handles:
                h.result(timeout=10.0)
        assert batcher.queue_depth == 0

    def test_stop_resolves_stranded_handles(self):
        scorer = self._scorer()
        batcher = ContinuousBatcher(
            scorer, bucket_sizes=(8,), max_wait_s=30.0, max_queue=8
        )
        batcher.start()
        h = batcher.submit(_requests(1, seed=8)[0])
        batcher.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            h.result(timeout=1.0)

    def test_submit_after_stop_raises(self):
        scorer = self._scorer()
        batcher = ContinuousBatcher(scorer, bucket_sizes=(8,))
        batcher.start()
        batcher.stop()
        with pytest.raises(RuntimeError):
            batcher.submit(_requests(1)[0])

    def test_concurrent_submitters_all_resolve(self):
        scorer = self._scorer()
        reqs = _requests(60, seed=12)
        out = {}
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=0.002, max_queue=32
        ) as batcher:
            def worker(chunk):
                for h, r in zip(batcher.submit_many(chunk), chunk):
                    out[r.request_id] = h.result(timeout=10.0)
            threads = [
                threading.Thread(target=worker, args=(reqs[i::3],))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert len(out) == 60
        want = {
            w.request_id: w.score
            for w in scorer.score_batch(reqs, bucket_size=64)
        }
        for rid, res in out.items():
            assert res.score == want[rid]


class TestCoordinatedHotSwap:
    def test_replicas_swap_as_one_generation(self):
        from photon_ml_tpu.incremental.delta import build_delta

        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2, routing=routing
            )
            routing = s.routing
            scorers.append(s)
        managers = [HotSwapManager(s) for s in scorers]
        coord = CoordinatedHotSwap(managers)
        delta = build_delta(
            {"per_user": {"u3": {0: 9.0, 2: -1.5}}}, artifact,
            generation=1,
        )
        reports = coord.apply_delta(delta)
        assert len(reports) == 2
        assert all(not r.rolled_back for r in reports)
        assert coord.generation == 1
        req = _requests(4, seed=30)
        req = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": "u3"}, offset=r.offset,
            )
            for r in req
        ]
        a = scorers[0].score_batch(req, bucket_size=4)
        b = scorers[1].score_batch(req, bucket_size=4)
        for x, y in zip(a, b):
            assert x.score == y.score
