"""Sharded device-resident serving tests.

The load-bearing guarantees, per ISSUE acceptance criteria:

- the sharded scorer is BITWISE equal to the single-table ``GameScorer``
  on the same requests, for any shard count — the stacked ``[S, cap+1]``
  gather must reproduce the exact rows, and the accumulation order is
  shared, so scores match bit for bit, not to a tolerance;
- cold entities (beyond the device budget, or absent from the model)
  degrade to the FE-only left-join score through the zero cold slot;
- one compiled XLA program per (bucket, shard-layout) signature: replaying
  traffic after warmup adds ZERO retraces, including while the admission
  tier scatters rows in the background;
- routing publication ordering: a row is never routable before its bytes
  are written on every replica, and eviction unpublishes first;
- the continuous microbatcher forms buckets to a deadline, backpressures
  at ``max_queue``, and resolves stranded handles on stop;
- multi-scorer mode: replicas share one routing index and agree on every
  score; a coordinated hot swap keeps all replicas on one generation.
"""

import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.indexmap import DefaultIndexMap
from photon_ml_tpu.serving import (
    AdmissionController,
    ContinuousBatcher,
    CoordinateRouting,
    CoordinatedHotSwap,
    GameScorer,
    HotSwapManager,
    ScoreRequest,
    ServingArtifact,
    ServingTable,
    ShardedGameScorer,
    build_routing,
    replay_requests,
)
from photon_ml_tpu.types import TaskType

N_ENT = 64
D_RE = 4
D_FE = 16


def _artifact(n_ent=N_ENT, seed=5):
    rng = np.random.default_rng(seed)
    return ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=(rng.standard_normal(D_FE) * 0.1).astype(np.float32),
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=(
                    rng.standard_normal((n_ent, D_RE)) * 0.3
                ).astype(np.float32),
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(n_ent)}
                ),
            ),
        },
        model_name="sharded-test",
    )


def _requests(n, n_ent=N_ENT, seed=9, ghost_every=0, missing_every=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        if missing_every and i % missing_every == 0:
            ids = {}
        elif ghost_every and i % ghost_every == 0:
            ids = {"userId": f"ghost-{i}"}
        else:
            ids = {"userId": f"u{int(rng.integers(0, n_ent))}"}
        out.append(
            ScoreRequest(
                request_id=f"r{i}",
                features={
                    "global": {
                        int(c): float(v)
                        for c, v in zip(
                            rng.integers(0, D_FE, 6), rng.standard_normal(6)
                        )
                    },
                    "per_user": {
                        j: float(v)
                        for j, v in enumerate(rng.standard_normal(D_RE))
                    },
                },
                entity_ids=ids,
                offset=float(rng.standard_normal() * 0.1),
            )
        )
    return out


MAX_NNZ = {"global": 6, "per_user": D_RE}


class TestShardedParity:
    @pytest.mark.parametrize("num_shards", [1, 2, 4])
    def test_bitwise_parity_with_single_table(self, num_shards):
        """Acceptance: sharded gather == single-table gather bit for bit,
        including ghost entities (FE-only) and id-less requests."""
        artifact = _artifact()
        reqs = _requests(48, ghost_every=7, missing_every=11)
        want = GameScorer(artifact, max_nnz=MAX_NNZ).score_batch(
            reqs, bucket_size=48
        )
        sharded = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=num_shards
        )
        got = sharded.score_batch(reqs, bucket_size=48)
        for g, w in zip(got, want):
            assert g.request_id == w.request_id
            assert g.score == w.score  # bitwise, not allclose
            assert g.mean == w.mean
            assert g.cold_coordinates == w.cold_coordinates

    def test_cold_entities_degrade_to_fe_only(self):
        """A ghost entity's score equals the same request scored with no
        entity id at all (the zero cold slot contributes nothing)."""
        artifact = _artifact()
        base = _requests(8)
        ghost = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": "nobody"}, offset=r.offset,
            )
            for r in base
        ]
        bare = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={}, offset=r.offset,
            )
            for r in base
        ]
        scorer = ShardedGameScorer(artifact, max_nnz=MAX_NNZ, num_shards=2)
        got_ghost = scorer.score_batch(ghost, bucket_size=8)
        got_bare = scorer.score_batch(bare, bucket_size=8)
        for g, b in zip(got_ghost, got_bare):
            assert g.score == b.score
            assert g.cold_coordinates == ("per_user",)

    def test_budget_limited_scorer_serves_tail_fe_only_then_admits(self):
        """Beyond-budget entities score FE-only until admission copies
        their rows on-device; after a drain they match the full table."""
        artifact = _artifact()
        reqs = _requests(32, seed=3)
        want = GameScorer(artifact, max_nnz=MAX_NNZ).score_batch(
            reqs, bucket_size=32
        )
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=32
        )
        admission = AdmissionController([scorer], admit_batch=8)
        scorer.attach_admission(admission)
        admission.warmup()
        first = scorer.score_batch(reqs, bucket_size=32)
        deferred_ids = {
            i for i, r in enumerate(first) if r.cold_coordinates
        }
        assert deferred_ids, "fixture must exercise the cold tail"
        admission.drain()
        second = scorer.score_batch(reqs, bucket_size=32)
        for i, (g, w) in enumerate(zip(second, want)):
            if not g.cold_coordinates:
                assert g.score == w.score, i
        # the drain made at least part of the tail resident
        assert sum(1 for r in second if r.cold_coordinates) < len(
            deferred_ids
        )


class TestRouting:
    def test_cyclic_layout_and_cold_slot(self):
        routing = build_routing({"c": 10}, num_shards=2)["c"]
        shards, slots, deferred = routing.route(
            np.array([0, 1, 2, 3, -1], dtype=np.int64)
        )
        # row r -> (shard r % S, slot r // S)
        assert shards.tolist()[:4] == [0, 1, 0, 1]
        assert slots.tolist()[:4] == [0, 0, 1, 1]
        assert slots[4] == routing.cold_slot and shards[4] == 0
        assert deferred.size == 0
        assert routing.cold_lookups == 1 and routing.resident_lookups == 4

    def test_budget_splits_resident_and_deferred(self):
        routing = build_routing(
            {"c": 100}, num_shards=2, device_budget_rows=16
        )["c"]
        rows = np.arange(40, dtype=np.int64)
        _, slots, deferred = routing.route(rows)
        resident = slots != routing.cold_slot
        assert int(resident.sum()) == routing.base_rows
        assert set(deferred.tolist()) == set(
            rows[~resident].tolist()
        )

    def test_allocate_publish_evict_ordering(self):
        routing = build_routing(
            {"c": 100}, num_shards=2, device_budget_rows=16
        )["c"]
        free0 = routing.free_slots
        assert free0 > 0
        # admit `free0` rows: all slots come from the free list
        rows = np.arange(50, 50 + free0, dtype=np.int64)
        shards, slots, evicted = routing.allocate(free0)
        assert evicted == []
        # not routable until published
        _, s2, _ = routing.route(rows)
        assert (s2 == routing.cold_slot).all()
        routing.publish(rows, shards, slots)
        _, s3, _ = routing.route(rows)
        assert (s3 != routing.cold_slot).all()
        # next allocate must evict the OLDEST admitted rows, unpublishing
        # them before their slots are handed out
        _, _, evicted = routing.allocate(2)
        assert evicted == [50, 51]
        assert not routing.is_resident(50)
        assert not routing.is_resident(51)

    def test_allocate_raises_without_headroom(self):
        routing = build_routing({"c": 4}, num_shards=2)["c"]
        # full-residency layout: every slot holds a base row
        if routing.free_slots == 0 and not routing._admitted:
            with pytest.raises(RuntimeError, match="headroom"):
                routing.allocate(1)


class TestAdmission:
    def _pair(self, budget=32, admit=8, n_ent=N_ENT):
        artifact = _artifact(n_ent=n_ent)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2,
            device_budget_rows=budget,
        )
        admission = AdmissionController([scorer], admit_batch=admit)
        scorer.attach_admission(admission)
        admission.warmup()
        return scorer, admission

    def test_note_deferred_dedups_and_keeps_order(self):
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.array([40, 41, 40, 42]))
        admission.note_deferred("per_user", np.array([41, 43]))
        assert admission.queue_depth == 4
        assert admission.deferred_total == 6

    def test_queue_overflow_drops(self):
        scorer, _ = self._pair()
        admission = AdmissionController(
            [scorer], admit_batch=8, max_queue=4
        )
        admission.note_deferred("per_user", np.arange(40, 50))
        assert admission.queue_depth == 4
        assert admission.dropped_total == 6

    def test_capacity_cap_requeues_overflow_at_head(self):
        """A step can only claim free+evictable slots; overflow rows go
        back to the queue head so the next step admits them first."""
        scorer, admission = self._pair(budget=32, admit=32)
        routing = scorer.routing["per_user"]
        capacity = routing.free_slots + len(routing._admitted)
        over = np.arange(
            routing.base_rows, routing.base_rows + capacity + 3,
            dtype=np.int64,
        )
        admission.note_deferred("per_user", over)
        admitted = admission.step()
        assert admitted == capacity
        assert admission.queue_depth == 3
        # requeued rows are the ones beyond capacity, in order
        q = list(admission._queues["per_user"])
        assert q == over[capacity:].tolist()

    def test_warmup_precompiles_the_scatter(self):
        """The fixed-shape admission scatter compiles during warmup, not
        during the first live admit (which must stay copy-only)."""
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.array([40, 41]))
        before = scorer.compile_count
        admitted = admission.step()
        assert admitted == 2
        assert scorer.compile_count == before  # score fn untouched
        assert scorer.routing["per_user"].is_resident(40)

    def test_background_thread_drains(self):
        scorer, admission = self._pair()
        admission.note_deferred("per_user", np.arange(40, 56))
        admission.start(interval_s=0.001)
        try:
            deadline = time.time() + 5.0
            while admission.queue_depth and time.time() < deadline:
                time.sleep(0.005)
        finally:
            admission.stop()
        assert admission.queue_depth == 0
        assert admission.admitted_total == 16

    def test_multi_replica_rows_written_everywhere_before_publish(self):
        """Multi-scorer mode: an admitted row gathers identical (real)
        bytes from every replica — content lands on all devices before
        routing publishes it."""
        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2,
                device_budget_rows=32, routing=routing,
            )
            routing = s.routing
            scorers.append(s)
        admission = AdmissionController(scorers, admit_batch=8)
        for s in scorers:
            s.attach_admission(admission)
        admission.warmup()
        reqs = _requests(32, seed=3)
        scorers[0].score_batch(reqs, bucket_size=32)
        admission.drain()
        a = scorers[0].score_batch(reqs, bucket_size=32)
        b = scorers[1].score_batch(reqs, bucket_size=32)
        for x, y in zip(a, b):
            assert x.score == y.score


class TestCompileDiscipline:
    def test_zero_post_warmup_retraces_with_admission(self):
        """Acceptance: after one warmup pass per bucket, replaying traffic
        (with background admission scattering rows) adds zero compiles."""
        artifact = _artifact()
        reqs = _requests(96, seed=21)
        buckets = (1, 4, 16, 32)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=32
        )
        for b in buckets:
            scorer.score_batch(reqs[:b], bucket_size=b)
        warm = scorer.compile_count
        assert warm == len(buckets)
        admission = AdmissionController([scorer], admit_batch=8)
        scorer.attach_admission(admission)
        admission.warmup()
        results, snapshot = replay_requests(
            [scorer], reqs, bucket_sizes=buckets,
            model_id="sharded-test", continuous=True,
            max_wait_s=0.001, max_queue=64, admission=admission,
        )
        assert len(results) == len(reqs)
        assert scorer.compile_count == warm
        assert snapshot["residency"]["per_user"]["resident_lookups"] > 0


class TestContinuousBatcher:
    def _scorer(self):
        return ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )

    def test_full_bucket_drains_without_deadline(self):
        scorer = self._scorer()
        reqs = _requests(16, seed=2)
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=10.0, max_queue=32
        ) as batcher:
            handles = batcher.submit_many(reqs)
            got = [h.result(timeout=10.0) for h in handles]
        want = scorer.score_batch(reqs, bucket_size=16)
        assert [g.score for g in got] == [w.score for w in want]

    def test_deadline_drains_partial_bucket(self):
        scorer = self._scorer()
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=0.005, max_queue=32
        ) as batcher:
            h = batcher.submit(_requests(1, seed=4)[0])
            got = h.result(timeout=10.0)
        assert got.request_id == "r0"

    def test_backpressure_bounds_queue(self):
        scorer = self._scorer()
        reqs = _requests(24, seed=6)
        batcher = ContinuousBatcher(
            scorer, bucket_sizes=(8,), max_wait_s=0.001, max_queue=8
        )
        with batcher:
            handles = batcher.submit_many(reqs)  # blocks internally, no error
            assert len(handles) == 24
            for h in handles:
                h.result(timeout=10.0)
        assert batcher.queue_depth == 0

    def test_stop_resolves_stranded_handles(self):
        scorer = self._scorer()
        batcher = ContinuousBatcher(
            scorer, bucket_sizes=(8,), max_wait_s=30.0, max_queue=8
        )
        batcher.start()
        h = batcher.submit(_requests(1, seed=8)[0])
        batcher.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            h.result(timeout=1.0)

    def test_submit_after_stop_raises(self):
        scorer = self._scorer()
        batcher = ContinuousBatcher(scorer, bucket_sizes=(8,))
        batcher.start()
        batcher.stop()
        with pytest.raises(RuntimeError):
            batcher.submit(_requests(1)[0])

    def test_concurrent_submitters_all_resolve(self):
        scorer = self._scorer()
        reqs = _requests(60, seed=12)
        out = {}
        with ContinuousBatcher(
            scorer, bucket_sizes=(4, 16), max_wait_s=0.002, max_queue=32
        ) as batcher:
            def worker(chunk):
                for h, r in zip(batcher.submit_many(chunk), chunk):
                    out[r.request_id] = h.result(timeout=10.0)
            threads = [
                threading.Thread(target=worker, args=(reqs[i::3],))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert len(out) == 60
        want = {
            w.request_id: w.score
            for w in scorer.score_batch(reqs, bucket_size=64)
        }
        for rid, res in out.items():
            assert res.score == want[rid]


class TestRoutingThreadSafety:
    def test_route_out_of_range_rows_defer_not_crash(self):
        """Rows from a newer entity index (mid hot swap, before grow) are
        deferred — never an out-of-bounds read of the placement arrays."""
        routing = build_routing({"c": 10}, num_shards=2)["c"]
        shards, slots, deferred = routing.route(
            np.array([5, 12, -1], dtype=np.int64)
        )
        assert slots[0] != routing.cold_slot  # resident
        assert slots[1] == routing.cold_slot  # beyond n_rows: deferred
        assert deferred.tolist() == [12]

    @pytest.mark.parametrize("policy", ["oldest", "importance"])
    def test_concurrent_admission_and_hotswap_updates(self, policy):
        """The background admission thread and hot-swap row updates
        mutate the SAME routing concurrently; the routing lock must keep
        allocate/publish atomic — no double-popped slot, no two rows
        published into one slot, no dead admission thread. Runs under
        BOTH eviction policies: importance selection walks the admitted
        deque (which hot swaps riddle with stale entries), so it must
        uphold the same invariants as the FIFO path."""
        artifact = _artifact(n_ent=128)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=32,
            eviction_policy=policy,
        )
        admission = AdmissionController([scorer], admit_batch=8)
        scorer.attach_admission(admission)
        admission.warmup()
        routing = scorer.routing["per_user"]
        stop = threading.Event()
        errors = []

        def feed():
            try:
                rng = np.random.default_rng(0)
                while not stop.is_set():
                    admission.note_deferred(
                        "per_user", rng.integers(0, 128, size=16)
                    )
                    # the scoring thread's lock-free frequency notes race
                    # the eviction reads by design (stats-grade planes)
                    routing.note_requests(rng.integers(0, 128, size=16))
                    time.sleep(0.0005)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def swap():
            try:
                rng = np.random.default_rng(1)
                while not stop.is_set():
                    rows = rng.integers(0, 128, size=4)
                    vals = rng.standard_normal((4, D_RE)).astype(np.float32)
                    scorer.update_random_effect_rows("per_user", rows, vals)
                    time.sleep(0.0005)
            except Exception as e:
                errors.append(e)

        admission.start(interval_s=0.0005)
        threads = [threading.Thread(target=f) for f in (feed, swap, swap)]
        for t in threads:
            t.start()
        time.sleep(0.7)
        stop.set()
        for t in threads:
            t.join()
        admission.stop()
        assert errors == []
        # the corruption detector: every resident row occupies a UNIQUE
        # (shard, slot) pair — a lost lock would double-assign slots
        with routing.lock:
            slot_of = routing._slot_of[: routing.n_rows]
            resident = np.nonzero(slot_of >= 0)[0]
            pairs = {
                (int(routing._shard_of[r]), int(slot_of[r]))
                for r in resident
            }
            assert len(pairs) == resident.size
            # and bookkeeping balances: occupied + free == all data slots
            occupied = resident.size
            assert occupied + routing.free_slots == routing.device_rows


class TestEvictionPolicy:
    """Importance-scored admission eviction (freq × norm, DuHL applied to
    device residency) vs the historical FIFO — and the FIFO default must
    stay byte-identical."""

    def _routing(self, policy):
        # 20-row table, budget 8 -> base 6 pinned + 2 headroom slots
        return build_routing(
            {"c": 20}, num_shards=2, device_budget_rows=8,
            eviction_policy=policy,
        )["c"]

    def _admit(self, routing, rows):
        shards, slots, evicted = routing.allocate(len(rows))
        routing.publish(np.asarray(rows), shards, slots)
        return evicted

    def test_invalid_policy_raises(self):
        with pytest.raises(ValueError, match="eviction_policy"):
            CoordinateRouting(8, 2, 4, eviction_policy="lru")

    def test_policies_pick_different_victims(self):
        """Admit cold-then-hot; FIFO evicts the hot first-admitted row,
        importance keeps it and recycles the unrequested one."""
        for policy, expect_victim in (("oldest", 10), ("importance", 11)):
            routing = self._routing(policy)
            assert self._admit(routing, [10, 11]) == []  # into free slots
            routing.note_requests(np.array([10, 10, 10]))
            routing.note_row_norms(np.array([10, 11]), np.array([1.0, 1.0]))
            evicted = self._admit(routing, [12])
            assert evicted == [expect_victim], policy
            assert routing.is_resident(10) == (policy == "importance")
            assert routing.is_resident(12)
            stats = routing.stats()
            assert stats["eviction_policy"] == policy
            assert stats[f"evicted_{policy}"] == 1

    def test_norm_scales_importance(self):
        """Equal frequency, unequal coefficient magnitude: the near-zero
        row loses — its score barely differs from the FE-only fallback."""
        routing = self._routing("importance")
        self._admit(routing, [10, 11])
        routing.note_requests(np.array([10, 11]))
        routing.note_row_norms(np.array([10, 11]), np.array([1e-6, 2.0]))
        assert self._admit(routing, [12]) == [10]

    def test_importance_skips_stale_deque_entries(self):
        """A hot-swap unpublish leaves the row's deque entry behind; the
        importance pass must neither evict through it (slot -1) nor let it
        unbalance occupancy accounting."""
        routing = self._routing("importance")
        self._admit(routing, [10, 11])
        routing.unpublish(np.array([10]))  # stale deque entry for 10
        evicted = self._admit(routing, [12])
        assert evicted == [11]  # the only LIVE admitted row
        with routing.lock:
            assert 10 not in routing._admitted  # stale entry dropped
        # row 10's slot stays in limbo until a re-admission re-publishes
        # it (unpublish never frees storage) — exactly one orphaned slot,
        # and no two resident rows share a (shard, slot) pair
        resident = np.nonzero(routing._slot_of[: routing.n_rows] >= 0)[0]
        assert resident.size + routing.free_slots == routing.device_rows - 1
        pairs = {
            (int(routing._shard_of[r]), int(routing._slot_of[r]))
            for r in resident
        }
        assert len(pairs) == resident.size

    def test_importance_headroom_exhaustion_raises(self):
        routing = self._routing("importance")
        self._admit(routing, [10, 11])
        with pytest.raises(RuntimeError, match="headroom"):
            routing.allocate(3)  # only 2 evictable slots exist

    def test_frequency_plane_is_an_exponential_window(self):
        routing = self._routing("importance")
        routing.note_row_norms(np.array([10]), np.array([1.0]))
        routing.note_requests(np.array([10]))
        before = float(routing.importance_of(np.array([10]))[0])
        for _ in range(CoordinateRouting.FREQ_DECAY_EVERY):
            routing.note_requests(np.empty(0, dtype=np.int64))
        after = float(routing.importance_of(np.array([10]))[0])
        assert after == pytest.approx(before / 2)

    def test_oldest_policy_tracks_no_planes(self):
        routing = self._routing("oldest")
        routing.note_requests(np.array([1, 2]))  # no-ops, no allocation
        routing.note_row_norms(np.array([1]), np.array([1.0]))
        assert routing._freq is None and routing._norm is None
        assert routing.importance_of(np.array([1, 2])).tolist() == [0.0, 0.0]
        assert "importance_mean" not in routing.stats()

    def test_grow_extends_importance_planes(self):
        routing = self._routing("importance")
        routing.grow(40)
        routing.note_requests(np.array([35]))
        routing.note_row_norms(np.array([35]), np.array([3.0]))
        assert routing.importance_of(np.array([35]))[0] == pytest.approx(3.0)

    def test_admission_stats_report_policy_counters(self):
        artifact = _artifact(n_ent=32)
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, device_budget_rows=8,
            eviction_policy="importance",
        )
        admission = AdmissionController([scorer], admit_batch=4)
        scorer.attach_admission(admission)
        admission.warmup()
        by_policy = admission.stats()["evicted_by_policy"]
        assert set(by_policy) == {"oldest", "importance"}
        assert scorer.routing["per_user"].stats()["eviction_policy"] == (
            "importance"
        )

    def test_snapshot_exports_eviction_gauges(self):
        from photon_ml_tpu.telemetry import get_registry

        routing = self._routing("importance")
        self._admit(routing, [10, 11])
        routing.note_requests(np.array([10]))
        routing.note_row_norms(np.array([10]), np.array([1.0]))
        self._admit(routing, [12])
        reg = get_registry()
        reg.record_serving_snapshot({"residency": {"c": routing.stats()}})
        gauges = reg.snapshot()["gauges"]
        assert gauges["serving.eviction.importance"]["last"] == 1.0
        assert "serving.importance.mean" in gauges


class TestEntityIdCoercion:
    @pytest.mark.parametrize("sharded", [False, True])
    def test_int_entity_ids_resolve_like_str(self, sharded):
        """Artifact entity indexes are keyed by str; non-str ids must be
        coerced (the pre-sharding route path did), not silently miss or
        crash an off-heap index."""
        rng = np.random.default_rng(7)
        artifact = ServingArtifact(
            task=TaskType.LOGISTIC_REGRESSION,
            tables={
                "fixed": ServingTable(
                    feature_shard="global", random_effect_type=None,
                    weights=(
                        rng.standard_normal(D_FE) * 0.1
                    ).astype(np.float32),
                ),
                "per_user": ServingTable(
                    feature_shard="per_user", random_effect_type="userId",
                    weights=(
                        rng.standard_normal((8, D_RE)) * 0.3
                    ).astype(np.float32),
                    # numeric-string keys, as packed from int id tags
                    entity_index=DefaultIndexMap(
                        {str(i): i for i in range(8)}
                    ),
                ),
            },
            model_name="int-ids",
        )
        base = _requests(8, n_ent=8, seed=11)
        as_str = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": str(i % 8)}, offset=r.offset,
            )
            for i, r in enumerate(base)
        ]
        as_int = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": i % 8}, offset=r.offset,
            )
            for i, r in enumerate(base)
        ]
        if sharded:
            scorer = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2
            )
        else:
            scorer = GameScorer(artifact, max_nnz=MAX_NNZ)
        want = scorer.score_batch(as_str, bucket_size=8)
        got = scorer.score_batch(as_int, bucket_size=8)
        for g, w in zip(got, want):
            assert g.score == w.score
            assert g.cold_coordinates == w.cold_coordinates == ()


class TestCoordinatedHotSwap:
    def test_replicas_swap_as_one_generation(self):
        from photon_ml_tpu.incremental.delta import build_delta

        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2, routing=routing
            )
            routing = s.routing
            scorers.append(s)
        managers = [HotSwapManager(s) for s in scorers]
        coord = CoordinatedHotSwap(managers)
        delta = build_delta(
            {"per_user": {"u3": {0: 9.0, 2: -1.5}}}, artifact,
            generation=1,
        )
        reports = coord.apply_delta(delta)
        assert len(reports) == 2
        assert all(not r.rolled_back for r in reports)
        assert coord.generation == 1
        req = _requests(4, seed=30)
        req = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": "u3"}, offset=r.offset,
            )
            for r in req
        ]
        a = scorers[0].score_batch(req, bucket_size=4)
        b = scorers[1].score_batch(req, bucket_size=4)
        for x, y in zip(a, b):
            assert x.score == y.score

    def test_row_update_writes_every_replica_before_publish(self):
        """Hot-swap admission of a NEW row in multi-replica mode must land
        the bytes on every replica's device table before the shared
        routing publishes the row — otherwise replica k serves the evicted
        victim's coefficients until its own swap lands."""
        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2,
                device_budget_rows=32, routing=routing,
            )
            routing = s.routing
            scorers.append(s)
        admission = AdmissionController(scorers, admit_batch=8)
        for s in scorers:
            s.attach_admission(admission)
        assert scorers[0]._replica_group == scorers
        vals = np.full((1, D_RE), 3.5, dtype=np.float32)
        # row 40 is beyond the resident base (budget 32 → base 24): the
        # update admits it into headroom through the replica-group path
        scorers[0].update_random_effect_rows(
            "per_user", np.array([40]), vals
        )
        coord = routing["per_user"]
        assert coord.is_resident(40)
        shard, slot = coord.placement(40)
        for s in scorers:
            got = np.asarray(s._providers["per_user"].table)[shard, slot]
            np.testing.assert_array_equal(got, vals[0])

    def test_rollback_after_regrow_restores_routing(self):
        """A regrowing rebind replaces the shared routing coordinate; a
        rollback must restore the (provider, routing) pair together, or
        the scorer routes with the grown layout while gathering from the
        old-shape table."""
        from photon_ml_tpu.incremental.delta import build_delta

        artifact = _artifact()
        scorer = ShardedGameScorer(artifact, max_nnz=MAX_NNZ, num_shards=2)
        manager = HotSwapManager(scorer)
        routing_before = scorer.routing["per_user"]
        reqs = _requests(16, seed=17)
        before = scorer.score_batch(reqs, bucket_size=16)
        # more new entities than the full-residency headroom (16 slots for
        # N_ENT=64): forces the rebind + regrow path
        delta = build_delta(
            {
                "per_user": {
                    f"zz{i}": {0: 1.0 + i} for i in range(24)
                }
            },
            artifact,
            generation=1,
        )
        report = manager.apply_delta(delta)
        assert report.regrew == ("per_user",)
        assert scorer.routing["per_user"] is not routing_before
        manager.rollback()
        assert scorer.routing["per_user"] is routing_before
        assert (
            scorer._providers["per_user"].routing is routing_before
        )
        after = scorer.score_batch(reqs, bucket_size=16)
        for b, a in zip(before, after):
            assert b.score == a.score


class TestPauselessFlip:
    """Generation-flip hot swap: the double-buffered device table stages
    candidate rows into the spare half off the request path and blocks
    scoring only for the atomic flip. Acceptance: bitwise score parity
    through a flip (under concurrent scoring), bitwise rollback parity,
    and converged halves after every update."""

    def _halves(self, scorer, cid="per_user"):
        p = scorer._providers[cid]
        return np.asarray(p._tables[0]), np.asarray(p._tables[1])

    def test_update_returns_blocking_seconds_and_flips(self):
        artifact = _artifact()
        scorer = ShardedGameScorer(artifact, max_nnz=MAX_NNZ, num_shards=2)
        provider = scorer._providers["per_user"]
        gen_before = provider.generation
        t0 = time.perf_counter()
        ret = scorer.update_random_effect_rows(
            "per_user", np.array([3, 7]),
            np.full((2, D_RE), 1.25, dtype=np.float32),
        )
        wall = time.perf_counter() - t0
        assert isinstance(ret, float)
        assert 0.0 <= ret <= wall
        assert provider.generation == 1 - gen_before
        a, b = self._halves(scorer)
        np.testing.assert_array_equal(a, b)  # phase-3 convergence

    def test_flip_parity_under_concurrent_scoring(self):
        """A scoring thread hammers score_batch while the main thread
        applies row updates; every drained batch must be bitwise equal to
        a reference scorer that saw the same updates synchronously —
        a gather must never observe a half-written table."""
        artifact = _artifact()
        sharded = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2
        )
        ref = GameScorer(_artifact(), max_nnz=MAX_NNZ)
        reqs = _requests(16, seed=21)
        stop = threading.Event()
        errors = []

        def _hammer():
            while not stop.is_set():
                try:
                    sharded.score_batch(reqs, bucket_size=16)
                except BaseException as e:  # pragma: no cover
                    errors.append(e)
                    return

        t = threading.Thread(target=_hammer)
        t.start()
        rng = np.random.default_rng(11)
        try:
            for _ in range(8):
                rows = np.unique(rng.integers(0, N_ENT, size=6))
                values = rng.standard_normal(
                    (rows.size, D_RE)
                ).astype(np.float32)
                sharded.update_random_effect_rows("per_user", rows, values)
                ref.update_random_effect_rows("per_user", rows, values)
        finally:
            stop.set()
            t.join()
        assert not errors
        want = ref.score_batch(reqs, bucket_size=16)
        got = sharded.score_batch(reqs, bucket_size=16)
        for g, w in zip(got, want):
            assert g.score == w.score  # bitwise, not allclose
            assert g.mean == w.mean
        a, b = self._halves(sharded)
        np.testing.assert_array_equal(a, b)

    def test_flip_parity_sealed_and_continuous_batchers(self):
        """Both serving paths (sealed MicroBatcher, continuous batcher)
        observe identical post-flip scores."""
        from photon_ml_tpu.serving import MicroBatcher

        artifact = _artifact()
        sharded = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2
        )
        rows = np.array([2, 5, 9])
        values = np.full((3, D_RE), -0.75, dtype=np.float32)
        sharded.update_random_effect_rows("per_user", rows, values)
        reqs = _requests(8, seed=33)
        want = sharded.score_batch(reqs, bucket_size=8)
        sealed = MicroBatcher(sharded, bucket_sizes=(8,))
        got_sealed = sealed.submit_many(reqs)
        with ContinuousBatcher(
            sharded, bucket_sizes=(8,), max_wait_s=0.001
        ) as cb:
            handles = cb.submit_many(reqs)
            cb.flush()
            got_cont = [h.result(timeout=5) for h in handles]
        for out in (got_sealed, got_cont):
            assert len(out) == len(want)
            by_id = {r.request_id: r for r in out}
            for w in want:
                assert by_id[w.request_id].score == w.score

    def test_rollback_flip_back_parity(self):
        """apply_delta then rollback restores the exact pre-swap scores
        (the inverse update stages into the spare half and flips back),
        and both halves converge again."""
        from photon_ml_tpu.incremental.delta import build_delta

        artifact = _artifact()
        scorer = ShardedGameScorer(artifact, max_nnz=MAX_NNZ, num_shards=2)
        manager = HotSwapManager(scorer)
        reqs = [
            ScoreRequest(
                request_id=r.request_id, features=r.features,
                entity_ids={"userId": "u3" if i % 2 else "u9"},
                offset=r.offset,
            )
            for i, r in enumerate(_requests(16, seed=41))
        ]
        before = scorer.score_batch(reqs, bucket_size=16)
        delta = build_delta(
            {"per_user": {"u3": {0: 4.0}, "u9": {1: -2.0}}},
            artifact,
            generation=1,
        )
        report = manager.apply_delta(delta)
        assert not report.rolled_back
        mid = scorer.score_batch(reqs, bucket_size=16)
        assert any(m.score != b.score for m, b in zip(mid, before))
        manager.rollback()
        after = scorer.score_batch(reqs, bucket_size=16)
        for b, a in zip(before, after):
            assert b.score == a.score  # bitwise rollback parity
        a0, a1 = self._halves(scorer)
        np.testing.assert_array_equal(a0, a1)

    def test_multi_replica_flip_is_all_or_nothing(self):
        """All replicas flip generations together under the replica-group
        update; their halves converge and scores agree bitwise."""
        artifact = _artifact()
        routing = None
        scorers = []
        for _ in range(2):
            s = ShardedGameScorer(
                artifact, max_nnz=MAX_NNZ, num_shards=2, routing=routing
            )
            routing = s.routing
            scorers.append(s)
        scorers[0].set_replica_group(scorers)
        gens_before = [
            s._providers["per_user"].generation for s in scorers
        ]
        scorers[0].update_random_effect_rows(
            "per_user", np.array([4]),
            np.full((1, D_RE), 2.5, dtype=np.float32),
        )
        for s, g in zip(scorers, gens_before):
            assert s._providers["per_user"].generation == 1 - g
            h0, h1 = self._halves(s)
            np.testing.assert_array_equal(h0, h1)
        reqs = _requests(8, seed=51)
        a = scorers[0].score_batch(reqs, bucket_size=8)
        b = scorers[1].score_batch(reqs, bucket_size=8)
        for x, y in zip(a, b):
            assert x.score == y.score


class TestScoreDeltaImportance:
    """Satellite: per-entity |score - FE-only score| EWMA folded into the
    importance eviction signal."""

    def test_score_deltas_accumulate_under_importance(self):
        artifact = _artifact()
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2,
            eviction_policy="importance",
        )
        routing = scorer.routing["per_user"]
        assert routing.wants_score_deltas
        reqs = [
            ScoreRequest(
                request_id=f"d{i}",
                features={
                    "global": {0: 1.0},
                    "per_user": {j: 1.0 for j in range(D_RE)},
                },
                entity_ids={"userId": "u7"},
            )
            for i in range(8)
        ]
        scorer.score_batch(reqs, bucket_size=8)
        assert routing._sdelta is not None
        assert routing._sdelta[7] > 0.0
        # the fold-in lifts importance above the freq x norm bound alone
        bound = routing._freq[np.array([7])] * np.maximum(
            routing._norm[np.array([7])].astype(np.float64), 1e-12
        )
        imp = routing.importance_of(np.array([7]))
        assert imp[0] >= bound[0]
        assert imp[0] >= routing._sdelta[7]

    def test_oldest_policy_never_runs_delta_pass(self):
        """Default 'oldest' routing wants no deltas: the aux jit never
        runs and scores are bitwise identical with score_delta on/off."""
        artifact = _artifact()
        reqs = _requests(16, seed=61)
        on = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, score_delta=True
        )
        off = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2, score_delta=False
        )
        assert not on.routing["per_user"].wants_score_deltas
        a = on.score_batch(reqs, bucket_size=16)
        b = off.score_batch(reqs, bucket_size=16)
        for x, y in zip(a, b):
            assert x.score == y.score
            assert x.mean == y.mean

    def test_score_delta_off_reverts_to_freq_norm(self):
        artifact = _artifact()
        scorer = ShardedGameScorer(
            artifact, max_nnz=MAX_NNZ, num_shards=2,
            eviction_policy="importance", score_delta=False,
        )
        routing = scorer.routing["per_user"]
        assert not routing.wants_score_deltas
        assert routing._sdelta is None
        scorer.score_batch(_requests(8, seed=71), bucket_size=8)
        # importance_of still works on the freq x norm bound
        imp = routing.importance_of(np.arange(4))
        assert imp.shape == (4,)

    def test_decay_halves_sdelta_with_freq(self):
        routing = CoordinateRouting(
            n_rows=8, num_shards=1, shard_capacity=8,
            eviction_policy="importance",
        )
        rows = np.array([1, 2])
        routing.note_score_deltas(rows, np.array([4.0, 8.0]))
        before = routing._sdelta[rows].copy()
        for _ in range(CoordinateRouting.FREQ_DECAY_EVERY):
            routing.note_requests(np.array([0]))
        assert np.all(routing._sdelta[rows] <= before / 2 + 1e-12)
