"""Offline tuner + --auto-tune tests: the declared knob registry, evidence
-driven proposals, the A/B harness's registry-isolation and tie-breaking
contracts, tuned-config persistence on serving artifacts (fast lane), and
the end-to-end auto-tune drivers — train_game iteration-0 A/B, serve_game
warmup A/B, and the boots-tuned /varz assertion (slow lane)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.telemetry import MetricsRegistry, get_registry
from photon_ml_tpu.telemetry.analyze import analyze_records
from photon_ml_tpu.tuning import (
    KnobSpec,
    ab_candidates,
    all_knobs,
    get_knob,
    propose,
    register_knob,
    resolve_dep,
    run_ab_trials,
)


def _report(gauges=None, counters=None, solver_fields=None, phases=None):
    """RunReport from a minimal synthetic ledger: a 10s run with optional
    phase spans, solver events and registry snapshot."""
    records = [{"type": "meta", "ts": 0.0, "phase": "start", "label": "t"}]
    sid = 1
    for name, dur in (phases or {}).items():
        records.append({
            "type": "span", "ts": dur, "name": name, "path": name,
            "span_id": sid, "parent_id": None, "start_unix": 0.0,
            "duration_s": dur, "failed": False,
        })
        sid += 1
    if solver_fields:
        records.append({
            "type": "event", "ts": 5.0, "event": "SolverStatsEvent",
            "fields": solver_fields,
        })
    records.append({
        "type": "metrics", "ts": 9.9,
        "snapshot": {
            "counters": dict(counters or {}),
            "gauges": {
                k: {"last": v, "peak": v} for k, v in (gauges or {}).items()
            },
            "histograms": {},
        },
    })
    records.append({"type": "meta", "ts": 10.0, "phase": "finish"})
    return analyze_records(records)


class TestKnobRegistry:
    def test_knob_space_is_declared(self):
        knobs = all_knobs()
        assert len(knobs) >= 4
        names = {k.name for k in knobs}
        assert {"adaptive.chunk_iters", "serving.bucket_sizes",
                "serving.cache_capacity", "train.engine"} <= names
        for spec in knobs:
            assert spec.metric_deps, spec.name  # tunable ⇒ observable
            assert spec.applies_to in ("train", "serve", "both")
            assert spec.default in spec.candidates or spec.kind == "csv_ints"

    def test_parse_kinds(self):
        assert get_knob("adaptive.chunk_iters").parse("16") == 16
        assert get_knob("train.engine").parse("ell") == "ell"
        buckets = get_knob("serving.bucket_sizes")
        assert buckets.parse("1,4,16") == (1, 4, 16)
        assert buckets.parse([1, 4]) == (1, 4)

    def test_duplicate_registration_rejected(self):
        with pytest.raises(ValueError, match="registered twice"):
            register_knob(KnobSpec(
                name="adaptive.chunk_iters", kind="int", default=8,
                applies_to="train", phase="re_solve",
                metric_deps=("phase:re_solve",), candidates=(8,),
                description="dup",
            ))

    def test_unknown_knob_lists_registered(self):
        with pytest.raises(KeyError, match="registered"):
            get_knob("no.such.knob")


class TestProposal:
    def test_resolve_dep_kinds(self):
        r = _report(
            gauges={"serving.batch_fill": 0.5},
            counters={"jit.traces.fe_solve": 3},
            solver_fields={"executed_lane_iterations": 10,
                           "lockstep_lane_iterations": 25},
            phases={"re/train": 4.0},
        )
        assert resolve_dep(r, "phase:re_solve") == pytest.approx(0.4)
        assert resolve_dep(r, "metric:serving.batch_fill") == 0.5
        assert resolve_dep(r, "solver:lane_iteration_savings") == 2.5
        assert resolve_dep(r, "jit:fe_solve") == 3.0
        assert resolve_dep(r, "solver:nope") is None

    def _timed_report(self, segments):
        """RunReport over a 10s run with explicitly-placed spans
        (name, start, dur) — lets a test choose sequential vs concurrent
        layouts, which is what the overlap deps observe."""
        records = [
            {"type": "meta", "ts": 0.0, "phase": "start", "label": "t"}
        ]
        for sid, (name, start, dur) in enumerate(segments, start=1):
            records.append({
                "type": "span", "ts": start + dur, "name": name,
                "path": name, "span_id": sid, "parent_id": None,
                "start_unix": start, "duration_s": dur, "failed": False,
            })
        records.append({"type": "meta", "ts": 10.0, "phase": "finish"})
        return analyze_records(records)

    def test_resolve_dep_overlap_kind(self):
        # two fully-concurrent 4s spans: each phase is busy 4s but only
        # 2s attributes exclusively, so overlap resolves to 2.0s apiece
        r = self._timed_report([("fe/solve", 0.0, 4.0),
                                ("re/train", 0.0, 4.0)])
        assert resolve_dep(r, "overlap:fe_solve") == pytest.approx(2.0)
        assert resolve_dep(r, "overlap:re_solve") == pytest.approx(2.0)
        # sequential layout: same busy time, zero concurrency
        r = self._timed_report([("fe/solve", 0.0, 4.0),
                                ("re/train", 4.0, 4.0)])
        assert resolve_dep(r, "overlap:fe_solve") == pytest.approx(0.0)
        assert resolve_dep(r, "overlap:re_solve") == pytest.approx(0.0)

    def test_material_fe_re_without_overlap_proposes_async(self):
        # FE and RE each hold 40% of wall-clock back-to-back (a sync run):
        # the tuner proposes flipping the schedule to async
        r = self._timed_report([("fe/solve", 0.0, 4.0),
                                ("re/train", 4.0, 4.0)])
        p = propose(r)
        knob = p.knobs["train.schedule"]
        assert knob.value == "async"
        assert knob.changed
        assert "overlap" in knob.rationale
        # staleness only acts under async; no overlap evidence yet, so the
        # default holds with an explanation
        stale = p.knobs["train.staleness"]
        assert not stale.changed
        assert stale.rationale

    def test_measured_overlap_keeps_defaults_with_evidence(self):
        # the ledger already shows FE/RE concurrency (an async run): the
        # schedule knob holds and both rationales cite the measurement
        r = self._timed_report([("fe/solve", 0.0, 4.0),
                                ("re/train", 0.0, 4.0)])
        p = propose(r)
        assert not p.knobs["train.schedule"].changed
        assert "overlap" in p.knobs["train.schedule"].rationale
        assert not p.knobs["train.staleness"].changed
        assert "staleness" in p.knobs["train.staleness"].rationale
        doc = p.to_dict()
        assert doc["knobs"]["train.schedule"]["evidence"][
            "overlap:fe_solve"] == pytest.approx(2.0)

    def test_one_sided_workload_keeps_sync(self):
        # RE dominates, FE is negligible: pipelining buys nothing, the
        # reproducible sync loop stays
        r = self._timed_report([("fe/solve", 0.0, 0.5),
                                ("re/train", 0.5, 8.0)])
        p = propose(r)
        assert p.knobs["train.schedule"].value == "sync"
        assert not p.knobs["train.schedule"].changed

    def test_low_savings_steps_chunk_iters_down(self):
        r = _report(
            solver_fields={"executed_lane_iterations": 100,
                           "lockstep_lane_iterations": 105, "rounds": 2,
                           "chunk_retraces": 0},
            phases={"re/train": 5.0},
        )
        p = propose(r)
        assert p.knobs["adaptive.chunk_iters"].value == 4
        assert p.knobs["adaptive.chunk_iters"].changed
        assert p.knobs["adaptive.min_lanes"].value == 4
        assert "savings" in p.knobs["adaptive.chunk_iters"].rationale

    def test_serving_evidence_moves_serving_knobs(self):
        r = _report(gauges={
            "serving.batch_fill": 0.4,
            "serving.cache_hit_rate": 0.5,
        })
        p = propose(r)
        assert p.knobs["serving.bucket_sizes"].value == (1, 2, 4, 8, 16, 32, 64)
        assert p.knobs["serving.cache_capacity"].value == 16384
        changed = p.changed()
        assert set(changed) == {"serving.bucket_sizes",
                                "serving.cache_capacity"}

    def test_every_knob_proposed_even_without_evidence(self):
        p = propose(_report())
        assert set(p.knobs) == {k.name for k in all_knobs()}
        assert len(p.knobs) >= 4
        assert p.changed() == {}  # no evidence ⇒ defaults hold
        for knob in p.knobs.values():
            assert knob.rationale

    def test_to_dict_is_auditable(self):
        doc = propose(_report(gauges={"serving.cache_hit_rate": 0.5})).to_dict()
        knob = doc["knobs"]["serving.cache_capacity"]
        assert knob["changed"] is True
        assert knob["evidence"]["metric:serving.cache_hit_rate"] == 0.5


class TestAbCandidates:
    def test_control_is_always_first_and_default(self):
        p = propose(_report(gauges={"serving.cache_hit_rate": 0.5}))
        cands = ab_candidates(p, "serve")
        assert len(cands) == 2
        assert cands[0]["serving.cache_capacity"] == 4096  # the control
        assert cands[1]["serving.cache_capacity"] == 16384
        # train-scoped knobs never leak into serve candidates
        assert all("adaptive.chunk_iters" not in c for c in cands)

    def test_no_change_still_yields_b_arm(self):
        # healthy metrics: nothing changes, but --auto-tune still needs a
        # B arm to judge
        p = propose(_report(gauges={"serving.batch_fill": 0.8,
                                    "serving.cache_hit_rate": 0.9}))
        assert p.changed() == {}
        cands = ab_candidates(p, "serve")
        assert len(cands) == 2
        assert cands[0] != cands[1]


class TestAbTrials:
    def test_fresh_registry_per_trial_no_leaks(self):
        get_registry().reset()
        seen = []

        def trial(config, registry):
            # a leak would make trial 1 see trial 0's counter
            seen.append(registry.counter_value("trial.touch"))
            registry.count("trial.touch")
            registry.gauge("judge", config["x"])

        result = run_ab_trials([{"x": 2.0}, {"x": 1.0}], trial,
                               judge_metric="judge")
        assert seen == [0.0, 0.0]  # trial A cannot leak into trial B
        assert get_registry().counter_value("trial.touch") == 0.0  # no global pollution
        assert result.winner_index == 1
        assert result.winner.config == {"x": 1.0}

    def test_control_wins_ties(self):
        def trial(config, registry):
            registry.gauge("judge", 5.0)

        result = run_ab_trials([{"v": "a"}, {"v": "b"}], trial,
                               judge_metric="judge")
        assert result.winner_index == 0

    def test_failed_trial_never_wins(self):
        def trial(config, registry):
            if config["boom"]:
                raise RuntimeError("trial exploded")
            registry.gauge("judge", 100.0)

        result = run_ab_trials(
            [{"boom": False}, {"boom": True}], trial, judge_metric="judge"
        )
        assert result.winner_index == 0
        failed = result.trials[1]
        assert failed.score is None and "trial exploded" in failed.error

    def test_wall_clock_fallback_judge(self):
        result = run_ab_trials([{}, {}], lambda c, r: None)
        assert result.judge_metric == "autotune.wall_s"
        for t in result.trials:
            assert t.score is not None and t.score >= 0
        d = result.to_dict()
        assert "snapshot" not in d["trials"][0]  # kept portable


def _toy_artifact():
    from photon_ml_tpu.indexmap import DefaultIndexMap
    from photon_ml_tpu.serving import ServingArtifact, ServingTable
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    return ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=rng.standard_normal(8).astype(np.float32),
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=rng.standard_normal((4, 3)).astype(np.float32),
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(4)}
                ),
            ),
        },
        model_name="toy",
    )


class TestTunedConfigPersistence:
    def test_metadata_round_trip(self, tmp_path):
        from photon_ml_tpu.serving import load_artifact, save_artifact

        art = _toy_artifact()
        art.tuned_config = {"serving.cache_capacity": 1024}
        out = tmp_path / "artifact"
        save_artifact(art, str(out))
        loaded = load_artifact(str(out))
        assert loaded.tuned_config == {"serving.cache_capacity": 1024}

    def test_sidecar_overrides_metadata(self, tmp_path):
        from photon_ml_tpu.serving import (
            load_artifact,
            load_tuned_config,
            save_artifact,
            save_tuned_config,
        )

        art = _toy_artifact()
        art.tuned_config = {"serving.cache_capacity": 1024}
        out = tmp_path / "artifact"
        save_artifact(art, str(out))
        save_tuned_config(
            str(out), {"serving.cache_capacity": 16384},
            provenance={"source": "test"},
        )
        assert load_tuned_config(str(out)) == {
            "serving.cache_capacity": 16384
        }
        loaded = load_artifact(str(out))
        assert loaded.tuned_config == {"serving.cache_capacity": 16384}

    def test_untuned_artifact_loads_none(self, tmp_path):
        from photon_ml_tpu.serving import (
            load_artifact,
            load_tuned_config,
            save_artifact,
        )

        out = tmp_path / "artifact"
        save_artifact(_toy_artifact(), str(out))
        assert load_tuned_config(str(out)) is None
        assert load_artifact(str(out)).tuned_config is None

    def test_malformed_sidecar_rejected(self, tmp_path):
        from photon_ml_tpu.serving import load_tuned_config, save_artifact
        from photon_ml_tpu.serving.artifact import TUNED_CONFIG_FILE

        out = tmp_path / "artifact"
        save_artifact(_toy_artifact(), str(out))
        (out / TUNED_CONFIG_FILE).write_text('{"not": "tuned"}')
        with pytest.raises(ValueError, match="tuned_config"):
            load_tuned_config(str(out))

    def test_sidecar_excluded_from_fingerprint(self, tmp_path):
        """Writing the tuned-config sidecar must not invalidate the delta
        chain: hot-swap fingerprints skip it."""
        from photon_ml_tpu.incremental import fingerprint_dir
        from photon_ml_tpu.serving import save_artifact, save_tuned_config

        out = tmp_path / "artifact"
        save_artifact(_toy_artifact(), str(out))
        before = fingerprint_dir(str(out))
        save_tuned_config(str(out), {"serving.cache_capacity": 1024})
        assert fingerprint_dir(str(out)) == before


@pytest.fixture(scope="module")
def tiny_glmix(tmp_path_factory):
    """Tiny GLMix logistic workload + adaptive-RE config for the driver
    auto-tune gates."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    root = tmp_path_factory.mktemp("tuning_glmix")
    rng = np.random.default_rng(11)
    n_users, dg, du = 6, 4, 3
    records = []
    for i in range(n_users * 10):
        user = f"user{i % n_users}"
        xg = rng.normal(size=dg)
        xu = rng.normal(size=du)
        y = 1.0 if (xg.sum() + xu.sum()) > 0 else 0.0
        records.append({
            "uid": f"r{i}", "label": y,
            "features": [("g", str(j), xg[j]) for j in range(dg)],
            "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
            "metadataMap": {"userId": user},
        })
    train_dir = root / "train"
    train_dir.mkdir()
    write_training_examples(str(train_dir / "part-00000.avro"), records)
    config = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {"feature_bags": ["userFeatures"],
                         "add_intercept": False},
        },
        "coordinates": {
            "fixed": {
                "type": "fixed", "feature_shard": "global",
                "optimizer": {"optimizer": "LBFGS",
                              "regularization": "L2",
                              "regularization_weight": 0.1},
            },
            "per_user": {
                "type": "random", "feature_shard": "per_user",
                "random_effect_type": "userId",
                "optimizer": {
                    "optimizer": "LBFGS", "regularization": "L2",
                    "regularization_weight": 1.0,
                    "adaptive": {"enabled": True, "chunk_iters": 4,
                                 "min_lanes": 2},
                },
            },
        },
        "update_order": ["fixed", "per_user"],
    }
    cfg = root / "game.json"
    cfg.write_text(json.dumps(config))
    return {"root": root, "train": train_dir, "config": cfg}


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode(), dict(resp.headers)


@pytest.mark.slow
class TestAutoTuneDrivers:
    def test_train_auto_tune(self, tiny_glmix, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.model_io import (
            load_game_model,
            load_game_model_metadata,
        )

        out = tmp_path / "model"
        run(parse_args([
            "--train-data-dirs", str(tiny_glmix["train"]),
            "--coordinate-config", str(tiny_glmix["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--auto-tune", "--auto-tune-trials", "1",
        ]))
        ab = json.loads((out / "auto-tune.json").read_text())
        assert len(ab["trials"]) >= 2  # incumbent + at least one candidate
        assert 0 <= ab["winner_index"] < len(ab["trials"])
        assert ab["judge_metric"] == "autotune.wall_s"
        for t in ab["trials"]:
            assert t["error"] is None, t["error"]
        # the tuned run still produces a loadable model
        model, _ = load_game_model(str(out / "best"))
        assert "fixed" in model.models
        if ab["winner_index"] != 0:
            meta = load_game_model_metadata(str(out / "best"))
            tuned = (meta.get("configurations") or {}).get("tuned_config")
            assert tuned == ab["winner_config"]

    def test_serve_auto_tune_persists_and_boots_tuned(self, tiny_glmix,
                                                      tmp_path):
        """serve_game --auto-tune judges candidates via the registry,
        persists the winner into the artifact, and a RESTARTED serve_game
        boots with it — asserted over live /varz."""
        from photon_ml_tpu.cli.serve_game import parse_args, run
        from photon_ml_tpu.cli.train_game import (
            parse_args as train_args,
            run as train_run,
        )
        from photon_ml_tpu.serving import load_tuned_config

        model_out = tmp_path / "model"
        train_run(train_args([
            "--train-data-dirs", str(tiny_glmix["train"]),
            "--coordinate-config", str(tiny_glmix["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(model_out),
        ]))
        artifact_dir = tmp_path / "artifact"
        metrics_out = tmp_path / "metrics.json"
        run(parse_args([
            "--model-dir", str(model_out / "best"),
            "--data-dirs", str(tiny_glmix["train"]),
            "--export-artifact-dir", str(artifact_dir),
            "--max-requests", "24",
            "--auto-tune", "--auto-tune-warmup", "16",
            "--metrics-output", str(metrics_out),
        ]))
        snapshot = json.loads(metrics_out.read_text())
        ab = snapshot["auto_tune"]
        assert len(ab["trials"]) >= 2
        assert ab["judge_metric"] == "serving.latency_p99_ms"
        for t in ab["trials"]:
            assert t["error"] is None, t["error"]
        persisted = load_tuned_config(str(artifact_dir))
        assert persisted  # the winner landed in the sidecar

        # restart from the tuned artifact and read /varz live
        port_file = tmp_path / "port"
        probes = {}

        def probe():
            deadline = time.time() + 60
            while time.time() < deadline and not port_file.exists():
                time.sleep(0.05)
            port = int(port_file.read_text())
            base = f"http://127.0.0.1:{port}"
            probes["varz"] = _get(f"{base}/varz")
            probes["healthz"] = _get(f"{base}/healthz")
            probes["metrics"] = _get(f"{base}/metrics")
            _get(f"{base}/quitquitquit")

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        run(parse_args([
            "--artifact-dir", str(artifact_dir),
            "--data-dirs", str(tiny_glmix["train"]),
            "--max-requests", "8",
            "--introspect-port", "0",
            "--introspect-port-file", str(port_file),
            "--introspect-hold", "60",
        ]))
        t.join(timeout=60)
        assert not t.is_alive()

        status, body, _ = probes["varz"]
        varz = json.loads(body)
        assert status == 200
        assert varz["tuned"] is True  # boots with the persisted winner
        assert varz["tuned_config"] == persisted
        for knob, value in (varz["tuned_applied"] or {}).items():
            assert varz[knob.split(".", 1)[1]] == value
        status, body, _ = probes["healthz"]
        assert status == 200 and json.loads(body)["healthy"] is True
        status, body, headers = probes["metrics"]
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "photon_serving_num_requests" in body
