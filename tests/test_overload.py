"""Closed-loop overload control + priority lanes + drain-time quota.

The load-bearing guarantees:

- hysteresis: burn >= burn_high engages (deadline shrink + shed), burn
  inside the band holds state, burn <= burn_low releases and restores
  the native deadline;
- FE-only shed answers ONLY requests whose every RE entity is absent or
  non-resident, with the same FE-only score the full path produces, and
  never sheds a resident entity;
- priority lanes: background submissions never drain ahead of pending
  live requests, in both the sealed and the continuous batcher;
- drain-time quota: an over-budget tenant's requests drop out at the
  bucket boundary, charged to that tenant, while other tenants' requests
  score normally.
"""

import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.indexmap import DefaultIndexMap
from photon_ml_tpu.serving import (
    ContinuousBatcher,
    MicroBatcher,
    OverloadController,
    ScoreRequest,
    ServingArtifact,
    ServingTable,
    ShardedGameScorer,
)
from photon_ml_tpu.serving.tenancy import TenantQuota
from photon_ml_tpu.serving.tenancy.quota import TenantBudget
from photon_ml_tpu.types import TaskType

N_ENT = 32
D_RE = 4
D_FE = 8
MAX_NNZ = {"global": 4, "per_user": D_RE}


def _artifact(seed=5):
    rng = np.random.default_rng(seed)
    return ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=(rng.standard_normal(D_FE) * 0.1).astype(np.float32),
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=(
                    rng.standard_normal((N_ENT, D_RE)) * 0.3
                ).astype(np.float32),
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(N_ENT)}
                ),
            ),
        },
        model_name="overload-test",
    )


def _request(i, entity="u1", tenant=None):
    rid = f"r{i}" if tenant is None else f"{tenant}!r{i}"
    ids = {} if entity is None else {"userId": entity}
    return ScoreRequest(
        request_id=rid,
        features={
            "global": {0: 1.0, 2: -0.5},
            "per_user": {j: 0.25 * (j + 1) for j in range(D_RE)},
        },
        entity_ids=ids,
        offset=0.1 * i,
    )


class FakeSLO:
    def __init__(self, burn=0.0):
        self.burn = burn

    def status(self):
        return {"burn_rate": self.burn}


class FakeBatcher:
    def __init__(self, max_wait_s=0.004):
        self.max_wait_s = max_wait_s


class TestHysteresis:
    def test_engage_hold_release(self):
        slo = FakeSLO(0.0)
        ctrl = OverloadController(
            slo, shrink_factor=0.5, burn_high=1.0, burn_low=0.5
        )
        b = FakeBatcher(0.004)
        ctrl.attach(b)
        assert b._overload is ctrl
        assert ctrl.poll() is False
        assert b.max_wait_s == 0.004

        slo.burn = 1.2
        assert ctrl.poll() is True
        assert b.max_wait_s == pytest.approx(0.002)
        assert ctrl.activations == 1

        # inside the hysteresis band: state holds
        slo.burn = 0.7
        assert ctrl.poll() is True
        assert b.max_wait_s == pytest.approx(0.002)
        assert ctrl.activations == 1

        slo.burn = 0.3
        assert ctrl.poll() is False
        assert b.max_wait_s == 0.004
        assert ctrl.recoveries == 1

    def test_attach_mid_overload_shrinks_immediately(self):
        ctrl = OverloadController(FakeSLO(2.0), shrink_factor=0.25)
        ctrl.poll()
        b = FakeBatcher(0.008)
        ctrl.attach(b)
        assert b.max_wait_s == pytest.approx(0.002)
        ctrl.detach(b)
        assert b.max_wait_s == 0.008
        assert b._overload is None

    def test_stop_restores_deadlines(self):
        ctrl = OverloadController(FakeSLO(5.0))
        b = FakeBatcher(0.004)
        ctrl.attach(b)
        ctrl.poll()
        assert b.max_wait_s < 0.004
        ctrl.stop()
        assert b.max_wait_s == 0.004
        assert ctrl.active is False

    def test_maybe_poll_rate_limits(self):
        clock = {"t": 0.0}
        slo = FakeSLO(2.0)
        ctrl = OverloadController(
            slo, poll_interval_s=1.0, clock=lambda: clock["t"]
        )
        ctrl.maybe_poll()
        assert ctrl.active is True
        slo.burn = 0.0
        ctrl.maybe_poll()  # within the interval: no state change
        assert ctrl.active is True
        clock["t"] = 1.5
        ctrl.maybe_poll()
        assert ctrl.active is False

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            OverloadController(FakeSLO(), shrink_factor=0.0)
        with pytest.raises(ValueError):
            OverloadController(FakeSLO(), burn_high=0.5, burn_low=1.0)


class TestFeOnlyShed:
    def _controller(self, scorer, burn=2.0):
        ctrl = OverloadController(FakeSLO(burn))
        ctrl.attach_scorer(scorer)
        ctrl.poll()
        return ctrl

    def test_sheds_ghost_entity_with_fe_only_score(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        ctrl = self._controller(scorer)
        req = _request(0, entity="nobody")
        shed = ctrl.try_shed(req)
        assert shed is not None
        assert shed.cold_coordinates == ("per_user",)
        want = scorer.score_batch([req], bucket_size=1)[0]
        assert shed.score == pytest.approx(want.score, rel=1e-5)
        assert shed.mean == pytest.approx(want.mean, rel=1e-5)
        assert ctrl.shed_total == 1

    def test_sheds_idless_request(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        ctrl = self._controller(scorer)
        assert ctrl.try_shed(_request(1, entity=None)) is not None

    def test_refuses_resident_entity(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        ctrl = self._controller(scorer)
        assert ctrl.try_shed(_request(2, entity="u3")) is None
        assert ctrl.shed_total == 0

    def test_sheds_non_resident_known_entity(self):
        # budget 8 -> only the base rows are resident; u30 is known but
        # non-resident, so the full path scores it FE-only anyway
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2,
            device_budget_rows=8,
        )
        ctrl = self._controller(scorer)
        req = _request(3, entity="u30")
        shed = ctrl.try_shed(req)
        assert shed is not None
        want = scorer.score_batch([req], bucket_size=1)[0]
        assert want.cold_coordinates  # fixture sanity: FE-only either way
        assert shed.score == pytest.approx(want.score, rel=1e-5)

    def test_no_shed_when_inactive(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        ctrl = self._controller(scorer, burn=0.0)
        assert ctrl.active is False
        assert ctrl.try_shed(_request(4, entity="nobody")) is None

    def test_continuous_batcher_sheds_at_submit(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        ctrl = self._controller(scorer)
        reqs = [
            _request(i, entity="nobody" if i % 2 else "u2")
            for i in range(8)
        ]
        with ContinuousBatcher(
            scorer, bucket_sizes=(4,), max_wait_s=0.001
        ) as cb:
            ctrl.attach(cb)
            handles = cb.submit_many(reqs)
            cb.flush()
            results = [h.result(timeout=5) for h in handles]
        assert ctrl.shed_total == 4
        want = scorer.score_batch(reqs, bucket_size=8)
        for got, w, req in zip(results, want, reqs):
            assert got.request_id == req.request_id
            if req.entity_ids.get("userId") == "u2":
                assert got.score == w.score  # device path: bitwise
            else:
                assert got.score == pytest.approx(w.score, rel=1e-5)


class TestPriorityLanes:
    def test_micro_batcher_live_drains_before_background(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        order = []
        real = scorer.score_batch

        def spy(requests, bucket_size, **kw):
            order.extend(r.request_id for r in requests)
            return real(requests, bucket_size, **kw)

        scorer.score_batch = spy
        mb = MicroBatcher(scorer, bucket_sizes=(4,), max_wait_s=10.0)
        mb.submit_many(
            [_request(i) for i in range(2)], priority="background"
        )
        assert mb.queue_depth == 2  # below a bucket: nothing drained
        mb.submit_many([_request(10 + i) for i in range(2)])
        out = mb.flush()
        assert len(out) == 4
        # live requests sealed first, background rode the later bucket
        assert order[:2] == ["r10", "r11"]
        assert order[2:4] == ["r0", "r1"]

    def test_micro_batcher_full_background_bucket_waits_for_live(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        mb = MicroBatcher(scorer, bucket_sizes=(2,), max_wait_s=10.0)
        mb._pending.append((_request(0), mb._clock()))  # one live waiting
        out = mb.submit_many(
            [_request(1), _request(2)], priority="background"
        )
        # a full background bucket must NOT seal ahead of pending live
        assert out == []
        assert len(mb._pending_bg) == 2
        out = mb.submit(_request(3))  # completes the live bucket
        assert [r.request_id for r in out][:2] == ["r0", "r3"]

    def test_micro_batcher_poll_drains_background_when_live_empty(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        clock = {"t": 0.0}
        mb = MicroBatcher(
            scorer, bucket_sizes=(4,), max_wait_s=0.5,
            clock=lambda: clock["t"],
        )
        mb.submit_many([_request(0)], priority="background")
        assert mb.poll(now=0.1) == []
        clock["t"] = 1.0
        out = mb.poll(now=1.0)
        assert [r.request_id for r in out] == ["r0"]

    def test_continuous_batcher_background_lane(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        with ContinuousBatcher(
            scorer, bucket_sizes=(4,), max_wait_s=0.001
        ) as cb:
            bg = cb.submit_many(
                [_request(i) for i in range(3)], priority="background"
            )
            live = cb.submit_many([_request(10)])
            cb.flush()
            for h in bg + live:
                assert h.result(timeout=5) is not None
        assert cb.queue_depth == 0

    def test_rejects_unknown_priority(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        mb = MicroBatcher(scorer, bucket_sizes=(4,))
        with pytest.raises(ValueError):
            mb.submit(_request(0), priority="urgent")


class TestDrainTimeQuota:
    def _quota(self, flooder_budget=2):
        return TenantQuota({
            "acme": TenantBudget(rate=0.001, burst=flooder_budget),
            "zen": TenantBudget(rate=0.001, burst=100),
        })

    def test_micro_batcher_drops_over_budget_tenant_at_drain(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        quota = self._quota(flooder_budget=2)
        mb = MicroBatcher(scorer, bucket_sizes=(4,), quota=quota)
        reqs = [
            _request(i, tenant="acme" if i % 2 else "zen")
            for i in range(8)
        ]
        out = mb.submit_many(reqs)
        out.extend(mb.flush())
        # acme offered 4, budget 2 -> 2 shed; zen all served
        assert len(out) == 6
        assert mb.quota_shed_total == 2
        stats = quota.stats()["tenants"]
        assert stats["acme"]["shed"] == 2
        assert stats["zen"]["shed"] == 0

    def test_continuous_batcher_resolves_shed_handles_with_error(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        quota = self._quota(flooder_budget=1)
        with ContinuousBatcher(
            scorer, bucket_sizes=(4,), max_wait_s=0.001, quota=quota
        ) as cb:
            handles = cb.submit_many(
                [_request(i, tenant="acme") for i in range(4)]
            )
            cb.flush()
            ok, shed = 0, 0
            for h in handles:
                try:
                    h.result(timeout=5)
                    ok += 1
                except RuntimeError:
                    shed += 1
        assert ok == 1 and shed == 3
        assert cb.quota_shed_total == 3

    def test_untagged_requests_bypass_quota(self):
        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        quota = TenantQuota({"acme": TenantBudget(rate=0.001, burst=1)})
        mb = MicroBatcher(scorer, bucket_sizes=(4,), quota=quota)
        out = mb.submit_many([_request(i) for i in range(4)])
        assert len(out) == 4
        assert mb.quota_shed_total == 0

    def test_tenancy_plane_drain_mode(self):
        from photon_ml_tpu.serving import TenancyPlane, VariantRegistry
        from photon_ml_tpu.serving.tenancy import tag_requests

        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        registry = VariantRegistry([scorer])
        quota = self._quota(flooder_budget=2)
        plane = TenancyPlane(
            registry, quota=quota, bucket_sizes=(4,),
            quota_mode="drain",
        )
        acme = tag_requests([_request(i) for i in range(4)], "acme")
        zen = tag_requests([_request(100 + i) for i in range(4)], "zen")
        results = plane.replay([*acme, *zen], poll_every=0)
        # submit-time admission is OFF in drain mode: sheds happen at the
        # bucket boundary and land in the quota's own ledger
        assert len(results) == 6
        assert quota.stats()["tenants"]["acme"]["shed"] == 2
        assert plane.tenant_shed == {}

    def test_tenancy_plane_rejects_bad_mode(self):
        from photon_ml_tpu.serving import TenancyPlane, VariantRegistry

        scorer = ShardedGameScorer(
            _artifact(), max_nnz=MAX_NNZ, num_shards=2
        )
        with pytest.raises(ValueError):
            TenancyPlane(
                VariantRegistry([scorer]), quota_mode="sideways"
            )


class TestObservability:
    def test_gauges_written_on_poll(self):
        class Reg:
            def __init__(self):
                self.vals = {}

            def gauge(self, name, v):
                self.vals[name] = v

        reg = Reg()
        ctrl = OverloadController(FakeSLO(1.5), registry=reg)
        ctrl.poll()
        assert reg.vals["serving.overload.burn_rate"] == 1.5
        assert reg.vals["serving.overload.active"] == 1.0
        assert reg.vals["serving.overload.deadline_scale"] == 0.5
        assert reg.vals["serving.overload.shed_total"] == 0.0

    def test_status_doc(self):
        ctrl = OverloadController(FakeSLO(2.0))
        ctrl.poll()
        doc = ctrl.status()
        assert doc["active"] is True
        assert doc["last_burn_rate"] == 2.0
        assert doc["activations"] == 1
        assert doc["shed_total"] == 0

    def test_background_poller_start_stop(self):
        slo = FakeSLO(2.0)
        ctrl = OverloadController(slo, poll_interval_s=0.005)
        with ctrl:
            deadline = time.monotonic() + 2.0
            while not ctrl.active and time.monotonic() < deadline:
                time.sleep(0.005)
            assert ctrl.active is True
        assert ctrl.active is False
