"""Online serving subsystem tests.

The load-bearing guarantees, per ISSUE acceptance criteria:

- the serving path reproduces the offline ``GameModel.score`` to 1e-6 on a
  GLMix fixture, including rows whose entities are absent from the model
  (FE-only fallback, the reference left-join semantics);
- the microbatcher compiles at most one XLA program per bucket size, even
  across differently-shaped request streams;
- LRU cache eviction order, hit accounting and batch pinning;
- artifact export/load round trip (npy tables + PHIX off-heap entity maps);
- the ``serve_game`` CLI never silently rots (fast smoke over the golden
  ratings fixture); the throughput bench itself is ``slow``-marked.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu import testing
from photon_ml_tpu.serving import (
    GameScorer,
    HotEntityCache,
    MicroBatcher,
    ScoreRequest,
    ServingMetrics,
    load_artifact,
    pack_game_model,
    replay_requests,
    requests_from_game_data,
    save_artifact,
)
from photon_ml_tpu.types import TaskType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RATINGS = os.path.join(REPO, "tests", "fixtures", "ratings")

TASK = TaskType.LOGISTIC_REGRESSION
COORDS = {
    "fixed": {"feature_shard": "global"},
    "per_user": {"feature_shard": "per_entity", "random_effect_type": "userId"},
}


@pytest.fixture(scope="module")
def glmix():
    data, _ = testing.generate_glmix_data(
        task=TASK, n_entities=8, rows_per_entity=10, d_global=8, d_entity=4,
        seed=11,
    )
    model = testing.generate_game_model(data, TASK, COORDS, seed=3)
    return data, model, pack_game_model(model)


class TestScoringParity:
    def test_serving_matches_game_model(self, glmix):
        """Acceptance: replayed serving margins == offline GameModel.score
        to 1e-6 on the fixture."""
        data, model, artifact = glmix
        scorer = GameScorer(artifact)
        requests = requests_from_game_data(data, artifact)
        results, snapshot = replay_requests(
            scorer, requests, bucket_sizes=(1, 2, 4, 8, 16)
        )
        assert [r.request_id for r in results] == [
            req.request_id for req in requests
        ]
        expected = model.score(data) + data.offsets
        got = np.array([r.score for r in results], dtype=np.float32)
        np.testing.assert_allclose(got, expected, atol=1e-6)
        # the mean goes through the task link-inverse (sigmoid here)
        means = np.array([r.mean for r in results], dtype=np.float32)
        np.testing.assert_allclose(
            means, 1.0 / (1.0 + np.exp(-expected.astype(np.float64))),
            atol=1e-6,
        )
        assert snapshot["num_requests"] == len(requests)

    def test_unseen_entities_fall_back_to_fe_only(self, glmix):
        """Acceptance: rows naming entities the model never saw score
        FE-only — identical to GameModel.score's left-join zero — not NaN."""
        data, model, artifact = glmix
        cold_data = data.slice_rows(np.arange(data.num_rows) < 16)
        ids = np.array(cold_data.id_tags["userId"], dtype=object).copy()
        ids[::2] = [f"ghost-{i}" for i in range(len(ids[::2]))]
        cold_data.id_tags["userId"] = ids

        scorer = GameScorer(artifact)
        results = scorer.score_batch(
            requests_from_game_data(cold_data, artifact), bucket_size=16
        )
        got = np.array([r.score for r in results], dtype=np.float32)
        assert np.isfinite(got).all()
        expected = model.score(cold_data) + cold_data.offsets
        np.testing.assert_allclose(got, expected, atol=1e-6)
        # and the ghost rows really are the fixed effect alone
        fe_only = model.score_coordinate("fixed", cold_data)
        np.testing.assert_allclose(got[::2], fe_only[::2], atol=1e-6)
        for r in results[::2]:
            assert r.cold_coordinates == ("per_user",)
        for r in results[1::2]:
            assert r.cold_coordinates == ()

    def test_request_without_entity_id_is_fe_only(self, glmix):
        _, _, artifact = glmix
        scorer = GameScorer(artifact)
        req = ScoreRequest(
            "no-entity", {"global": {1: 2.0}, "per_entity": {0: 1.0}}
        )
        (res,) = scorer.score_batch([req])
        fe_w = np.asarray(artifact.tables["fixed"].weights)
        assert res.score == pytest.approx(2.0 * fe_w[1], abs=1e-6)
        assert res.cold_coordinates == ("per_user",)

    def test_padding_does_not_change_scores(self, glmix):
        """Bucket-padding correctness: a request's score is independent of
        the batch composition around it."""
        data, _, artifact = glmix
        requests = requests_from_game_data(data, artifact)[:7]
        scorer = GameScorer(artifact)
        solo = [scorer.score_batch([r], bucket_size=8)[0] for r in requests]
        together = scorer.score_batch(requests, bucket_size=8)
        for a, b in zip(solo, together):
            assert a.score == b.score  # bitwise: same reduction order
            assert a.mean == b.mean

    def test_offsets_are_applied(self, glmix):
        _, _, artifact = glmix
        scorer = GameScorer(artifact)
        base = ScoreRequest("a", {"global": {0: 1.0}})
        shifted = ScoreRequest("b", {"global": {0: 1.0}}, offset=0.5)
        ra, rb = scorer.score_batch([base, shifted])
        assert rb.score == pytest.approx(ra.score + 0.5, abs=1e-6)


class TestCompileDiscipline:
    def test_one_xla_program_per_bucket(self, glmix):
        """Acceptance: across two differently-shaped request streams the
        scorer traces exactly one program per bucket size used."""
        data, _, artifact = glmix
        scorer = GameScorer(artifact)
        requests = requests_from_game_data(data, artifact)
        assert scorer.compile_count == 0

        # stream 1: 19 requests through buckets (4, 8) -> drains two 8s
        # (full) and the 3-leftover through the 4 bucket
        replay_requests(scorer, requests[:19], bucket_sizes=(4, 8))
        assert scorer.compile_count == 2

        # stream 2, differently shaped: 5 requests, same buckets -> the
        # 8-drain and the 4-drain signatures are already compiled
        replay_requests(scorer, requests[19:24], bucket_sizes=(4, 8))
        assert scorer.compile_count == 2

        # a genuinely new bucket size is one more program, exactly
        scorer.score_batch(requests[:2], bucket_size=2)
        assert scorer.compile_count == 3
        scorer.score_batch(requests[5:7], bucket_size=2)
        assert scorer.compile_count == 3

    def test_batcher_pads_to_buckets(self, glmix):
        data, _, artifact = glmix
        scorer = GameScorer(artifact)
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            scorer, bucket_sizes=(2, 4), metrics=metrics
        )
        requests = requests_from_game_data(data, artifact)[:7]
        out = []
        for r in requests[:3]:
            out.extend(batcher.submit(r))
        assert batcher.queue_depth == 3  # below max bucket: still queued
        assert out == []
        out.extend(batcher.flush())
        assert len(out) == 3 and batcher.queue_depth == 0
        snap = metrics.snapshot()
        # 3 pending flush through one 4-bucket (fill 3/4)
        assert snap["num_batches"] == 1
        assert snap["batch_fill_ratio"] == pytest.approx(0.75)


class TestHotEntityCache:
    def test_lru_eviction_order_and_accounting(self):
        backing = np.arange(18, dtype=np.float32).reshape(6, 3)
        cache = HotEntityCache(backing, capacity=2)

        cache.lookup(np.array([0]))          # miss, fill slot
        cache.lookup(np.array([1]))          # miss, cache now full
        assert (cache.hits, cache.misses, cache.evictions) == (0, 2, 0)
        cache.lookup(np.array([0]))          # hit: 0 becomes MRU
        assert cache.hits == 1
        cache.lookup(np.array([2]))          # evicts 1 (LRU), not 0
        assert cache.evictions == 1
        assert cache.cached_entities() == [0, 2]

        # resident rows hold the backing data; the cold slot stays zero
        slots = cache.lookup(np.array([0, 2, -1]))
        table = np.asarray(cache.table)
        np.testing.assert_array_equal(table[slots[0]], backing[0])
        np.testing.assert_array_equal(table[slots[1]], backing[2])
        assert slots[2] == cache.cold_slot
        np.testing.assert_array_equal(table[slots[2]], 0.0)
        assert cache.cold == 1

        stats = cache.stats()
        assert stats["capacity"] == 2 and stats["resident"] == 2
        assert stats["hits"] == cache.hits and stats["misses"] == cache.misses
        assert stats["hit_rate"] == pytest.approx(
            cache.hits / (cache.hits + cache.misses)
        )

    def test_hit_rate_and_stats_before_any_lookup(self):
        """Regression: ``hit_rate()``/``stats()`` on a fresh cache (zero
        lookups) must return 0.0, not raise ZeroDivisionError — the
        introspection endpoint scrapes caches that may never have served."""
        backing = np.ones((4, 2), dtype=np.float32)
        cache = HotEntityCache(backing, capacity=2)
        assert cache.hit_rate() == 0.0
        stats = cache.stats()
        assert stats["hit_rate"] == 0.0
        assert stats["hits"] == 0 and stats["misses"] == 0

    def test_duplicate_entities_in_one_batch_hit(self):
        backing = np.ones((4, 2), dtype=np.float32)
        cache = HotEntityCache(backing, capacity=2)
        slots = cache.lookup(np.array([3, 3, 3]))
        assert len(set(slots.tolist())) == 1
        assert (cache.hits, cache.misses) == (2, 1)

    def test_batch_pinning_guards_capacity(self):
        backing = np.ones((8, 2), dtype=np.float32)
        cache = HotEntityCache(backing, capacity=2)
        with pytest.raises(RuntimeError, match="capacity"):
            cache.lookup(np.array([0, 1, 2]))  # 3 distinct > 2 slots

    def test_batcher_rejects_undersized_cache(self, glmix):
        _, _, artifact = glmix
        scorer = GameScorer(artifact, cache_capacity=4)
        with pytest.raises(ValueError, match="max bucket"):
            MicroBatcher(scorer, bucket_sizes=(8,))

    def test_cached_scoring_equals_uncached(self, glmix):
        """The cache is a pure locality optimization: scores through a
        small LRU must equal full-table gathers, and the accounting must
        line up with the replayed stream."""
        data, _, artifact = glmix
        requests = requests_from_game_data(data, artifact)
        full = GameScorer(artifact)
        cached = GameScorer(artifact, cache_capacity=4)
        r_full, _ = replay_requests(full, requests, bucket_sizes=(4,))
        r_cached, snap = replay_requests(cached, requests, bucket_sizes=(4,))
        np.testing.assert_allclose(
            [r.score for r in r_full], [r.score for r in r_cached], atol=0
        )
        stats = snap["caches"]["per_user"]
        assert stats["hits"] + stats["misses"] == len(requests)
        assert snap["cache_hit_rate"] == pytest.approx(stats["hit_rate"])


class TestFullTableHeadroom:
    def test_pad_rows_reserve_zero_headroom(self):
        """``pad_rows`` puts zero rows between the live rows and the cold
        slot; appends land in them in place (no shape change)."""
        from photon_ml_tpu.serving.scorer import _FullTable

        backing = np.arange(12, dtype=np.float32).reshape(6, 2)
        table = _FullTable(backing, pad_rows=8)
        assert table.capacity == 8 and table.cold_slot == 8
        dev = np.asarray(table.table)
        assert dev.shape == (9, 2)
        np.testing.assert_array_equal(dev[6:], 0.0)  # headroom + cold
        table.update_rows(np.array([6]), np.array([[5.0, 7.0]]))
        dev = np.asarray(table.table)
        np.testing.assert_array_equal(dev[6], [5.0, 7.0])
        assert table.num_rows == 7
        with pytest.raises(ValueError, match="capacity"):
            table.update_rows(np.array([8]), np.array([[1.0, 1.0]]))

    def test_hot_swap_append_into_headroom_zero_retrace(self, glmix):
        """Acceptance: with ``growth_headroom`` a swap can append a brand
        new entity into a zero headroom row — content becomes servable
        with ZERO added compiles (shape unchanged, params are jit args)."""
        from photon_ml_tpu.indexmap import DefaultIndexMap
        from photon_ml_tpu.serving import ServingArtifact, ServingTable

        _, _, artifact = glmix
        scorer = GameScorer(artifact, growth_headroom=True)
        per = artifact.tables["per_user"]
        n = per.weights.shape[0]
        provider = scorer._providers["per_user"]
        assert provider.capacity > n  # headroom actually reserved

        req = ScoreRequest(
            request_id="new-entity",
            features={"global": {0: 1.0}, "per_entity": {0: 1.0}},
            entity_ids={"userId": "brand-new"},
        )
        scorer.score_batch([req], bucket_size=4)
        warm = scorer.compile_count

        new_row = np.full((1, per.dim), 0.25, dtype=np.float32)
        ids = {
            per.entity_index.get_feature_name(i): i for i in range(n)
        }
        ids["brand-new"] = n
        candidate = ServingArtifact(
            task=artifact.task,
            tables={
                **{
                    cid: t
                    for cid, t in artifact.tables.items()
                    if cid != "per_user"
                },
                "per_user": ServingTable(
                    feature_shard=per.feature_shard,
                    random_effect_type=per.random_effect_type,
                    weights=np.vstack([np.asarray(per.weights), new_row]),
                    entity_index=DefaultIndexMap(ids),
                ),
            },
            model_name=artifact.model_name,
        )
        before = scorer.score_batch([req], bucket_size=4)[0]
        assert before.cold_coordinates == ("per_user",)
        # the swap: append bytes into the headroom row, then flip the
        # artifact (entity index) so routing can see the new entity
        scorer.update_random_effect_rows(
            "per_user", np.array([n]), new_row
        )
        scorer.set_artifact(candidate)
        after = scorer.score_batch([req], bucket_size=4)[0]
        assert after.cold_coordinates == ()
        assert after.score == pytest.approx(before.score + 0.25)
        assert scorer.compile_count == warm  # zero retraces


class TestMetrics:
    def test_snapshot_shape(self):
        metrics = ServingMetrics()
        for i in range(10):
            metrics.observe_batch(n_real=3, bucket_size=4, queue_depth=i % 3)
            for _ in range(3):
                metrics.observe_latency(0.001 * (i + 1))
        snap = metrics.snapshot(
            cache_stats={"re": {"hits": 9, "misses": 1, "hit_rate": 0.9}},
            compile_count=2,
        )
        assert snap["num_requests"] == 30 and snap["num_batches"] == 10
        assert snap["batch_fill_ratio"] == pytest.approx(0.75)
        assert (
            snap["latency_p50_s"]
            <= snap["latency_p95_s"]
            <= snap["latency_p99_s"]
            <= snap["latency_max_s"]
        )
        assert sum(snap["latency_histogram"].values()) == 30
        assert snap["queue_depth_max"] == 2
        assert snap["xla_compiles"] == 2
        assert snap["cache_hit_rate"] == pytest.approx(0.9)

    def test_empty_snapshot(self):
        snap = ServingMetrics().snapshot()
        assert snap["num_requests"] == 0
        assert "latency_p99_s" not in snap
        assert "queue_wait_p99_s" not in snap
        assert "swaps" not in snap

    def test_queue_wait_separate_from_latency(self):
        metrics = ServingMetrics()
        for _ in range(4):
            metrics.observe_queue_wait(0.002)
            metrics.observe_latency(0.010)
        snap = metrics.snapshot()
        assert snap["queue_wait_p50_s"] == pytest.approx(0.002)
        assert (
            snap["queue_wait_p50_s"]
            <= snap["queue_wait_p99_s"]
            <= snap["queue_wait_max_s"]
        )
        assert snap["latency_p50_s"] == pytest.approx(0.010)

    def test_bounded_memory_under_sustained_load(self):
        """A long-lived scorer must not grow per-observation state without
        limit: after 100k observations the reservoirs stay at their fixed
        capacity while counts/means/maxima stay exact and the percentile
        estimates stay stable."""
        from photon_ml_tpu.serving.metrics import RESERVOIR_SIZE

        metrics = ServingMetrics()
        rng = np.random.default_rng(42)
        n = 100_000
        lats = rng.lognormal(mean=-6.0, sigma=0.5, size=n)
        for i, lat in enumerate(lats):
            metrics.observe_latency(float(lat))
            metrics.observe_queue_wait(float(lat) * 0.25)
            if i % 8 == 0:
                metrics.observe_batch(n_real=7, bucket_size=8, queue_depth=i % 5)
        # bounded: the retained sample arrays never exceed capacity
        assert len(metrics._latencies) == RESERVOIR_SIZE
        assert len(metrics._queue_waits) == RESERVOIR_SIZE
        assert metrics._latencies.samples().size == RESERVOIR_SIZE

        snap = metrics.snapshot()
        # exact aggregates survive the sampling
        assert metrics._latencies.count == n
        assert sum(snap["latency_histogram"].values()) == n
        # snapshot rounds to 6 decimals
        assert snap["latency_mean_s"] == pytest.approx(lats.mean(), abs=1e-6)
        assert snap["latency_max_s"] == pytest.approx(lats.max(), abs=1e-6)
        assert snap["queue_depth_mean"] == pytest.approx(2.0, abs=0.01)
        assert snap["queue_depth_max"] == 4
        # percentile ESTIMATES stay close to the exact stream percentiles
        p50, p99 = np.percentile(lats, [50, 99])
        assert snap["latency_p50_s"] == pytest.approx(p50, rel=0.05)
        assert snap["latency_p99_s"] == pytest.approx(p99, rel=0.10)
        assert snap["queue_wait_p50_s"] == pytest.approx(p50 * 0.25, rel=0.05)

    def test_small_counts_stay_exact(self):
        """Below reservoir capacity nothing is sampled: percentiles are
        computed from every observation, as before the bound."""
        metrics = ServingMetrics()
        vals = [0.001 * (i + 1) for i in range(30)]
        for v in vals:
            metrics.observe_latency(v)
        snap = metrics.snapshot()
        assert snap["latency_p50_s"] == pytest.approx(
            float(np.percentile(vals, 50))
        )
        assert snap["latency_max_s"] == pytest.approx(0.030)

    def test_swap_counters(self):
        metrics = ServingMetrics()
        metrics.observe_swap(
            generation=1, rows_updated=12, blackout_s=0.01, staleness_s=2.5
        )
        metrics.observe_swap(
            generation=1, rows_updated=0, blackout_s=0.02, rolled_back=True
        )
        swaps = metrics.snapshot()["swaps"]
        assert swaps["num_swaps"] == 2 and swaps["num_rollbacks"] == 1
        # a rollback never advances the generation or the row counters
        assert swaps["current_generation"] == 1
        assert swaps["rows_updated_total"] == 12
        assert swaps["max_blackout_s"] == pytest.approx(0.02)
        assert swaps["last_staleness_s"] == pytest.approx(2.5)

    def test_reservoir_percentile_empty_is_nan(self):
        """An empty reservoir answers NaN shaped like q — scalar q gives a
        scalar NaN, array q gives an all-NaN array — never an IndexError."""
        from photon_ml_tpu.serving.metrics import _Reservoir

        res = _Reservoir(capacity=8)
        scalar = res.percentile(50.0)
        assert np.isscalar(scalar) or np.ndim(scalar) == 0
        assert np.isnan(scalar)
        arr = res.percentile(np.array([50.0, 99.0]))
        assert arr.shape == (2,)
        assert np.isnan(arr).all()

    def test_reservoir_percentile_single_sample(self):
        """One observation: every quantile is that observation."""
        from photon_ml_tpu.serving.metrics import _Reservoir

        res = _Reservoir(capacity=8)
        res.add(0.042)
        assert res.percentile(0.0) == pytest.approx(0.042)
        assert res.percentile(50.0) == pytest.approx(0.042)
        assert res.percentile(99.0) == pytest.approx(0.042)

    def test_reservoir_percentile_array_matches_scalar(self):
        """Vector q answers elementwise-equal to the scalar calls."""
        from photon_ml_tpu.serving.metrics import _Reservoir

        res = _Reservoir(capacity=64)
        res.add_many([0.001 * (i + 1) for i in range(30)])
        qs = np.array([10.0, 50.0, 90.0, 99.0])
        vec = res.percentile(qs)
        assert vec.shape == qs.shape
        for q, v in zip(qs, vec):
            assert v == pytest.approx(res.percentile(float(q)))


class TestBatcherDeadline:
    def test_poll_drains_on_deadline(self, glmix):
        """Deadline policy: nothing drains before max_wait_s; once the
        OLDEST pending request times out, everything pending rides along."""
        data, _, artifact = glmix
        scorer = GameScorer(artifact)
        now = [0.0]
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            scorer, bucket_sizes=(4, 16), metrics=metrics,
            clock=lambda: now[0], max_wait_s=0.005,
        )
        requests = requests_from_game_data(data, artifact)[:3]
        for r in requests:
            batcher.submit(r)
            now[0] += 0.001
        assert batcher.poll() == []  # oldest has waited 2ms < 5ms
        assert batcher.queue_depth == 3
        now[0] = 0.006
        out = batcher.poll()
        assert len(out) == 3 and batcher.queue_depth == 0
        snap = metrics.snapshot()
        # queue wait is measured enqueue->dequeue, separate from latency
        assert snap["queue_wait_max_s"] == pytest.approx(0.006)
        assert snap["queue_wait_p50_s"] <= snap["queue_wait_max_s"]
        assert snap["num_batches"] == 1

    def test_poll_accepts_external_now(self, glmix):
        data, _, artifact = glmix
        scorer = GameScorer(artifact)
        batcher = MicroBatcher(
            scorer, bucket_sizes=(4,), clock=lambda: 0.0, max_wait_s=1.0,
        )
        batcher.submit(requests_from_game_data(data, artifact)[0])
        assert batcher.poll(now=0.5) == []
        assert len(batcher.poll(now=1.5)) == 1

    def test_poll_without_deadline_raises(self, glmix):
        _, _, artifact = glmix
        batcher = MicroBatcher(GameScorer(artifact), bucket_sizes=(4,))
        with pytest.raises(ValueError, match="max_wait_s"):
            batcher.poll()

    def test_negative_deadline_rejected(self, glmix):
        _, _, artifact = glmix
        with pytest.raises(ValueError, match="max_wait_s"):
            MicroBatcher(
                GameScorer(artifact), bucket_sizes=(4,), max_wait_s=-0.1
            )


class TestArtifact:
    def test_export_load_round_trip(self, glmix, tmp_path):
        data, model, artifact = glmix
        out = str(tmp_path / "artifact")
        save_artifact(artifact, out)

        # layout: metadata + npy tables + PHIX off-heap entity store
        assert os.path.exists(os.path.join(out, "model-metadata.json"))
        assert os.path.exists(os.path.join(out, "fixed-effect", "fixed.npy"))
        re_dir = os.path.join(out, "random-effect", "per_user")
        assert os.path.exists(os.path.join(re_dir, "table.npy"))
        assert os.path.exists(
            os.path.join(re_dir, "entity-index", "partition-0.bin")
        )

        loaded = load_artifact(out)
        assert loaded.task is TASK
        np.testing.assert_array_equal(
            np.asarray(loaded.tables["fixed"].weights),
            np.asarray(artifact.tables["fixed"].weights),
        )
        np.testing.assert_array_equal(
            np.asarray(loaded.tables["per_user"].weights),
            np.asarray(artifact.tables["per_user"].weights),
        )
        # entity rows resolve identically through the off-heap store
        for eid in sorted(set(map(str, data.id_tags["userId"]))):
            assert loaded.entity_row("per_user", eid) == artifact.entity_row(
                "per_user", eid
            )
        assert loaded.entity_row("per_user", "ghost") == -1

        requests = requests_from_game_data(data, loaded)
        results = GameScorer(loaded).score_batch(requests, len(requests))
        expected = model.score(data) + data.offsets
        np.testing.assert_allclose(
            np.array([r.score for r in results]), expected, atol=1e-6
        )

    def test_feature_index_round_trip(self, glmix, tmp_path):
        from photon_ml_tpu.indexmap import DefaultIndexMap

        data, model, _ = glmix
        imap = DefaultIndexMap({f"f{i}": i for i in range(8)})
        artifact = pack_game_model(model, index_maps={"global": imap})
        out = str(tmp_path / "artifact")
        save_artifact(artifact, out)
        loaded = load_artifact(out)
        assert set(loaded.feature_index) == {"global"}
        for name in ("f0", "f3", "f7"):
            assert loaded.feature_index["global"].get_index(name) == (
                imap.get_index(name)
            )
        assert loaded.feature_index["global"].get_index("missing") == -1

    def test_load_rejects_non_artifact_dir(self, glmix, tmp_path):
        from photon_ml_tpu.io.model_io import save_game_model_metadata

        save_game_model_metadata(str(tmp_path), TASK)
        with pytest.raises(ValueError, match="serving"):
            load_artifact(str(tmp_path))


class TestEvents:
    def test_scoring_events_emitted(self, glmix):
        from photon_ml_tpu.event import (
            EventEmitter,
            EventListener,
            ScoringFinishEvent,
            ScoringStartEvent,
        )

        data, _, artifact = glmix
        seen = []

        class Recorder(EventListener):
            def on_event(self, event):
                seen.append(event)

        emitter = EventEmitter()
        emitter.register_listener(Recorder())
        requests = requests_from_game_data(data, artifact)[:6]
        replay_requests(
            GameScorer(artifact), requests, bucket_sizes=(2, 4),
            emitter=emitter, model_id="m1",
        )
        assert [type(e) for e in seen] == [ScoringStartEvent, ScoringFinishEvent]
        start, finish = seen
        assert start.model_id == "m1" and start.num_requests == 6
        assert finish.num_requests == 6
        assert finish.metrics["num_requests"] == 6
        assert finish.wall_seconds >= 0

    def test_register_listener_class_bad_module(self):
        from photon_ml_tpu.event import EventEmitter

        emitter = EventEmitter()
        with pytest.raises(ValueError, match="no_such_module.Listener"):
            emitter.register_listener_class("no_such_module.Listener")

    def test_register_listener_class_bad_attribute(self):
        from photon_ml_tpu.event import EventEmitter

        emitter = EventEmitter()
        with pytest.raises(
            ValueError, match="photon_ml_tpu.event.*NoSuchListener"
        ):
            emitter.register_listener_class("photon_ml_tpu.event.NoSuchListener")

    def test_register_listener_class_not_dotted(self):
        from photon_ml_tpu.event import EventEmitter

        with pytest.raises(ValueError, match="dotted"):
            EventEmitter().register_listener_class("JustAName")


def _ratings_model_dir(tmp_path_factory):
    """A GAME model over the committed golden ratings fixture (random
    coefficients — CLI plumbing under test, not model quality)."""
    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration,
        read_game_data,
    )
    from photon_ml_tpu.io.model_io import save_game_model

    shard_cfg = {
        "global": FeatureShardConfiguration(
            feature_bags=["features"], add_intercept=True
        ),
        "per_user": FeatureShardConfiguration(
            feature_bags=["userFeatures"], add_intercept=False
        ),
    }
    data, index_maps, _ = read_game_data(
        [os.path.join(RATINGS, "train")], shard_cfg, id_tags=["userId"],
    )
    model = testing.generate_game_model(
        data, TaskType.LINEAR_REGRESSION,
        {
            "fixed": {"feature_shard": "global"},
            "per_user": {
                "feature_shard": "per_user",
                "random_effect_type": "userId",
            },
        },
        seed=5,
    )
    out = str(tmp_path_factory.mktemp("ratings-model"))
    save_game_model(
        model, out, index_maps=index_maps,
        configurations={
            "feature_shards": {
                "global": {"feature_bags": ["features"], "add_intercept": True},
                "per_user": {
                    "feature_bags": ["userFeatures"], "add_intercept": False,
                },
            }
        },
    )
    return out


@pytest.fixture(scope="module")
def ratings_model_dir(tmp_path_factory):
    return _ratings_model_dir(tmp_path_factory)


class TestServeGameCli:
    def test_smoke_over_golden_fixture(self, ratings_model_dir, tmp_path):
        """Tier-1 smoke: pack + export + replay a few hundred requests from
        the committed ratings fixture through the real CLI entrypoint."""
        from photon_ml_tpu.cli.serve_game import main as serve_main

        artifact_dir = str(tmp_path / "artifact")
        metrics_file = str(tmp_path / "metrics.json")
        rc = serve_main([
            "--model-dir", ratings_model_dir,
            "--data-dirs", os.path.join(RATINGS, "test"),
            "--export-artifact-dir", artifact_dir,
            "--metrics-output", metrics_file,
            "--max-requests", "200",
            "--bucket-sizes", "4,16",
            "--cache-capacity", "64",
        ])
        assert rc == 0
        with open(metrics_file) as f:
            snap = json.load(f)
        assert snap["num_requests"] == 200
        assert snap["latency_p99_s"] > 0
        assert snap["requests_per_s"] > 0
        assert snap["xla_compiles"] <= 2  # one program per bucket, at most
        assert 0.0 <= snap["cache_hit_rate"] <= 1.0
        assert snap["batch_fill_ratio"] > 0

        # second leg of train -> export -> serve: serve from the artifact
        metrics2 = str(tmp_path / "metrics2.json")
        rc = serve_main([
            "--artifact-dir", artifact_dir,
            "--data-dirs", os.path.join(RATINGS, "test"),
            "--metrics-output", metrics2,
            "--max-requests", "50",
        ])
        assert rc == 0
        with open(metrics2) as f:
            assert json.load(f)["num_requests"] == 50

    def test_tenants_flag_tracks_per_tenant_slo(
        self, ratings_model_dir, tmp_path
    ):
        """--tenants + --slo-latency-ms: the replayed stream is tagged
        round-robin and each tenant's SLO tracker writes its own
        tenant-labeled serving.slo.* series into the process registry."""
        from photon_ml_tpu.cli.serve_game import main as serve_main
        from photon_ml_tpu.serving import prometheus_text
        from photon_ml_tpu.telemetry.metrics import get_registry

        metrics_file = str(tmp_path / "metrics.json")
        rc = serve_main([
            "--model-dir", ratings_model_dir,
            "--data-dirs", os.path.join(RATINGS, "test"),
            "--metrics-output", metrics_file,
            "--max-requests", "64",
            "--bucket-sizes", "4,16",
            "--cache-capacity", "64",
            "--tenants", "alpha,beta",
            "--slo-latency-ms", "1000",
        ])
        assert rc == 0
        with open(metrics_file) as f:
            assert json.load(f)["num_requests"] == 64
        text = prometheus_text(get_registry().snapshot())
        assert 'tenant="alpha"' in text
        assert 'tenant="beta"' in text

    def test_variants_flag_serves_through_tenancy_plane(
        self, ratings_model_dir, tmp_path
    ):
        """--variants: the replay runs through the full tenancy plane —
        per-tenant quota admission, the seeded variant router, and one
        batcher per variant over the shared sharded scorer — and the
        snapshot carries the tenancy status block."""
        from photon_ml_tpu.cli.serve_game import main as serve_main

        metrics_file = str(tmp_path / "metrics.json")
        rc = serve_main([
            "--model-dir", ratings_model_dir,
            "--data-dirs", os.path.join(RATINGS, "test"),
            "--metrics-output", metrics_file,
            "--max-requests", "128",
            "--bucket-sizes", "4,16",
            "--tenants", "alpha,beta",
            "--slo-latency-ms", "1000",
            "--variants", "candidate",
            "--variant-ramp", "50",
            "--tenant-rate", "1",
            "--tenant-burst", "40",
        ])
        assert rc == 0
        with open(metrics_file) as f:
            snap = json.load(f)
        assert snap["serving_mode"] == "sharded-tenancy"
        ten = snap["tenancy"]
        # both variants exist and both actually served traffic
        assert set(ten["variants"]) == {"base", "candidate"}
        assert ten["router"]["ramps"]["*"]["candidate"] == 50.0
        assert ten["router"]["decisions"].get("candidate", 0) > 0
        assert ten["router"]["decisions"].get("base", 0) > 0
        # the candidate is undiverged: scores stay bitwise the base's
        assert ten["variants"]["candidate"]["diverged"] is False
        # quota: each tenant gets 64 of the 128; burst 40 sheds the rest,
        # charged per tenant
        quota = ten["quota"]["tenants"]
        for tenant in ("alpha", "beta"):
            assert quota[tenant]["admitted"] >= 40
            assert quota[tenant]["shed"] > 0
        # sheds never reach the scorer
        assert snap["num_requests"] == sum(
            quota[t]["admitted"] for t in ("alpha", "beta")
        )
        assert snap["num_results"] == snap["num_requests"]
        # per-tenant SLO budgets rode along on the shared request plane
        assert set(ten["tenants"]) == {"alpha", "beta"}

    def test_variants_rejects_cached_mode(self, ratings_model_dir):
        from photon_ml_tpu.cli.serve_game import main as serve_main

        with pytest.raises(SystemExit, match="cache-capacity"):
            serve_main([
                "--model-dir", ratings_model_dir,
                "--data-dirs", os.path.join(RATINGS, "test"),
                "--max-requests", "8",
                "--cache-capacity", "64",
                "--variants", "candidate",
            ])

    def test_export_only_invocation(self, ratings_model_dir, tmp_path):
        from photon_ml_tpu.cli.serve_game import main as serve_main

        artifact_dir = str(tmp_path / "artifact")
        rc = serve_main([
            "--model-dir", ratings_model_dir,
            "--export-artifact-dir", artifact_dir,
        ])
        assert rc == 0
        assert load_artifact(artifact_dir).tables["per_user"].n_entities > 0

    def test_nothing_to_do_exits_nonzero(self, ratings_model_dir):
        from photon_ml_tpu.cli.serve_game import main as serve_main

        assert serve_main(["--model-dir", ratings_model_dir]) == 2


class TestScoreGameMissingEntityPolicy:
    @pytest.fixture(scope="class")
    def scored_setup(self, tmp_path_factory):
        """Model over the ratings train split, scored against the test
        split PLUS rows naming users the model never saw."""
        import shutil

        from photon_ml_tpu.io.avro import read_avro_dir
        from photon_ml_tpu.io.data_reader import write_training_examples

        model_dir = _ratings_model_dir(tmp_path_factory)
        data_dir = tmp_path_factory.mktemp("score-data")
        recs = list(
            read_avro_dir(os.path.join(RATINGS, "test"))
        )[:30]
        ghosts = 0
        for i, rec in enumerate(recs):
            rec.setdefault("metadataMap", {})
            if i % 3 == 0:
                rec["metadataMap"]["userId"] = f"ghost-{i}"
                ghosts += 1
            rec["uid"] = f"row-{i:04d}"
        assert ghosts > 0

        def to_writer(rec):
            out = {
                "uid": rec["uid"],
                "label": rec.get("label"),
                "metadataMap": rec.get("metadataMap"),
            }
            for bag in ("features", "userFeatures", "movieFeatures"):
                if rec.get(bag):
                    out[bag] = [
                        (f["name"], f["term"], f["value"]) for f in rec[bag]
                    ]
            return out

        write_training_examples(
            str(data_dir / "part-00000.avro"), [to_writer(r) for r in recs]
        )
        return model_dir, str(data_dir), ghosts

    def test_fe_only_policy_scores_unknown_entities(
        self, scored_setup, tmp_path
    ):
        """Satellite regression: unknown entities score FE-only — never
        NaN, never a crash — matching the serving path's fallback."""
        from photon_ml_tpu.cli.score_game import parse_args, run
        from photon_ml_tpu.io.scores_io import load_scores

        model_dir, data_dir, _ = scored_setup
        out = str(tmp_path / "scores")
        run(parse_args([
            "--data-dirs", data_dir,
            "--model-dir", model_dir,
            "--output-dir", out,
            "--missing-entity-policy", "fe-only",
        ]))
        scored = {s.uid: s for s in load_scores(out)}
        assert len(scored) == 30
        scores = np.array(
            [scored[f"row-{i:04d}"].prediction_score for i in range(30)]
        )
        assert np.isfinite(scores).all()

        # ghost rows = fixed-effect-only scores, computed independently
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )
        from photon_ml_tpu.io.model_io import load_game_model

        model, index_maps = load_game_model(model_dir)
        data, _, uids = read_game_data(
            [data_dir],
            {
                "global": FeatureShardConfiguration(
                    feature_bags=["features"], add_intercept=True
                ),
                "per_user": FeatureShardConfiguration(
                    feature_bags=["userFeatures"], add_intercept=False
                ),
            },
            index_maps, id_tags=["userId"], is_response_required=False,
        )
        fe_only = model.score_coordinate("fixed", data) + data.offsets
        by_uid = dict(zip(uids, fe_only))
        for i in range(0, 30, 3):
            uid = f"row-{i:04d}"
            assert scored[uid].prediction_score == pytest.approx(
                float(by_uid[uid]), abs=1e-5
            )

    def test_error_policy_raises(self, scored_setup, tmp_path):
        from photon_ml_tpu.cli.score_game import parse_args, run

        model_dir, data_dir, _ = scored_setup
        with pytest.raises(ValueError, match="ghost-0"):
            run(parse_args([
                "--data-dirs", data_dir,
                "--model-dir", model_dir,
                "--output-dir", str(tmp_path / "scores"),
                "--missing-entity-policy", "error",
            ]))


@pytest.mark.slow
class TestServingBench:
    def test_bench_serving_contract(self):
        """`python bench.py --serving` emits one well-formed JSON line with
        the p99/throughput contract (smoke shapes on CPU)."""
        env = dict(os.environ, BENCH_SMOKE="1", JAX_PLATFORMS="cpu")
        env.pop("BENCH_SERVING_WRITE", None)
        out_path = os.path.join(REPO, "BENCH_SERVING.json")
        mtime_before = (
            os.path.getmtime(out_path) if os.path.exists(out_path) else None
        )
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--serving"],
            capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        assert payload["metric"] == "serving_p99_latency_s"
        assert "error" not in payload
        assert payload["value"] > 0
        assert payload["requests_per_s"] > 0
        assert payload["latency_p50_s"] <= payload["latency_p99_s"]
        assert payload["serving_mode"] == "sharded-continuous"
        assert 0.0 <= payload["device_resident_rate"] <= 1.0
        assert payload["admission"]["admitted_total"] >= 0
        assert "per_user" in payload["residency"]
        # compile-once-per-bucket holds on the bench path too, even with
        # the admission tier scattering rows in the background
        assert payload["warm_compiles"] == len(payload["bucket_sizes"])
        assert payload["post_warmup_compiles"] == 0
        # eviction-policy A/B: both arms recorded with rates in range and
        # zero post-warmup compiles (victim choice must not retrace)
        ab = payload["eviction_ab"]
        assert ab["device_budget_rows"] > 0
        for arm in ("oldest", "importance"):
            stats = ab[arm]
            assert 0.0 <= stats["device_resident_rate"] <= 1.0
            assert 0.0 <= stats["deferred_rate"] <= 1.0
            assert stats["evicted_total"] >= 0
            assert stats["post_warmup_compiles"] == 0
        assert "resident_rate_gain" in ab
        # smoke must not overwrite a committed measurement
        mtime_after = (
            os.path.getmtime(out_path) if os.path.exists(out_path) else None
        )
        assert mtime_after == mtime_before

    def test_bench_serving_committed_artifact(self):
        """The committed full-scale record must back the importance-eviction
        claim: at the same device budget on the Zipf-replay A/B, scoring
        victims by request-frequency x coefficient-norm keeps a higher
        device-resident rate than oldest-admitted FIFO."""
        path = os.path.join(REPO, "BENCH_SERVING.json")
        assert os.path.exists(path), "full-scale --serving record missing"
        with open(path) as f:
            payload = json.load(f)
        assert payload["metric"] == "serving_p99_latency_s"
        ab = payload["eviction_ab"]
        assert ab["importance"]["device_resident_rate"] > (
            ab["oldest"]["device_resident_rate"]
        )
        assert ab["oldest"]["post_warmup_compiles"] == 0
        assert ab["importance"]["post_warmup_compiles"] == 0
