"""Pallas fused-kernel tests (interpret mode on the CPU mesh): both variants
must match the XLA objective bit-for-bit-ish (f32 tolerances), including the
normalization-shift coefficient sum, padding no-ops, and vmap batching of
the single-block kernel (the per-entity random-effect inner loop)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from photon_ml_tpu.losses.pointwise import LogisticLoss, PoissonLoss, SquaredLoss
from photon_ml_tpu.ops.pallas_kernels import (
    fused_value_grad,
    fused_value_grad_single,
)

_LOSS = {"logistic": LogisticLoss, "squared": SquaredLoss, "poisson": PoissonLoss}


def _reference(kind, X, y, off, wt, w):
    z = X @ w + off
    if kind == "logistic":
        l = np.logaddexp(0, z) - y * z
        d1 = 1 / (1 + np.exp(-z)) - y
    elif kind == "squared":
        l = 0.5 * (z - y) ** 2
        d1 = z - y
    else:
        l = np.exp(z) - y * z
        d1 = np.exp(z) - y
    lw = np.where(wt > 0, wt * l, 0.0)
    dz = np.where(wt > 0, wt * d1, 0.0)
    return lw.sum(), dz @ X, dz.sum()


@pytest.mark.parametrize("kind", ["logistic", "squared", "poisson"])
@pytest.mark.parametrize("variant", ["blocked", "single"])
def test_fused_matches_reference(rng, kind, variant):
    n, d = (700, 37) if variant == "blocked" else (50, 13)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = (0.3 * rng.normal(size=d)).astype(np.float32)
    off = (0.1 * rng.normal(size=n)).astype(np.float32)
    wt = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    wt[::7] = 0.0  # padding-style rows
    if kind == "logistic":
        y = (rng.random(n) > 0.5).astype(np.float32)
    elif kind == "poisson":
        y = rng.poisson(1.0, size=n).astype(np.float32)
    else:
        y = rng.normal(size=n).astype(np.float32)

    fn = fused_value_grad if variant == "blocked" else fused_value_grad_single
    val, grad, csum = fn(X, y, off, wt, w, kind=_LOSS[kind], interpret=True)
    rv, rg, rc = _reference(kind, X, y, off, wt, w)
    assert float(val) == pytest.approx(rv, rel=2e-4)
    np.testing.assert_allclose(np.asarray(grad), rg, rtol=2e-3, atol=2e-3)
    assert float(csum) == pytest.approx(rc, rel=2e-3, abs=2e-3)


def test_blocked_multi_block_accumulation(rng):
    """n spanning several row blocks exercises the cross-step accumulator."""
    n, d = 1000, 130  # > ROW_BLOCK rows, > LANE columns
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = (0.1 * rng.normal(size=d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    z = np.zeros(n, dtype=np.float32)
    wt = np.ones(n, dtype=np.float32)
    val, grad, csum = fused_value_grad(X, y, z, wt, w, kind=LogisticLoss,
                                       interpret=True)
    rv, rg, rc = _reference("logistic", X, y, z, wt, w)
    assert float(val) == pytest.approx(rv, rel=2e-4)
    np.testing.assert_allclose(np.asarray(grad), rg, rtol=2e-3, atol=5e-3)


def test_single_kernel_vmaps(rng):
    """vmap over entities — the RE inner-loop batching pattern."""
    E, s, d = 6, 24, 10
    X = rng.normal(size=(E, s, d)).astype(np.float32)
    w = (0.2 * rng.normal(size=(E, d))).astype(np.float32)
    y = (rng.random((E, s)) > 0.5).astype(np.float32)
    off = np.zeros((E, s), dtype=np.float32)
    wt = np.ones((E, s), dtype=np.float32)

    batched = jax.vmap(
        lambda Xi, yi, oi, wti, wi: fused_value_grad_single(
            Xi, yi, oi, wti, wi, kind=LogisticLoss, interpret=True
        )
    )
    vals, grads, csums = batched(X, y, off, wt, w)
    assert vals.shape == (E,)
    assert grads.shape == (E, d)
    for e in range(E):
        rv, rg, _ = _reference("logistic", X[e], y[e], off[e], wt[e], w[e])
        assert float(vals[e]) == pytest.approx(rv, rel=2e-4)
        np.testing.assert_allclose(np.asarray(grads[e]), rg, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("variant,n,d", [("single", 50, 13), ("blocked", 700, 37)])
def test_native_tpu_lowering(variant, n, d):
    """Mosaic (native TPU) lowering must succeed — interpret-mode tests
    alone would let scalar-store / tile-rule violations ship. jax.export
    cross-lowers for the tpu platform without needing a chip."""
    import functools

    fn = fused_value_grad_single if variant == "single" else fused_value_grad
    args = (
        jax.ShapeDtypeStruct((n, d), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((d,), jnp.float32),
    )
    f = jax.jit(functools.partial(fn, kind=LogisticLoss, interpret=False))
    exported = jax.export.export(f, platforms=["tpu"])(*args)
    assert len(exported.mlir_module()) > 0


def test_single_kernel_native_lowering_under_vmap():
    """The RE inner loop vmaps the single kernel; that too must lower."""
    import functools

    E, s, d = 4, 24, 10
    f = jax.vmap(
        functools.partial(
            fused_value_grad_single, kind=LogisticLoss, interpret=False
        )
    )
    args = (
        jax.ShapeDtypeStruct((E, s, d), jnp.float32),
        jax.ShapeDtypeStruct((E, s), jnp.float32),
        jax.ShapeDtypeStruct((E, s), jnp.float32),
        jax.ShapeDtypeStruct((E, s), jnp.float32),
        jax.ShapeDtypeStruct((E, d), jnp.float32),
    )
    exported = jax.export.export(jax.jit(f), platforms=["tpu"])(*args)
    assert len(exported.mlir_module()) > 0


def test_objective_uses_xla_when_disabled(rng):
    """With the env flag unset, the objective must not route into pallas."""
    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops.features import DenseFeatures

    n, d = 40, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    objective = make_glm_objective(LogisticLoss)
    v, g = objective.value_and_grad(jnp.zeros(d), data, jnp.float32(0.0))
    rv, rg, _ = _reference("logistic", X, y, np.zeros(n, np.float32),
                           np.ones(n, np.float32), np.zeros(d, np.float32))
    assert float(v) == pytest.approx(rv, rel=1e-4)
    np.testing.assert_allclose(np.asarray(g), rg, rtol=1e-3, atol=1e-3)
