"""Failure-plane chaos harness (photon_ml_tpu.resilience).

The reference Photon-ML inherited fault tolerance from Spark (lineage
recompute, task retry, supervised executors); this runtime carries its
own failure plane and this module is its chaos gate:

- every registered fault site is armed at least once here
  (``test_chaos_covers_every_registered_site`` pins the coverage);
- transient (recovered) faults leave training output **bitwise
  identical** to a fault-free run, and an armed-but-never-firing site is
  bitwise invisible (the disabled-path parity contract);
- permanent faults degrade, never kill: blocks are skipped into the
  progress ledger and excluded from gap scheduling, corrupt deltas keep
  the previous serving generation, a dead admission daemon flips
  ``/healthz`` to 503 while the scorer keeps answering FE-only.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.resilience import (
    FatalInjectedFault,
    InjectedFault,
    RetryExhausted,
    RetryPolicy,
    SupervisedThread,
    arm_fault,
    clear_failures,
    configure_faults,
    fault_point,
    fault_stats,
    parse_fault_env,
    recent_failures,
    record_failure,
    register_fault_site,
    registered_fault_sites,
    reset_faults,
)
from photon_ml_tpu.telemetry.metrics import get_registry

# Every fault site the production modules register. Importing these
# modules is what registers the sites; the coverage test below fails if a
# new site appears without a chaos test arming it here.
import photon_ml_tpu.checkpoint  # noqa: F401  train.checkpoint.publish
import photon_ml_tpu.parallel.cluster.worker  # noqa: F401  cluster.worker_block
import photon_ml_tpu.serving.admission  # noqa: F401  serve.admission.*
import photon_ml_tpu.serving.hotswap  # noqa: F401  serve.delta.load
import photon_ml_tpu.streaming.blockcache  # noqa: F401  stream.blockcache.*
import photon_ml_tpu.streaming.blocks  # noqa: F401  stream.read/build

COVERED_SITES = {
    "stream.read_part_file",
    "stream.build_block",
    "stream.blockcache.load",
    "stream.blockcache.store",
    "serve.admission.step",
    "serve.admission.stage",
    "serve.delta.load",
    "train.checkpoint.publish",
    "cluster.worker_block",
}


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts and ends with nothing armed and an empty ring."""
    reset_faults()
    clear_failures()
    yield
    reset_faults()
    clear_failures()


def _counter(name):
    return get_registry().snapshot()["counters"].get(name, 0)


def _failure_kinds():
    return [r["kind"] for r in recent_failures()]


# ===================================================================== units
class TestFaultPoints:
    def test_chaos_covers_every_registered_site(self):
        assert set(registered_fault_sites()) == COVERED_SITES

    def test_parse_env_spec(self):
        specs = parse_fault_env(
            "a=once:2, b=every:5,c=prob:0.25:7,d=once:1!fatal"
        )
        assert specs["a"].mode == "once" and specs["a"].param == 2
        assert specs["b"].mode == "every" and specs["b"].param == 5
        assert specs["c"].mode == "prob" and specs["c"].seed == 7
        assert specs["d"].fatal and not specs["a"].fatal

    @pytest.mark.parametrize("bad", ["nonsense", "a=warp:3", "a=prob:2.0"])
    def test_bad_spec_rejected(self, bad):
        with pytest.raises(ValueError):
            configure_faults(bad)

    def test_once_fires_exactly_on_nth_call(self):
        site = register_fault_site("chaos.test.once", "test seam")
        configure_faults({site: parse_fault_env(f"{site}=once:3")[site]})
        fault_point(site)
        fault_point(site)
        with pytest.raises(InjectedFault):
            fault_point(site)
        fault_point(site)  # only call 3, ever
        assert fault_stats()[site] == {"calls": 4, "trips": 1}

    def test_every_nth_and_fatal(self):
        site = register_fault_site("chaos.test.every", "test seam")
        configure_faults(f"{site}=every:2!fatal")
        fault_point(site)
        with pytest.raises(FatalInjectedFault):
            fault_point(site)
        fault_point(site)
        with pytest.raises(FatalInjectedFault):
            fault_point(site)

    def test_prob_is_seeded_and_reproducible(self):
        site = register_fault_site("chaos.test.prob", "test seam")

        def trips():
            configure_faults(f"{site}=prob:0.5:11")
            fired = []
            for i in range(50):
                try:
                    fault_point(site)
                    fired.append(False)
                except InjectedFault:
                    fired.append(True)
            return fired

        first, second = trips(), trips()
        assert first == second
        assert any(first) and not all(first)

    def test_env_var_arms_faults(self, monkeypatch):
        site = register_fault_site("chaos.test.env", "test seam")
        monkeypatch.setenv("PHOTON_FAULTS", f"{site}=once:1")
        reset_faults()  # forget the env was read, so it re-reads
        with pytest.raises(InjectedFault):
            fault_point(site)

    def test_unarmed_site_is_a_noop(self):
        site = register_fault_site("chaos.test.noop", "test seam")
        for _ in range(10):
            fault_point(site)
        assert fault_stats() == {}

    def test_trips_are_counted_in_the_registry(self):
        site = register_fault_site("chaos.test.count", "test seam")
        before = _counter(f"resilience.fault.{site}.trips")
        configure_faults(f"{site}=once:1")
        with pytest.raises(InjectedFault):
            fault_point(site)
        assert _counter(f"resilience.fault.{site}.trips") == before + 1


class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("sleep", lambda s: None)
        kw.setdefault("base_delay_s", 0.0)
        return RetryPolicy(**kw)

    def test_recovers_from_transient_failure(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        before = _counter("resilience.retry.t.recovered")
        assert self._policy().run("t", flaky) == "ok"
        assert calls["n"] == 3
        assert _counter("resilience.retry.t.recovered") == before + 1

    def test_exhaustion_raises_and_records(self):
        def dead():
            raise OSError("always")

        before = _counter("resilience.retry.t2.exhausted")
        with pytest.raises(RetryExhausted) as ei:
            self._policy(max_attempts=3).run("t2", dead)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, OSError)
        assert _counter("resilience.retry.t2.exhausted") == before + 1
        assert "retry_exhausted" in _failure_kinds()

    def test_non_retryable_raises_immediately(self):
        calls = {"n": 0}

        def missing():
            calls["n"] += 1
            raise FileNotFoundError("not a transient fault")

        with pytest.raises(FileNotFoundError):
            self._policy().run("t3", missing)
        assert calls["n"] == 1

        def fatal():
            calls["n"] += 1
            raise FatalInjectedFault("chaos")

        with pytest.raises(FatalInjectedFault):
            self._policy().run("t3", fatal)
        assert calls["n"] == 2

    def test_jitter_is_deterministic(self):
        p = RetryPolicy()
        assert p.delay_for("site", 2) == p.delay_for("site", 2)
        assert p.delay_for("site", 1) != p.delay_for("other", 1)

    def test_on_retry_callback_sees_each_failure(self):
        seen = []

        def flaky():
            if len(seen) < 2:
                raise OSError("x")
            return 1

        self._policy().run(
            "t4", flaky, on_retry=lambda a, e: seen.append((a, str(e)))
        )
        assert [a for a, _ in seen] == [1, 2]


class TestFailureRing:
    def test_records_are_ordered_and_counted(self):
        before = _counter("resilience.failures")
        record_failure("test_kind", "test.site", "detail", block=3)
        record_failure("test_kind", "test.site", "detail2")
        recs = recent_failures()
        assert [r["kind"] for r in recs] == ["test_kind", "test_kind"]
        assert recs[0]["seq"] < recs[1]["seq"]
        assert recs[0]["block"] == 3
        assert _counter("resilience.failures") == before + 2
        assert _counter("resilience.failures.test_kind") >= 2

    def test_ring_is_bounded(self):
        for i in range(300):
            record_failure("flood", "test.site", str(i))
        recs = recent_failures(1000)
        assert len(recs) == 256
        assert recs[-1]["detail"] == "299"

    def test_sink_errors_are_swallowed(self):
        from photon_ml_tpu.resilience import add_failure_sink, remove_failure_sink

        def bad_sink(rec):
            raise RuntimeError("sink exploded")

        add_failure_sink(bad_sink)
        try:
            record_failure("test_kind", "test.site")  # must not raise
        finally:
            remove_failure_sink(bad_sink)
        assert "test_kind" in _failure_kinds()


class TestSupervisedThread:
    def test_tick_crash_restarts_and_recovers(self):
        hits, crashed = [], []

        def tick():
            hits.append(1)
            if len(crashed) < 2:
                crashed.append(1)
                raise RuntimeError("tick exploded")
            if len(hits) > 10:
                time.sleep(0.001)

        t = SupervisedThread(
            "chaos-tick", tick, max_restarts=5, restart_backoff_s=0.001
        )
        t.start()
        deadline = time.time() + 5
        while len(hits) < 5 and time.time() < deadline:
            time.sleep(0.005)
        t.stop()
        s = t.stats()
        assert len(hits) >= 5
        assert s["crashes"] == 2 and s["restarts"] == 2 and not s["dead"]
        assert t.health()["healthy"]

    def test_loop_clean_return_ends_thread(self):
        done = []

        def loop():
            done.append(1)

        t = SupervisedThread("chaos-loop", loop, mode="loop")
        t.start()
        t.join(5)
        assert not t.is_alive() and done == [1]
        assert t.stats()["crashes"] == 0

    def test_death_past_restart_cap_flips_health(self):
        dead_cb = []

        def always():
            raise ValueError("permanent")

        t = SupervisedThread(
            "chaos-dead", always, max_restarts=2,
            restart_backoff_s=0.001, on_dead=dead_cb.append,
        )
        t.start()
        t.join(5)
        s = t.stats()
        assert s["dead"] and s["crashes"] == 3 and s["restarts"] == 2
        assert dead_cb and dead_cb[0] is t
        h = t.health()
        assert not h["healthy"] and "permanent" in h["degraded"]
        assert "thread_dead" in _failure_kinds()


# ========================================================== streaming chaos
FILE_ROWS = (110, 90)
N_ROWS = sum(FILE_ROWS)
D_GLOBAL = 8
BLOCK_ROWS = 64  # 200 rows -> 4 blocks, final one ragged

from photon_ml_tpu.io.data_reader import (  # noqa: E402
    FeatureShardConfiguration,
    build_index_maps,
    write_training_examples,
)

STREAM_SHARDS = {
    "global": FeatureShardConfiguration(
        feature_bags=("features",), add_intercept=True
    ),
}


@pytest.fixture(scope="module")
def stream_dataset(tmp_path_factory):
    rng = np.random.default_rng(23)
    root = tmp_path_factory.mktemp("chaos_stream")
    X = rng.normal(size=(N_ROWS, D_GLOBAL)).astype(np.float32)
    w = rng.normal(size=D_GLOBAL).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-(X @ w))) > rng.random(N_ROWS)).astype(
        np.float32
    )
    users = rng.integers(0, 6, size=N_ROWS)
    paths, row = [], 0
    for fi, n in enumerate(FILE_ROWS):
        recs = [
            {
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0,
                "features": [
                    ("g", str(j), float(X[i, j])) for j in range(D_GLOBAL)
                ],
                "metadataMap": {"userId": f"u{users[i]:02d}"},
            }
            for i in range(row, row + n)
        ]
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    return {"paths": paths, "index_maps": build_index_maps(paths, STREAM_SHARDS)}


def _open_source(stream_dataset, cache_dir=None, decode_workers=None):
    from photon_ml_tpu.streaming import StreamingSource

    return StreamingSource.open(
        stream_dataset["paths"], STREAM_SHARDS,
        index_maps=stream_dataset["index_maps"],
        block_rows=BLOCK_ROWS, id_tags=("userId",),
        cache_dir=cache_dir, decode_workers=decode_workers,
    )


def _solve_streamed(source):
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.opt import GlmOptimizationConfiguration
    from photon_ml_tpu.opt.config import RegularizationContext
    from photon_ml_tpu.streaming import BlockPrefetcher, solve_streaming
    from photon_ml_tpu.types import RegularizationType

    cfg = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.5,
    )
    objective = make_glm_objective(LogisticLoss)
    dim = source.plan.shard_dims["global"]
    w0 = jnp.zeros((dim,), jnp.float32)

    def gen():
        for blk in BlockPrefetcher(source, shards=("global",), depth=2):
            yield blk.data["global"]

    return np.asarray(solve_streaming(objective, w0, gen, cfg).w)


class TestStreamingChaos:
    def test_transient_read_fault_is_bitwise_invisible(self, stream_dataset):
        """The acceptance gate: a streamed solve whose part-file reads hit
        (recovered) transient faults produces the bit-for-bit same model
        as a fault-free run."""
        reset_faults()
        ref = _solve_streamed(_open_source(stream_dataset))

        configure_faults("stream.read_part_file=once:2")
        before = _counter("resilience.retry.stream.read_part_file.recovered")
        got = _solve_streamed(_open_source(stream_dataset))
        assert fault_stats()["stream.read_part_file"]["trips"] == 1
        assert (
            _counter("resilience.retry.stream.read_part_file.recovered")
            == before + 1
        )
        assert np.array_equal(ref, got)

    def test_armed_but_never_firing_site_is_bitwise_invisible(
        self, stream_dataset
    ):
        """Disabled-path parity: arming machinery itself (spec parsing,
        per-call trigger checks) must not perturb output."""
        reset_faults()
        ref = _solve_streamed(_open_source(stream_dataset))
        configure_faults("stream.read_part_file=once:1000000000")
        got = _solve_streamed(_open_source(stream_dataset))
        assert fault_stats()["stream.read_part_file"]["trips"] == 0
        assert np.array_equal(ref, got)

    def test_cache_load_exhaustion_degrades_to_decode(
        self, stream_dataset, tmp_path
    ):
        """A block cache that cannot be read is a MISS, not a crash: the
        epoch falls back to decoding Avro and the data is identical."""
        cache_dir = str(tmp_path / "cache")
        reset_faults()
        warm = _open_source(stream_dataset, cache_dir=cache_dir)
        ref = _solve_streamed(warm)  # epoch 1 populates the cache

        configure_faults("stream.blockcache.load=every:1")
        got = _solve_streamed(
            _open_source(stream_dataset, cache_dir=cache_dir)
        )
        assert fault_stats()["stream.blockcache.load"]["trips"] >= 1
        assert np.array_equal(ref, got)

    def test_cache_store_failure_is_nonfatal(self, stream_dataset, tmp_path):
        """Spill failures lose the cache, never the epoch."""
        configure_faults("stream.blockcache.store=every:1")
        src = _open_source(
            stream_dataset, cache_dir=str(tmp_path / "cache2")
        )
        blocks = list(src.iter_blocks(shards=("global",)))
        assert len(blocks) == src.plan.num_blocks
        assert fault_stats()["stream.blockcache.store"]["trips"] >= 1
        assert "cache_store_failed" in _failure_kinds()

    def test_skip_mode_drops_block_and_records_it(self, stream_dataset):
        configure_faults("stream.build_block=once:2!fatal")
        src = _open_source(stream_dataset, decode_workers=0)
        src.on_block_error = "skip"
        blocks = list(src.iter_blocks(shards=("global",)))
        assert len(blocks) == src.plan.num_blocks - 1
        assert src.failed_blocks == {1}
        skipped = src.drain_skipped_blocks()
        assert len(skipped) == 1 and skipped[0]["block"] == 1
        assert src.drain_skipped_blocks() == []  # drained
        assert "block_skipped" in _failure_kinds()

    def test_abort_mode_raises_by_default(self, stream_dataset):
        configure_faults("stream.build_block=once:1!fatal")
        src = _open_source(stream_dataset, decode_workers=0)
        assert src.on_block_error == "abort"
        with pytest.raises(FatalInjectedFault):
            list(src.iter_blocks(shards=("global",)))

    def test_prefetch_worker_crash_falls_back_to_sync_decode(
        self, stream_dataset
    ):
        """A crash that escapes the prefetch worker (abort mode) degrades
        to synchronous decode for the remaining blocks instead of losing
        the epoch — and the once-fired fault doesn't fire again on the
        sync path, so every block still streams."""
        from photon_ml_tpu.streaming import BlockPrefetcher

        configure_faults("stream.build_block=once:1!fatal")
        src = _open_source(stream_dataset)
        blocks = list(BlockPrefetcher(src, shards=("global",), depth=2))
        assert len(blocks) == src.plan.num_blocks
        assert "prefetch_worker_failed" in _failure_kinds()


class TestGapSchedulerExclusion:
    def _sched(self, n=6):
        from photon_ml_tpu.streaming.gapsched import GapScheduler

        return GapScheduler(num_blocks=n, seed=4)

    def test_mark_failed_excludes_from_epochs(self):
        s = self._sched()
        s.mark_failed([2, 4])
        for _ in range(5):
            order = s.epoch_order()
            assert 2 not in order and 4 not in order
            s.update({int(b): 1.0 for b in order})

    def test_exclusion_survives_scoring(self):
        s = self._sched()
        order = s.epoch_order()
        s.update({int(b): float(b + 1) for b in order})
        s.mark_failed([0])
        assert 0 not in s.epoch_order()

    def test_all_excluded_raises(self):
        s = self._sched(3)
        s.mark_failed([0, 1, 2])
        with pytest.raises(RuntimeError, match="excluded"):
            s.epoch_order()

    def test_no_exclusions_is_bitwise_identical(self):
        a, b = self._sched(), self._sched()
        b.mark_failed([])  # the no-op path must not perturb anything
        for _ in range(3):
            oa, ob = a.epoch_order(), b.epoch_order()
            assert np.array_equal(oa, ob)
            a.update({int(x): 1.0 for x in oa})
            b.update({int(x): 1.0 for x in ob})


class TestClusterWorkerChaos:
    """Arming ``cluster.worker_block``: the injected fault kills a whole
    WORKER (coarse failure semantics — see cluster/worker.py), and the
    recovery is cluster-level: the coordinator reassigns the dead host's
    blocks to the survivor and the pass still sums every block."""

    def _plane(self, stream_dataset, hosts=2):
        from photon_ml_tpu.parallel.cluster import (
            ClusterCoordinator,
            ClusterWorker,
            serve_worker_in_thread,
        )
        from photon_ml_tpu.types import TaskType

        num_blocks = _open_source(stream_dataset).plan.num_blocks
        coord = ClusterCoordinator(
            hosts, num_blocks, heartbeat_timeout_s=60.0
        )
        for h in range(hosts):
            serve_worker_in_thread(
                ClusterWorker(
                    host_id=h,
                    source=_open_source(stream_dataset),
                    shard_id="global",
                    task=TaskType.LOGISTIC_REGRESSION,
                ),
                coord.address,
            )
        coord.wait_for_workers(timeout_s=60.0)
        return coord

    def test_armed_fault_kills_host_pass_completes_on_survivor(
        self, stream_dataset
    ):
        dim = _open_source(stream_dataset).plan.shard_dims["global"]
        w = np.zeros(dim, dtype=np.float32)

        healthy = self._plane(stream_dataset)
        try:
            f_ref, g_ref, _, stats_ref = healthy.distributed_pass(w)
        finally:
            healthy.shutdown()

        # the 3rd per-block fault_point call across the two thread-hosted
        # workers trips fatally: one host dies mid-pass, the other survives
        configure_faults("cluster.worker_block=once:3!fatal")
        lost_before = _counter("resilience.failures.cluster_host_lost")
        reassigned_before = _counter("cluster.blocks_reassigned")
        chaos = self._plane(stream_dataset)
        try:
            f_got, g_got, _, stats_got = chaos.distributed_pass(w)
            events = [e["event"] for e in chaos.drain_events()]
        finally:
            chaos.shutdown()

        assert fault_stats()["cluster.worker_block"]["trips"] == 1
        assert "cluster_host_lost" in _failure_kinds()
        assert _counter("resilience.failures.cluster_host_lost") == (
            lost_before + 1
        )
        assert _counter("cluster.blocks_reassigned") > reassigned_before
        assert "host_lost" in events and "blocks_reassigned" in events
        # every block still summed exactly once; only fp reassociation
        # (different host partition) separates the totals
        assert len(stats_got) == len(stats_ref)
        assert {s["block"] for s in stats_got} == {
            s["block"] for s in stats_ref
        }
        np.testing.assert_allclose(f_got, f_ref, rtol=1e-6)
        np.testing.assert_allclose(g_got, g_ref, rtol=1e-5, atol=1e-6)


class TestStreamingEstimatorChaos:
    def _estimator(self):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        return GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration(
                    "global",
                    GlmOptimizationConfiguration(
                        regularization=RegularizationContext(
                            RegularizationType.L2
                        ),
                        regularization_weight=0.5,
                    ),
                )
            },
            num_outer_iterations=1,
        )

    def test_streamed_fit_recovers_bitwise_identical(self, stream_dataset):
        reset_faults()
        ref = self._estimator().fit_streaming(
            _open_source(stream_dataset)
        )
        configure_faults("stream.read_part_file=once:3")
        got = self._estimator().fit_streaming(
            _open_source(stream_dataset)
        )
        assert fault_stats()["stream.read_part_file"]["trips"] == 1
        rw = np.asarray(ref.model.models["fixed"].coefficients.means)
        gw = np.asarray(got.model.models["fixed"].coefficients.means)
        assert np.array_equal(rw, gw)

    def test_skipped_block_lands_in_the_progress_ledger(
        self, stream_dataset, tmp_path
    ):
        from photon_ml_tpu.telemetry import ConvergenceTracker
        from photon_ml_tpu.telemetry.validate import validate_ledger

        ledger = str(tmp_path / "progress.jsonl")
        tracker = ConvergenceTracker(ledger_path=ledger, label="chaos")
        tracker.attach_failure_sink()
        configure_faults("stream.build_block=once:2!fatal")
        src = _open_source(stream_dataset, decode_workers=0)
        src.on_block_error = "skip"
        try:
            fit = self._estimator().fit_streaming(src, progress=tracker)
        finally:
            tracker.finish()
        assert fit is not None
        recs = validate_ledger(ledger)
        res = [
            r for r in recs
            if r["type"] == "progress" and r["kind"] == "resilience"
        ]
        assert res, "skip must emit a resilience progress record"
        assert any(r["failure_kind"] == "block_skipped" for r in res)
        # degraded, not unhealthy: resilience records never flip health
        assert tracker.health()["healthy"]
        assert tracker.health()["resilience_events"] >= 1


# ============================================================ serving chaos
from photon_ml_tpu.indexmap import DefaultIndexMap  # noqa: E402
from photon_ml_tpu.serving import (  # noqa: E402
    AdmissionController,
    GameScorer,
    HotSwapManager,
    ScoreRequest,
    ServingArtifact,
    ServingTable,
    ShardedGameScorer,
)
from photon_ml_tpu.types import TaskType  # noqa: E402

N_ENT, D_RE, D_FE = 48, 4, 8
SERVE_NNZ = {"global": 6, "per_user": D_RE}


def _serving_artifact(n_ent=N_ENT, seed=5):
    rng = np.random.default_rng(seed)
    return ServingArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        tables={
            "fixed": ServingTable(
                feature_shard="global", random_effect_type=None,
                weights=(rng.standard_normal(D_FE) * 0.1).astype(np.float32),
            ),
            "per_user": ServingTable(
                feature_shard="per_user", random_effect_type="userId",
                weights=(
                    rng.standard_normal((n_ent, D_RE)) * 0.3
                ).astype(np.float32),
                entity_index=DefaultIndexMap(
                    {f"u{i}": i for i in range(n_ent)}
                ),
            ),
        },
        model_name="chaos-test",
    )


def _score_request(i, uid="u1"):
    rng = np.random.default_rng(100 + i)
    return ScoreRequest(
        request_id=f"r{i}",
        features={
            "global": {
                int(c): float(v)
                for c, v in zip(
                    rng.integers(0, D_FE, 6), rng.standard_normal(6)
                )
            },
            "per_user": {
                j: float(v) for j, v in enumerate(rng.standard_normal(D_RE))
            },
        },
        entity_ids={"userId": uid},
    )


def _admission_pair(budget=24, admit=8):
    scorer = ShardedGameScorer(
        _serving_artifact(), max_nnz=SERVE_NNZ, num_shards=2,
        device_budget_rows=budget,
    )
    admission = AdmissionController([scorer], admit_batch=admit)
    scorer.attach_admission(admission)
    admission.warmup()
    return scorer, admission


class TestAdmissionSupervision:
    def test_step_killed_once_daemon_resumes(self):
        """The motivating regression: one exception in step() used to kill
        the admission daemon silently. Now the supervisor records the
        crash, restarts the tick, and the queue still drains."""
        scorer, admission = _admission_pair()
        configure_faults("serve.admission.step=once:1")
        admission.note_deferred("per_user", np.arange(30, 46))
        admission.start(interval_s=0.001)
        try:
            deadline = time.time() + 10
            while admission.queue_depth and time.time() < deadline:
                time.sleep(0.005)
            stats = admission.stats()
        finally:
            admission.stop()
        assert admission.queue_depth == 0
        assert stats["admitted_total"] == 16
        assert stats["thread_crashes"] >= 1
        assert stats["thread_restarts"] >= 1
        assert not stats["thread_dead"]
        assert "thread_crash" in _failure_kinds()

    def test_one_bad_coordinate_requeues_not_crashes(self, monkeypatch):
        scorer, admission = _admission_pair()
        orig = admission._admit
        calls = {"n": 0}

        def flaky(cid, rows):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("scatter exploded")
            return orig(cid, rows)

        monkeypatch.setattr(admission, "_admit", flaky)
        admission.note_deferred("per_user", np.arange(30, 38))
        admission.drain()
        assert admission.queue_depth == 0
        assert admission.admitted_total == 8
        assert admission.stats()["admit_failures"] == 1
        assert "admit_failed" in _failure_kinds()

    def test_stage_gather_fault_is_retried(self):
        scorer, admission = _admission_pair()
        configure_faults("serve.admission.stage=once:1")
        before = _counter("resilience.retry.serve.admission.stage.recovered")
        admission.note_deferred("per_user", np.arange(30, 34))
        admitted = admission.step()
        assert admitted == 4
        assert (
            _counter("resilience.retry.serve.admission.stage.recovered")
            == before + 1
        )

    def test_dead_daemon_degrades_healthz_serving_stays_up(self):
        """Kill admission permanently: the thread dies past its restart
        cap, /healthz flips to 503 with the degraded reason, and the
        scorer keeps answering (cold entities FE-only)."""
        from photon_ml_tpu.serving import IntrospectionServer

        scorer, admission = _admission_pair()
        configure_faults("serve.admission.step=every:1!fatal")
        admission.note_deferred("per_user", np.arange(30, 38))
        admission.start(interval_s=0.001, max_restarts=2)
        try:
            deadline = time.time() + 10
            while not admission.stats()["thread_dead"] and (
                time.time() < deadline
            ):
                time.sleep(0.005)
            stats = admission.stats()
            health = admission.health()
            assert stats["thread_dead"]
            assert not health["healthy"]
            assert "serving-admission" in health["degraded"]

            # serving still answers: a cold (deferred) entity scores FE-only
            results = scorer.score_batch(
                [_score_request(0, uid="u45"), _score_request(1, uid="u2")],
                bucket_size=2,
            )
            assert len(results) == 2
            assert all(np.isfinite(r.score) for r in results)

            # and the introspection endpoint reports 503 + the reason
            server = IntrospectionServer(health=admission.health, port=0)
            server.start()
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{server.port}/healthz"
                )
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=5)
                assert ei.value.code == 503
                doc = json.loads(ei.value.read().decode())
                assert not doc["healthy"]
                assert "serving-admission" in doc["degraded"]
            finally:
                server.stop()
        finally:
            admission.stop()


class TestContinuousBatcherSupervision:
    def _scorer(self):
        return GameScorer(
            _serving_artifact(), max_nnz=SERVE_NNZ, cache_capacity=16
        )

    def test_worker_crash_restarts_and_keeps_scoring(self):
        from photon_ml_tpu.serving import ContinuousBatcher

        batcher = ContinuousBatcher(
            self._scorer(), bucket_sizes=[1, 2, 4], max_wait_s=0.001
        )
        # crash the serve loop itself (not score_batch, which is already
        # contained): the first clock() call inside the loop explodes
        real_clock = batcher._clock
        state = {"armed": True}

        def bomb_clock():
            # only explode on the supervised worker thread — the clock is
            # also consulted on the submit path
            if state["armed"] and threading.current_thread().name.startswith(
                "serving-batcher"
            ):
                state["armed"] = False
                raise RuntimeError("loop exploded")
            return real_clock()

        batcher._clock = bomb_clock
        batcher.start(max_restarts=3)
        try:
            handles = [batcher.submit(_score_request(i)) for i in range(4)]
            scores = [h.result(timeout=60).score for h in handles]
            assert all(np.isfinite(s) for s in scores)
            stats = batcher.thread_stats()
            assert sum(s["crashes"] for s in stats) >= 1
            assert batcher.health()["healthy"]
        finally:
            batcher.stop()

    def test_all_workers_dead_flips_health(self):
        from photon_ml_tpu.serving import ContinuousBatcher

        batcher = ContinuousBatcher(self._scorer(), bucket_sizes=[1])

        def always(*a, **k):
            raise RuntimeError("permanently broken")

        batcher._serve_loop = always
        batcher.start(max_restarts=1)
        try:
            deadline = time.time() + 10
            while batcher.health()["healthy"] and time.time() < deadline:
                time.sleep(0.005)
            h = batcher.health()
            assert not h["healthy"]
            assert "serving-batcher-0" in h["degraded"]
        finally:
            batcher._running = False
            batcher._stop_event.set()
            batcher._threads = []


# ======================================================== delta watch chaos
def _fe_delta(artifact, generation, scale):
    from photon_ml_tpu.incremental.delta import DeltaArtifact

    w = np.asarray(artifact.tables["fixed"].weights, np.float32) * scale
    return DeltaArtifact(
        base_fingerprint=None, generation=generation,
        re_rows={}, fe_updates={"fixed": w},
    )


class TestDeltaResilience:
    def _manager(self):
        artifact = _serving_artifact()
        scorer = GameScorer(artifact, max_nnz=SERVE_NNZ)
        return artifact, scorer, HotSwapManager(scorer)

    def _corrupt_delta(self, watch_dir, name):
        d = os.path.join(watch_dir, name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "delta-manifest.json"), "w") as f:
            f.write('{"format_version": 1, "coordinates": {truncated')

    def test_corrupt_delta_keeps_generation_and_next_good_applies(
        self, tmp_path
    ):
        """Satellite: partial/corrupt delta artifact — serving keeps the
        old generation, records the failure, and still picks up the next
        good delta. The corrupt path stays unprocessed, so a re-publish
        at the same name is retried on a later poll."""
        from photon_ml_tpu.incremental.delta import delta_dir_name, save_delta

        artifact, scorer, mgr = self._manager()
        watch = str(tmp_path / "deltas")
        self._corrupt_delta(watch, delta_dir_name(1))
        save_delta(_fe_delta(artifact, 2, 2.0), os.path.join(
            watch, delta_dir_name(2)
        ))

        req = _score_request(0)
        before = scorer.score_batch([req], bucket_size=1)[0].score
        reports = mgr.poll_directory(watch)
        after = scorer.score_batch([req], bucket_size=1)[0].score

        assert len(reports) == 1 and not reports[0].rolled_back
        assert mgr.generation == 1  # the good delta applied...
        assert after != before      # ...and actually changed the scores
        assert mgr.delta_load_failures >= 1
        assert "delta_load_failed" in _failure_kinds()

        # re-publishing a good artifact at the failed name is picked up
        save_delta(_fe_delta(artifact, 1, 3.0), os.path.join(
            watch, delta_dir_name(1)
        ))
        reports = mgr.poll_directory(watch)
        assert len(reports) == 1
        assert mgr.generation == 2

    def test_injected_delta_load_fault_recovers(self, tmp_path):
        from photon_ml_tpu.incremental.delta import delta_dir_name, save_delta

        artifact, scorer, mgr = self._manager()
        watch = str(tmp_path / "deltas")
        save_delta(_fe_delta(artifact, 1, 2.0), os.path.join(
            watch, delta_dir_name(1)
        ))
        configure_faults("serve.delta.load=once:1")
        reports = mgr.poll_directory(watch)
        assert len(reports) == 1 and mgr.generation == 1
        assert mgr.delta_load_failures == 0  # retried, recovered
        assert _counter("resilience.retry.serve.delta.load.recovered") >= 1

    def test_watcher_thread_survives_poll_crashes(self, tmp_path):
        from photon_ml_tpu.serving import DeltaWatcher

        calls = {"n": 0}

        class FlakyMgr:
            def poll_directory(self, d):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("poll exploded")
                return []

        w = DeltaWatcher(FlakyMgr(), str(tmp_path), interval_s=0.001)
        w.start()
        try:
            deadline = time.time() + 10
            while calls["n"] < 4 and time.time() < deadline:
                time.sleep(0.005)
        finally:
            w.stop()
        assert calls["n"] >= 4
        assert w.stats()["polls"] >= 3
        assert w.health()["healthy"]

    def test_watcher_applies_deltas_in_background(self, tmp_path):
        from photon_ml_tpu.incremental.delta import delta_dir_name, save_delta
        from photon_ml_tpu.serving import DeltaWatcher

        artifact, scorer, mgr = self._manager()
        watch = str(tmp_path / "deltas")
        os.makedirs(watch)
        w = DeltaWatcher(mgr, watch, interval_s=0.001)
        w.start()
        try:
            save_delta(_fe_delta(artifact, 1, 2.0), os.path.join(
                watch, delta_dir_name(1)
            ))
            deadline = time.time() + 10
            while mgr.generation == 0 and time.time() < deadline:
                time.sleep(0.005)
        finally:
            w.stop()
        assert mgr.generation == 1
        assert w.swaps >= 1
        assert len(w.drain_reports()) == 1


# ========================================================= checkpoint chaos
def _glm(value, dim=4):
    import jax.numpy as jnp

    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import GeneralizedLinearModel

    return GeneralizedLinearModel(
        coefficients=Coefficients(means=jnp.full((dim,), float(value))),
        task=TaskType.LINEAR_REGRESSION,
    )


class TestCheckpointResilience:
    def test_publish_fault_keeps_previous_checkpoint(self, tmp_path):
        from photon_ml_tpu.checkpoint import (
            load_training_checkpoint,
            save_training_checkpoint,
        )

        ckpt = str(tmp_path / "ckpt")
        save_training_checkpoint(ckpt, {"fixed": _glm(1.0)}, {"outer": 1})
        configure_faults("train.checkpoint.publish=once:1")
        with pytest.raises(InjectedFault):
            save_training_checkpoint(ckpt, {"fixed": _glm(2.0)}, {"outer": 2})
        # the failed save cleaned its tmp dir and left generation 1 intact
        assert not glob.glob(str(tmp_path / ".ckpt-*"))
        models, state, _ = load_training_checkpoint(ckpt)
        assert state["outer"] == 1
        assert float(np.asarray(models["fixed"].coefficients.means)[0]) == 1.0
        # and the NEXT save succeeds (once:1 fired already)
        save_training_checkpoint(ckpt, {"fixed": _glm(2.0)}, {"outer": 2})
        _, state, _ = load_training_checkpoint(ckpt)
        assert state["outer"] == 2

    def test_resume_sweeps_orphaned_tmp_and_old_dirs(self, tmp_path):
        from photon_ml_tpu.checkpoint import (
            load_training_checkpoint,
            save_training_checkpoint,
        )

        ckpt = str(tmp_path / "ckpt")
        save_training_checkpoint(ckpt, {"fixed": _glm(1.0)}, {"outer": 1})
        # replicate what a kill between tmp write and rename leaves behind
        for orphan in (".ckpt-tmp-dead1", ".ckpt-old-dead2"):
            d = tmp_path / orphan
            d.mkdir()
            (d / "junk.bin").write_bytes(b"x" * 128)
        _, state, _ = load_training_checkpoint(ckpt)
        assert state["outer"] == 1
        assert not glob.glob(str(tmp_path / ".ckpt-*"))
        assert os.path.isdir(ckpt)  # the live checkpoint is never swept

    @pytest.mark.slow
    def test_sigkill_between_tmp_write_and_rename(self, tmp_path):
        """A real SIGKILL after the tmp dir is fully written but before
        the publish rename: the previous checkpoint must resume cleanly
        and the orphaned tmp dir is swept on that resume."""
        from photon_ml_tpu.checkpoint import load_training_checkpoint

        ckpt = str(tmp_path / "ckpt")
        script = r"""
import os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax.numpy as jnp
from photon_ml_tpu.checkpoint import save_training_checkpoint
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType

def glm(v):
    return GeneralizedLinearModel(
        coefficients=Coefficients(means=jnp.full((4,), float(v))),
        task=TaskType.LINEAR_REGRESSION,
    )

d = sys.argv[1]
save_training_checkpoint(d, {"fixed": glm(1.0)}, {"outer": 1})
# second save: die at the first rename — tmp is written and fsynced,
# nothing has been published
os.replace = lambda s, t: os.kill(os.getpid(), signal.SIGKILL)
save_training_checkpoint(d, {"fixed": glm(2.0)}, {"outer": 2})
"""
        proc = subprocess.run(
            [sys.executable, "-c", script, ckpt],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == -signal.SIGKILL, proc.stderr
        orphans = glob.glob(str(tmp_path / ".ckpt-tmp-*"))
        assert orphans, "the kill must strand the tmp dir"
        models, state, _ = load_training_checkpoint(ckpt)
        assert state["outer"] == 1
        assert float(np.asarray(models["fixed"].coefficients.means)[0]) == 1.0
        assert not glob.glob(str(tmp_path / ".ckpt-*"))
