"""Event emission from the drivers, profiler hook, and warm start across
estimator fits / tuning trials (reference event/EventEmitter wiring in
Driver.scala:120-186 and warmStartModels, Driver.scala:484-501)."""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.event import (
    PhotonOptimizationLogEvent,
    PhotonSetupEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from tests._listeners import CollectingListener


@pytest.fixture
def collecting():
    CollectingListener.received = []
    CollectingListener.closed = 0
    return CollectingListener


def _glm_fixture(tmp_path, rng):
    from photon_ml_tpu.io.data_reader import write_training_examples

    recs = [
        {"label": float(i % 2),
         "features": [("f", str(j), float(rng.normal())) for j in range(5)]}
        for i in range(120)
    ]
    p = tmp_path / "train"
    p.mkdir()
    write_training_examples(str(p / "part-00000.avro"), recs)
    return p


class TestDriverEvents:
    def test_train_glm_emits_lifecycle(self, tmp_path, rng, collecting):
        from photon_ml_tpu.cli.train_glm import parse_args, run

        train = _glm_fixture(tmp_path, rng)
        run(parse_args([
            "--training-data-dirs", str(train),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out"),
            "--regularization-weights", "0.1", "1",
            "--event-listeners",
            "tests._listeners.CollectingListener",
        ]))
        kinds = [type(e) for e in collecting.received]
        assert kinds[0] is PhotonSetupEvent
        assert TrainingStartEvent in kinds
        assert kinds[-1] is TrainingFinishEvent
        opt_events = [e for e in collecting.received
                      if isinstance(e, PhotonOptimizationLogEvent)]
        assert {e.regularization_weight for e in opt_events} == {0.1, 1.0}
        assert all(e.iterations > 0 for e in opt_events)
        assert all(e.convergence_reason for e in opt_events)
        assert collecting.closed == 1

    def test_train_game_emits_and_profiles(self, tmp_path, rng, collecting):
        from photon_ml_tpu.cli.train_game import parse_args, run

        train = _glm_fixture(tmp_path, rng)
        cfg = tmp_path / "g.json"
        cfg.write_text(json.dumps({
            "feature_shards": {"g": {"feature_bags": ["features"]}},
            "coordinates": {"fixed": {"type": "fixed", "feature_shard": "g"}},
        }))
        prof = tmp_path / "prof"
        run(parse_args([
            "--train-data-dirs", str(train),
            "--coordinate-config", str(cfg),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out"),
            "--event-listeners",
            "tests._listeners.CollectingListener",
            "--profile-dir", str(prof),
        ]))
        kinds = [type(e) for e in collecting.received]
        assert kinds[0] is PhotonSetupEvent and kinds[-1] is TrainingFinishEvent
        opt = [e for e in collecting.received
               if isinstance(e, PhotonOptimizationLogEvent)]
        assert opt and opt[0].coordinate_id == "fixed"
        # profiler wrote a trace
        assert prof.is_dir() and any(prof.rglob("*"))


class TestWarmStart:
    def _data(self, rng):
        from photon_ml_tpu.testing import generate_fixed_effect_data
        from photon_ml_tpu.types import TaskType

        data, _ = generate_fixed_effect_data(
            TaskType.LINEAR_REGRESSION, n=200, d=8, seed=11
        )
        vdata, _ = generate_fixed_effect_data(
            TaskType.LINEAR_REGRESSION, n=80, d=8, seed=12
        )
        return data, vdata

    def test_fit_initial_models_warm_start(self, rng):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.types import TaskType

        data, vdata = self._data(rng)
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("global")},
        )
        first = est.fit(data, validation_data=vdata)
        warm = est.fit(
            data, validation_data=vdata,
            initial_models=dict(first.model.models),
        )
        np.testing.assert_allclose(
            warm.model.score(vdata), first.model.score(vdata),
            rtol=1e-3, atol=1e-3,
        )

    def test_tuning_trials_warm_start(self, rng):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.estimators.tuning import run_hyperparameter_tuning
        from photon_ml_tpu.types import TaskType

        data, vdata = self._data(rng)
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("global")},
        )
        base = est.fit(data, validation_data=vdata)
        trials = run_hyperparameter_tuning(
            est, data, vdata, mode="RANDOM", num_iterations=3,
            log10_range=(-2.0, 1.0), prior_fits=[base], seed=1,
        )
        assert len(trials) == 3
        # warm-started trials still produce sane models
        assert all(np.isfinite(t.value) for t in trials)

    def test_incompatible_warm_start_rejected(self, rng):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.testing import generate_fixed_effect_data
        from photon_ml_tpu.types import TaskType

        data, vdata = self._data(rng)
        other, _ = generate_fixed_effect_data(
            TaskType.LINEAR_REGRESSION, n=100, d=3, seed=13
        )
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("global")},
        )
        donor = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("global")},
        ).fit(other)
        with pytest.raises(ValueError, match="incompatible"):
            est.fit(data, initial_models=dict(donor.model.models))
