"""Property-based invariants (hypothesis) for the sparse routing engines,
the layout planner, and the Avro varint codec — arbitrary small inputs
rather than fixed seeds, complementing the randomized cases in
test_benes.py/test_fused_perm.py."""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

pytestmark = pytest.mark.slow  # many small engine builds per test


coo_shapes = st.tuples(
    st.integers(min_value=1, max_value=96),   # rows
    st.integers(min_value=1, max_value=64),   # cols
    st.integers(min_value=0, max_value=400),  # nnz draws (pre-coalesce)
)


def _coo(draw_shape, seed):
    n, d, m = draw_shape
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, n, m)
    cols = rng.integers(0, d, m)
    vals = rng.standard_normal(m).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (rows, cols), vals)
    return rows, cols, vals, dense


class TestEngineProperties:
    @settings(max_examples=20, deadline=None)
    @given(
        engine=st.sampled_from(["benes", "fused"]),
        shape=coo_shapes,
        seed=st.integers(0, 2**31),
    )
    def test_routed_maps_match_dense(self, engine, shape, seed):
        """For ANY coo pattern (duplicates, empty rows/cols, hot columns,
        degenerate shapes), both routed engines' matvec/rmatvec equal the
        dense reference (the fused builder exercises its CPU fallback +
        pow2 slot groups + auto layout)."""
        from photon_ml_tpu.ops import fused_perm, sparse_perm

        builder = (
            sparse_perm.from_coo if engine == "benes" else fused_perm.from_coo
        )
        rows, cols, vals, dense = _coo(shape, seed)
        n, d = dense.shape
        feats = builder(rows, cols, vals, (n, d), plan_cache="")
        rng = np.random.default_rng(seed + 1)
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(feats.matvec(w)), dense @ w, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(feats.rmatvec(c)), dense.T @ c, atol=2e-4
        )


class TestPlannerProperties:
    @settings(max_examples=60, deadline=None)
    @given(
        n=st.integers(1, 1 << 16),
        d=st.integers(1, 1 << 18),
        k=st.integers(1, 64),
        seed=st.integers(0, 2**31),
        lam=st.floats(0.05, 8.0),
    )
    def test_plan_always_legal_and_never_worse_than_flat(
        self, n, d, k, seed, lam
    ):
        """For any column-degree profile: the cap is a power of two below
        kp_full (or None), the block count is a power of two within the
        search bound, spill respects the nnz/8 bound, and the planned
        slots+spill cost never exceeds the flat layout's."""
        from photon_ml_tpu.ops import routing
        from photon_ml_tpu.ops.sparse_perm import (
            _spill_slot_cost,
            plan_column_layout,
        )

        rng = np.random.default_rng(seed)
        cc = rng.poisson(lam, d).astype(np.int64)
        nnz = int(cc.sum())
        if not nnz:
            return
        kp_full = int(cc.max())
        cap, t = plan_column_layout(cc, n, d, k, kp_full)
        assert t >= 1 and (t & (t - 1)) == 0 and t <= 16
        if cap is not None:
            assert cap < kp_full and (cap & (cap - 1)) == 0
            spill = int(np.maximum(cc - cap, 0).sum())
            assert spill <= max(nnz // 8, 4096)
        eff = cap if cap is not None else kp_full
        spill = int(np.maximum(cc - eff, 0).sum())
        total = t * routing.valid_size(max(n * k, -(-d // t) * eff, 1)) \
            + spill * _spill_slot_cost()
        flat = routing.valid_size(max(n * k, d * kp_full, 1))
        assert total <= flat or (cap is None and t == 1)


class TestValidSizeProperties:
    @settings(max_examples=200, deadline=None)
    @given(n=st.integers(1, 1 << 34))
    def test_valid_size_on_ladder_and_minimal(self, n):
        from photon_ml_tpu.ops.routing import valid_size

        s = valid_size(n)
        assert s >= n
        # on the ladder: s = c * 128^(m+1), c in {1,2,4,8}
        m = s
        while m % 128 == 0:
            m //= 128
        assert m in (1, 2, 4, 8), s
        # minimal: the next-smaller ladder value is below n (128 is the
        # ladder floor — nothing below it to compare)
        if s > 128:
            smaller = s // 2 if m in (2, 4, 8) else s * 8 // 128
            assert smaller < n, (n, s, smaller)


class TestAvroVarintProperties:
    @settings(max_examples=300, deadline=None)
    @given(v=st.integers(-(2**63), 2**63 - 1))
    def test_long_zigzag_roundtrip(self, v):
        """The in-tree codec's zigzag varint encode/decode are inverse
        over the full int64 range (io/avro.py _write_long / read_long)."""
        import io

        from photon_ml_tpu.io.avro import _Reader, _write_long

        out = io.BytesIO()
        _write_long(out, v)
        r = _Reader(out.getvalue())
        assert r.read_long() == v
        assert r.pos == len(out.getvalue())
