"""ELL builder edge cases flagged in review: duplicates, empty input, overflow."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.features import from_scipy_like


def test_empty_matrix_plain_lists():
    ell = from_scipy_like([], [], [], (4, 3))
    assert ell.values.shape == (4, 1)
    np.testing.assert_allclose(ell.matvec(jnp.ones(3)), np.zeros(4))


def test_duplicate_entries_coalesced():
    # two entries at (0, 2): 1.5 + 2.5 = 4.0; rmatvec_sq must see 4^2 not 1.5^2+2.5^2
    ell = from_scipy_like([0, 0, 1], [2, 2, 0], [1.5, 2.5, 3.0], (2, 3))
    w = jnp.array([1.0, 1.0, 1.0])
    np.testing.assert_allclose(ell.matvec(w), [4.0, 3.0])
    c = jnp.array([1.0, 0.0])
    np.testing.assert_allclose(ell.rmatvec_sq(c), [0.0, 0.0, 16.0])


def test_max_nnz_overflow_raises():
    with pytest.raises(ValueError, match="exceeds max_nnz"):
        from_scipy_like([0, 0, 0], [0, 1, 2], [1.0, 1.0, 1.0], (1, 3), max_nnz=2)


def test_max_nnz_padding():
    ell = from_scipy_like([0], [1], [2.0], (2, 3), max_nnz=4)
    assert ell.values.shape == (2, 4)
    np.testing.assert_allclose(ell.to_dense().matrix, [[0.0, 2.0, 0.0], [0.0, 0.0, 0.0]])


def test_out_of_range_indices_raise():
    with pytest.raises(ValueError, match="column index out of range"):
        from_scipy_like([0], [5], [1.0], (1, 3))
    with pytest.raises(ValueError, match="row index out of range"):
        from_scipy_like([4], [0], [1.0], (2, 3))
