"""Projector tests: index-map exactness, random-projection determinism and
distance preservation, identity passthrough, and RE-dataset integration.

Mirrors reference IndexMapProjectorTest / ProjectionMatrixTest and
RandomEffectCoordinateInProjectedSpace behavior.
"""

import numpy as np
import pytest

from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.estimators.random_effect import (
    score_random_effects,
    train_random_effects,
)
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.projector import (
    IdentityProjector,
    IndexMapProjector,
    ProjectorType,
    RandomProjectionMatrix,
)
from photon_ml_tpu.types import TaskType


class TestIndexMapProjector:
    def test_roundtrip_exact(self):
        proj = IndexMapProjector.from_observed(np.array([7, 2, 9, 2]), global_dim=20)
        assert proj.projected_dim == 3
        local, mask = proj.project_cols(np.array([2, 7, 9]))
        assert mask.all()
        assert sorted(local.tolist()) == [0, 1, 2]
        cols, vals = proj.project_coefficients_back(np.array([0.5, -1.0, 2.0]))
        assert cols.tolist() == [2, 7, 9]
        assert vals.tolist() == [0.5, -1.0, 2.0]

    def test_unobserved_columns_masked(self):
        proj = IndexMapProjector.from_observed(np.array([1, 5]), global_dim=10)
        _, mask = proj.project_cols(np.array([1, 3, 5, 9]))
        assert mask.tolist() == [True, False, True, False]

    def test_empty(self):
        proj = IndexMapProjector.from_observed(np.array([]), global_dim=10)
        _, mask = proj.project_cols(np.array([0, 1]))
        assert not mask.any()


class TestRandomProjectionMatrix:
    def test_rows_deterministic_per_column(self):
        p = RandomProjectionMatrix(projected_dim=8, global_dim=1000, seed=3)
        a = p.rows(np.array([5, 100, 999]))
        b = p.rows(np.array([100]))
        np.testing.assert_array_equal(a[1], b[0])  # same col -> same row
        assert not np.allclose(a[0], a[2])  # distinct cols differ

    def test_projection_approximately_preserves_norms(self):
        # Johnson-Lindenstrauss sanity: E||B^T x||^2 = ||x||^2
        d, k, n = 200, 64, 50
        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, d)).astype(np.float32)
        p = RandomProjectionMatrix(projected_dim=k, global_dim=d, seed=0)
        b = p.rows(np.arange(d))
        z = x @ b
        ratio = np.sum(z * z, axis=1) / np.sum(x * x, axis=1)
        assert abs(float(ratio.mean()) - 1.0) < 0.15

    def test_project_coo_matches_dense(self):
        d, k = 30, 6
        p = RandomProjectionMatrix(projected_dim=k, global_dim=d, seed=1)
        rng = np.random.default_rng(2)
        dense = (rng.random((4, d)) * (rng.random((4, d)) < 0.3)).astype(np.float32)
        rows, cols = np.nonzero(dense)
        out = p.project_coo(rows, cols, dense[rows, cols], num_samples=4)
        expected = dense @ p.rows(np.arange(d))
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    def test_back_projection_shape(self):
        p = RandomProjectionMatrix(projected_dim=4, global_dim=12, seed=0)
        cols, vals = p.project_coefficients_back(np.ones(4, np.float32))
        assert cols.shape == (12,) and vals.shape == (12,)

    def test_config_requires_k(self):
        with pytest.raises(ValueError, match="projected_dim"):
            RandomEffectDataConfiguration(
                random_effect_type="u", projector=ProjectorType.RANDOM
            )


class TestIdentityProjector:
    def test_passthrough(self):
        proj = IdentityProjector(global_dim=5)
        local, mask = proj.project_cols(np.array([0, 4]))
        assert local.tolist() == [0, 4] and mask.all()
        cols, vals = proj.project_coefficients_back(np.arange(5, dtype=np.float32))
        assert cols.tolist() == list(range(5))


def _synthetic(n=600, d=24, entities=12, seed=0):
    rng = np.random.default_rng(seed)
    X = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.4)).astype(np.float32)
    ids = np.array([f"e{i % entities}" for i in range(n)])
    w_e = rng.normal(size=(entities, d)).astype(np.float32)
    z = np.einsum("nd,nd->n", X, w_e[np.arange(n) % entities])
    y = (z > 0).astype(np.float32)
    rows, cols = np.nonzero(X)
    return ids, rows, cols, X[rows, cols], y, d, n


class TestDatasetProjectorIntegration:
    @pytest.mark.parametrize(
        "ptype,k",
        [(ProjectorType.IDENTITY, None), (ProjectorType.RANDOM, 16)],
    )
    def test_train_and_score(self, ptype, k):
        ids, rows, cols, vals, y, d, n = _synthetic()
        ds = build_random_effect_dataset(
            entity_ids=ids,
            feature_rows=rows,
            feature_cols=cols,
            feature_vals=vals,
            global_dim=d,
            labels=y,
            config=RandomEffectDataConfiguration(
                random_effect_type="e", projector=ptype, projected_dim=k
            ),
        )
        D = ds.buckets[0].local_dim
        assert D == (d if k is None else k)
        model, _ = train_random_effects(
            ds,
            TaskType.LOGISTIC_REGRESSION,
            GlmOptimizationConfiguration(regularization_weight=0.5),
        )
        scores = score_random_effects(model, ds)
        acc = float(np.mean((scores > 0) == (y > 0.5)))
        assert acc > 0.8, f"{ptype}: accuracy {acc}"
        # export goes through back-projection
        coeffs = model.coefficients_for("e0")
        assert coeffs and len(coeffs) <= d

    def test_random_projection_scores_match_manual(self):
        # scoring a model in projected space == B^T x . w_proj
        ids, rows, cols, vals, y, d, n = _synthetic(n=60, entities=3)
        cfg = RandomEffectDataConfiguration(
            random_effect_type="e",
            projector=ProjectorType.RANDOM,
            projected_dim=8,
            seed=5,
        )
        ds = build_random_effect_dataset(
            entity_ids=ids, feature_rows=rows, feature_cols=cols,
            feature_vals=vals, global_dim=d, labels=y, config=cfg,
        )
        p = RandomProjectionMatrix(projected_dim=8, global_dim=d, seed=5)
        dense = np.zeros((n, d), np.float32)
        dense[rows, cols] = vals
        expected_proj = dense @ p.rows(np.arange(d))
        bucket = ds.buckets[0]
        pos = np.asarray(bucket.sample_pos)
        wts = np.asarray(bucket.weights)
        got = np.asarray(bucket.X)[wts > 0]
        np.testing.assert_allclose(
            got, expected_proj[pos[wts > 0]], rtol=1e-4, atol=1e-5
        )
