"""Test harness: force an 8-virtual-device CPU platform BEFORE jax initializes.

This is the TPU-world analog of the reference's SparkTestUtils.sparkTest
(`local[4]` in-process Spark, SparkTestUtils.scala:61-77): multi-device
semantics are simulated in one process so sharding/collective code paths are
exercised without real hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Tests must exercise the real routing/compile paths, never a persistent
# per-uid cache left by an earlier run (a stale-but-correct cached plan
# would mask routing regressions).
os.environ["PHOTON_ML_TPU_PLAN_CACHE"] = ""
os.environ["PHOTON_ML_TPU_COMPILE_CACHE"] = ""
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The TPU plugin in this environment overrides JAX_PLATFORMS at import time;
# the config update below wins (must happen before any device use).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(seed=42)
