"""Nearline incremental training + zero-downtime hot-swap tests.

The load-bearing guarantees, per ISSUE acceptance criteria:

- an incremental update over the full event set with one fixed-effect
  refresh reproduces one full warm-started CD outer pass (the warm-start
  path is the SAME solve, just restricted to touched entities);
- delta artifacts round-trip (atomic dir write, content fingerprint),
  chain by base fingerprint, and ``compact`` folds a chain into a full
  artifact identical to applying the deltas in memory;
- a hot swap mutates the live scorer's tables with ZERO additional XLA
  compilations (params are jit arguments), updates scores for touched
  entities only, invalidates exactly the touched hot-cache rows, and a
  failed validation gate rolls back to the previous generation;
- ``save_artifact`` is atomic under crash injection (the old artifact
  survives; no tmp litter);
- end-to-end nearline loop: train -> serve -> new events -> update ->
  publish -> watch -> swap, through the same ``replay_requests`` plumbing
  the ``serve_game --watch-deltas`` CLI uses.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.data import RandomEffectDataConfiguration
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.incremental import (
    DeltaArtifact,
    OverlayIndexMap,
    apply_delta,
    build_delta,
    compact,
    delta_dir_name,
    discover_deltas,
    fingerprint_dir,
    incremental_update,
    load_delta,
    rebase_delta,
    save_delta,
    verify_chain,
)
from photon_ml_tpu.opt import GlmOptimizationConfiguration, RegularizationContext
from photon_ml_tpu.serving import (
    GameScorer,
    HotSwapManager,
    ValidationGate,
    load_artifact,
    pack_game_model,
    replay_requests,
    save_artifact,
)
from photon_ml_tpu.serving.replay import max_nnz_of, requests_from_game_data
from photon_ml_tpu.types import RegularizationType, TaskType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_USERS, ROWS, DG, DU = 8, 20, 6, 3
TOUCHED = [f"u{i}" for i in range(4)]          # re-solved by the update
UNTOUCHED = [f"u{i}" for i in range(4, N_USERS)]
NEW = ["v0", "v1"]                             # first seen in the events

L2 = lambda lam: GlmOptimizationConfiguration(  # noqa: E731
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=lam,
)


def _estimator(num_outer=1):
    return GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("g", L2(0.1)),
            "per_user": RandomEffectCoordinateConfiguration(
                "u", RandomEffectDataConfiguration(random_effect_type="userId"),
                L2(1.0),
            ),
        },
        num_outer_iterations=num_outer,
    )


def _coo(X):
    r, c = np.nonzero(X)
    return FeatureShard(rows=r, cols=c, vals=X[r, c], dim=X.shape[1])


def _dataset(rng, users, rows, wg, wu):
    n = len(users) * rows
    Xg = rng.normal(size=(n, DG)).astype(np.float32)
    Xu = rng.normal(size=(n, DU)).astype(np.float32)
    ids = np.repeat(users, rows)
    y = Xg @ wg + np.array([Xu[i] @ wu[ids[i]] for i in range(n)], np.float32)
    y += 0.05 * rng.normal(size=n).astype(np.float32)
    return GameData(
        labels=y,
        feature_shards={"g": _coo(Xg), "u": _coo(Xu)},
        id_tags={"userId": ids},
    )


@pytest.fixture(scope="module")
def nearline(tmp_path_factory):
    """One trained base model + one events batch + one published delta,
    shared read-only by the module (fit once, not per test)."""
    rng = np.random.default_rng(7)
    wg = rng.normal(size=DG).astype(np.float32)
    all_users = [f"u{i}" for i in range(N_USERS)] + NEW
    wu = {u: rng.normal(size=DU).astype(np.float32) for u in all_users}

    base_data = _dataset(rng, [f"u{i}" for i in range(N_USERS)], ROWS, wg, wu)
    events = _dataset(rng, TOUCHED + NEW, ROWS // 2, wg, wu)

    fit = _estimator(num_outer=2).fit(base_data)
    artifact = pack_game_model(fit.model, model_name="nearline-test")

    root = tmp_path_factory.mktemp("nearline")
    artifact_dir = str(root / "artifact")
    save_artifact(artifact, artifact_dir)

    update = incremental_update(
        _estimator(), fit.model, events, refresh_fixed_iterations=0,
        merge=False,
    )
    deltas_dir = str(root / "deltas")
    delta = build_delta(
        update.re_updates, artifact,
        base_fingerprint=fingerprint_dir(artifact_dir),
        generation=1, created_at_unix=100.0,
    )
    delta = save_delta(delta, os.path.join(deltas_dir, delta_dir_name(1)))
    return {
        "base_data": base_data,
        "events": events,
        "fit": fit,
        "artifact": artifact,
        "artifact_dir": artifact_dir,
        "update": update,
        "delta": delta,
        "deltas_dir": deltas_dir,
        "delta_dir": os.path.join(deltas_dir, delta_dir_name(1)),
    }


class TestIncrementalTrainer:
    def test_incremental_equals_full_pass(self, nearline):
        """Acceptance: an update whose events are the FULL dataset, with
        one FE refresh, reproduces one full warm-started CD outer pass."""
        base, data = nearline["fit"], nearline["base_data"]
        full = _estimator(num_outer=1).fit(
            data, initial_models=dict(base.model.models)
        )
        inc = incremental_update(
            _estimator(), base.model, data, refresh_fixed_iterations=1,
        )
        np.testing.assert_allclose(
            np.asarray(inc.fe_updates["fixed"]),
            np.asarray(full.model.models["fixed"].coefficients.means),
            atol=2e-4,
        )
        got_re = inc.models["per_user"]
        want_re = full.model.models["per_user"]
        assert set(got_re.entity_to_loc) == set(want_re.entity_to_loc)
        for eid in want_re.entity_to_loc:
            got = dict(got_re.coefficients_for(eid))
            want = dict(want_re.coefficients_for(eid))
            for k in set(got) | set(want):
                assert got.get(k, 0.0) == pytest.approx(
                    want.get(k, 0.0), abs=2e-4
                ), (eid, k)

    def test_touched_and_new_entities(self, nearline):
        upd = nearline["update"]
        assert set(upd.touched_entities["per_user"]) == set(TOUCHED + NEW)
        assert set(upd.new_entities["per_user"]) == set(NEW)
        assert upd.num_events == nearline["events"].num_rows
        # merge=False keeps ONLY the touched entities in the RE sub-model
        assert set(upd.models["per_user"].entity_to_loc) == set(TOUCHED + NEW)

    def test_merge_folds_old_rows(self, nearline):
        upd = incremental_update(
            _estimator(), nearline["fit"].model, nearline["events"],
        )
        merged = upd.models["per_user"]
        assert set(merged.entity_to_loc) == {
            f"u{i}" for i in range(N_USERS)
        } | set(NEW)
        # untouched entities keep their exact old coefficients
        old = nearline["fit"].model.models["per_user"]
        for eid in UNTOUCHED:
            assert dict(merged.coefficients_for(eid)) == pytest.approx(
                dict(old.coefficients_for(eid))
            )


class TestDeltaArtifact:
    def test_round_trip_and_fingerprint(self, nearline):
        delta, ddir = nearline["delta"], nearline["delta_dir"]
        loaded = load_delta(ddir)
        assert loaded.fingerprint == delta.fingerprint
        assert loaded.base_fingerprint == delta.base_fingerprint
        assert loaded.generation == 1
        assert loaded.num_rows_updated == delta.num_rows_updated > 0
        ids0, rows0 = delta.re_rows["per_user"]
        ids1, rows1 = loaded.re_rows["per_user"]
        assert ids1 == list(ids0)
        np.testing.assert_allclose(rows1, rows0, atol=0)
        # the fingerprint is the dir content hash — stable across loads
        assert fingerprint_dir(ddir) == delta.fingerprint

    def test_apply_matches_compact(self, nearline, tmp_path):
        folded = apply_delta(nearline["artifact"], nearline["delta"])
        out = str(tmp_path / "compacted")
        fp = compact(nearline["artifact_dir"], [nearline["delta_dir"]], out)
        reloaded = load_artifact(out)
        assert fp == fingerprint_dir(out)
        for cid, table in folded.tables.items():
            np.testing.assert_allclose(
                np.asarray(reloaded.tables[cid].weights),
                np.asarray(table.weights), atol=1e-7,
            )
            if table.entity_index is not None:
                for eid in TOUCHED + NEW:
                    assert reloaded.tables[cid].entity_index.get_index(
                        eid
                    ) == table.entity_index.get_index(eid)

    def test_broken_chain_raises(self, nearline):
        bogus = DeltaArtifact(
            base_fingerprint="0" * 16, generation=2,
            re_rows=dict(nearline["delta"].re_rows), fe_updates={},
            created_at_unix=0.0, fingerprint="f" * 16,
        )
        with pytest.raises(ValueError, match="chain broken"):
            verify_chain(
                fingerprint_dir(nearline["artifact_dir"]),
                [nearline["delta"], bogus],
            )

    def test_overlay_index_map(self, nearline):
        base = nearline["artifact"].tables["per_user"].entity_index
        n = len(base)
        overlay = OverlayIndexMap(base, {"v0": n, "v1": n + 1})
        assert len(overlay) == n + 2
        assert overlay.get_index("v0") == n
        assert overlay.get_feature_name(n + 1) == "v1"
        assert overlay.get_index("u0") == base.get_index("u0")

    def test_independent_chains_share_one_base(self, nearline, tmp_path):
        """The multi-variant shape: TWO independent delta chains rooted at
        the SAME base fingerprint (one per served variant). Each chain
        verifies and compacts on its own; splicing a link from one chain
        into the other is refused."""
        base_fp = fingerprint_dir(nearline["artifact_dir"])
        art = nearline["artifact"]
        upd = nearline["update"].re_updates

        def _scaled(s):
            return {
                cid: {
                    eid: {k: v * s for k, v in m.items()}
                    for eid, m in ents.items()
                }
                for cid, ents in upd.items()
            }

        def _chain(scale, root):
            d1 = build_delta(
                _scaled(scale), art, base_fingerprint=base_fp, generation=1
            )
            d1 = save_delta(d1, os.path.join(root, delta_dir_name(1)))
            d2 = build_delta(
                _scaled(scale * 3), art,
                base_fingerprint=d1.fingerprint, generation=2,
            )
            d2 = save_delta(d2, os.path.join(root, delta_dir_name(2)))
            return root, [d1, d2]

        dir_a, chain_a = _chain(0.5, str(tmp_path / "variant-a"))
        dir_b, chain_b = _chain(-1.0, str(tmp_path / "variant-b"))
        assert chain_a[0].fingerprint != chain_b[0].fingerprint
        verify_chain(base_fp, chain_a)
        verify_chain(base_fp, chain_b)
        with pytest.raises(ValueError, match="chain broken"):
            verify_chain(base_fp, [chain_a[0], chain_b[1]])
        # each chain compacts to its OWN artifact == its in-memory fold
        for chain, root in ((chain_a, dir_a), (chain_b, dir_b)):
            folded = apply_delta(apply_delta(art, chain[0]), chain[1])
            out = os.path.join(root, "compacted")
            compact(
                nearline["artifact_dir"],
                [os.path.join(root, delta_dir_name(g)) for g in (1, 2)],
                out,
            )
            reloaded = load_artifact(out)
            for cid, table in folded.tables.items():
                np.testing.assert_allclose(
                    np.asarray(reloaded.tables[cid].weights),
                    np.asarray(table.weights), atol=1e-7,
                )

    def test_rebase_retargets_chain_head(self, nearline):
        """``rebase_delta`` moves a base-rooted delta onto a variant's own
        chain head: the copy verifies there, the input is untouched, and
        the content fingerprint is cleared (new content, unsaved)."""
        delta = nearline["delta"]
        moved = rebase_delta(delta, "a" * 16)
        assert moved.base_fingerprint == "a" * 16
        assert moved.fingerprint is None
        assert delta.base_fingerprint != "a" * 16  # input untouched
        verify_chain("a" * 16, [moved])
        with pytest.raises(ValueError, match="chain broken"):
            verify_chain("a" * 16, [delta])

    def test_discover_deltas_sorted(self, nearline, tmp_path):
        d = str(tmp_path / "watch")
        os.makedirs(os.path.join(d, "delta-000002"))
        assert discover_deltas(d) == []  # no manifest yet
        for g in (2, 1):
            save_delta(nearline["delta"], os.path.join(d, delta_dir_name(g)))
        assert [os.path.basename(p) for p in discover_deltas(d)] == [
            "delta-000001", "delta-000002",
        ]


def _serving_stack(nearline, **scorer_kw):
    requests = requests_from_game_data(
        nearline["events"], nearline["artifact"]
    )
    scorer = GameScorer(
        nearline["artifact"], max_nnz=max_nnz_of(requests),
        growth_headroom=True, **scorer_kw,
    )
    return scorer, requests


def _scores(scorer, requests, bucket=16):
    out = {}
    for i in range(0, len(requests), bucket):
        for r in scorer.score_batch(requests[i:i + bucket], bucket_size=bucket):
            out[r.request_id] = r.score
    return out


class TestHotSwap:
    def test_swap_updates_touched_scores_without_rejit(self, nearline):
        """Acceptance: in-place swap adds ZERO XLA compilations; touched
        entities' scores move, untouched entities' scores are bit-equal."""
        scorer, requests = _serving_stack(nearline)
        before = _scores(scorer, requests)
        compiles = scorer.compile_count

        manager = HotSwapManager(
            scorer, fingerprint=fingerprint_dir(nearline["artifact_dir"])
        )
        report = manager.apply_delta(nearline["delta_dir"])
        assert not report.rolled_back
        assert report.generation == manager.generation == 1
        assert report.compiles_added == 0
        assert report.regrew == ()  # NEW ids fit the power-of-two headroom
        assert report.rows_updated == nearline["delta"].num_rows_updated
        assert manager.fingerprint == nearline["delta"].fingerprint

        after = _scores(scorer, requests)
        assert scorer.compile_count == compiles  # same bucket, no retrace
        by_user = {
            req.request_id: req.entity_ids["userId"] for req in requests
        }
        moved = {rid for rid in before if before[rid] != after[rid]}
        assert {by_user[rid] for rid in moved} <= set(TOUCHED + NEW)
        assert any(by_user[rid] in TOUCHED for rid in moved)
        # new entities scored cold (FE-only) before, personalized after
        assert any(by_user[rid] in NEW for rid in moved)

    def test_swap_invalidates_touched_cache_rows_only(self, nearline):
        scorer, requests = _serving_stack(nearline, cache_capacity=16)
        _scores(scorer, requests)  # populate the hot cache
        cache = scorer.caches["per_user"]
        index = nearline["artifact"].tables["per_user"].entity_index
        touched_rows = {index.get_index(e) for e in TOUCHED}
        resident_before = set(cache.cached_entities())
        assert resident_before & touched_rows

        manager = HotSwapManager(scorer)
        manager.apply_delta(nearline["delta_dir"])
        resident_after = set(cache.cached_entities())
        assert not resident_after & touched_rows  # stale rows evicted
        # untouched residents survive the swap untouched
        assert resident_before - touched_rows <= resident_after

    def test_validation_gate_rollback(self, nearline):
        """Acceptance: a delta that tanks held-out AUC is rolled back —
        scores, generation and fingerprint all restore."""
        scorer, requests = _serving_stack(nearline)
        labels = np.asarray(
            nearline["events"].labels
            > np.median(nearline["events"].labels),
            dtype=np.float32,
        )
        gate = ValidationGate(requests, labels, max_auc_regression=0.05, bucket_size=16)
        base_fp = fingerprint_dir(nearline["artifact_dir"])
        manager = HotSwapManager(scorer, fingerprint=base_fp, gate=gate)
        before = _scores(scorer, requests)
        compiles = scorer.compile_count

        garbage = DeltaArtifact(
            base_fingerprint=base_fp, generation=1,
            re_rows={
                "per_user": (
                    list(TOUCHED),
                    np.full((len(TOUCHED), DU), -50.0, np.float32),
                )
            },
            fe_updates={}, created_at_unix=0.0, fingerprint="bad0" * 4,
        )
        report = manager.apply_delta(garbage)
        assert report.rolled_back
        assert report.validation_metric < report.baseline_metric - 0.05
        assert manager.generation == 0
        assert manager.fingerprint == base_fp
        after = _scores(scorer, requests)
        assert before == after  # bit-identical restore
        # gate evaluation reuses a warmed bucket: still no extra compiles
        assert scorer.compile_count == compiles

    def test_good_delta_passes_gate(self, nearline):
        scorer, requests = _serving_stack(nearline)
        labels = np.asarray(
            nearline["events"].labels
            > np.median(nearline["events"].labels),
            dtype=np.float32,
        )
        gate = ValidationGate(requests, labels, max_auc_regression=0.05, bucket_size=16)
        manager = HotSwapManager(
            scorer, fingerprint=fingerprint_dir(nearline["artifact_dir"]),
            gate=gate,
        )
        report = manager.apply_delta(nearline["delta_dir"])
        assert not report.rolled_back
        assert report.validation_metric is not None
        assert manager.generation == 1

    def test_poll_directory_applies_once(self, nearline):
        scorer, _ = _serving_stack(nearline)
        manager = HotSwapManager(
            scorer, fingerprint=fingerprint_dir(nearline["artifact_dir"])
        )
        reports = manager.poll_directory(nearline["deltas_dir"])
        assert [r.generation for r in reports] == [1]
        assert manager.poll_directory(nearline["deltas_dir"]) == []

    def test_chain_mismatch_rejected(self, nearline):
        scorer, _ = _serving_stack(nearline)
        manager = HotSwapManager(scorer, fingerprint="0" * 16)
        with pytest.raises(ValueError, match="chain"):
            manager.apply_delta(nearline["delta_dir"])


class TestEndToEndNearline:
    def test_train_serve_update_publish_swap(self, nearline, tmp_path):
        """The full nearline loop through the serve_game --watch-deltas
        plumbing: replay sees the pre-swap scores, a delta lands in the
        watch dir, the next poll swaps it in between batches."""
        watch = str(tmp_path / "watch")
        os.makedirs(watch)
        scorer, requests = _serving_stack(nearline)
        manager = HotSwapManager(
            scorer, fingerprint=fingerprint_dir(nearline["artifact_dir"])
        )
        before = _scores(scorer, requests)
        compiles = scorer.compile_count

        # replay with nothing to watch: no swap
        _, snap0 = replay_requests(
            scorer, requests, bucket_sizes=(16,),
            swap_manager=manager, watch_dir=watch, poll_every=8,
        )
        assert snap0["swap_reports"] == []

        # the nearline trainer publishes a delta mid-stream
        save_delta(nearline["delta"], os.path.join(watch, delta_dir_name(1)))
        results, snap1 = replay_requests(
            scorer, requests, bucket_sizes=(16,),
            swap_manager=manager, watch_dir=watch, poll_every=8,
        )
        assert len(snap1["swap_reports"]) == 1
        assert snap1["swap_reports"][0]["generation"] == 1
        assert not snap1["swap_reports"][0]["rolled_back"]
        assert manager.generation == 1

        after = {r.request_id: r.score for r in results}
        by_user = {
            req.request_id: req.entity_ids["userId"] for req in requests
        }
        changed = {
            by_user[rid] for rid in before if before[rid] != after[rid]
        }
        assert changed <= set(TOUCHED + NEW) and changed
        for rid in before:
            if by_user[rid] in UNTOUCHED:
                assert before[rid] == after[rid]
        # zero additional compilations across the whole swap + replay
        assert scorer.compile_count == compiles


class TestAtomicArtifactSave:
    def test_crash_mid_write_preserves_old_artifact(
        self, nearline, tmp_path, monkeypatch
    ):
        """Crash injection: dying mid-write must leave the previous
        artifact loadable and no tmp litter behind."""
        from photon_ml_tpu.serving import artifact as artifact_mod

        target = str(tmp_path / "artifact")
        save_artifact(nearline["artifact"], target)
        fp = fingerprint_dir(target)

        real = artifact_mod._write_artifact_contents

        def _boom(artifact, out_dir):
            real(artifact, out_dir)  # full payload written, then we die
            raise RuntimeError("injected crash before publish")

        monkeypatch.setattr(artifact_mod, "_write_artifact_contents", _boom)
        with pytest.raises(RuntimeError, match="injected crash"):
            save_artifact(nearline["artifact"], target)
        monkeypatch.undo()

        assert fingerprint_dir(target) == fp  # old artifact intact
        load_artifact(target)
        litter = [
            n for n in os.listdir(tmp_path)
            if n.startswith((".artifact-tmp-", ".artifact-old-"))
        ]
        assert litter == []

    def test_first_write_crash_leaves_nothing(
        self, nearline, tmp_path, monkeypatch
    ):
        from photon_ml_tpu.serving import artifact as artifact_mod

        target = str(tmp_path / "fresh")
        monkeypatch.setattr(
            artifact_mod, "_write_artifact_contents",
            lambda *a: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        with pytest.raises(RuntimeError):
            save_artifact(nearline["artifact"], target)
        assert not os.path.exists(target)
        assert [n for n in os.listdir(tmp_path) if n.startswith(".")] == []


RATINGS = os.path.join(REPO, "tests", "fixtures", "ratings")


@pytest.fixture(scope="module")
def ratings_artifact(tmp_path_factory):
    """Golden-fixture CLI plumbing: a saved model dir, its exported serving
    artifact, and the coordinate-config file that trained it."""
    from photon_ml_tpu import testing
    from photon_ml_tpu.cli.serve_game import main as serve_main
    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration,
        read_game_data,
    )
    from photon_ml_tpu.io.model_io import save_game_model

    shards_raw = {
        "global": {"feature_bags": ["features"], "add_intercept": True},
        "per_user": {"feature_bags": ["userFeatures"], "add_intercept": False},
    }
    shard_cfg = {
        sid: FeatureShardConfiguration(
            feature_bags=s["feature_bags"],
            add_intercept=s["add_intercept"],
        )
        for sid, s in shards_raw.items()
    }
    data, index_maps, _ = read_game_data(
        [os.path.join(RATINGS, "train")], shard_cfg, id_tags=["userId"],
    )
    model = testing.generate_game_model(
        data, TaskType.LINEAR_REGRESSION,
        {
            "fixed": {"feature_shard": "global"},
            "per_user": {
                "feature_shard": "per_user", "random_effect_type": "userId",
            },
        },
        seed=5,
    )
    root = tmp_path_factory.mktemp("ratings-nearline")
    model_dir = str(root / "model")
    save_game_model(
        model, model_dir, index_maps=index_maps,
        configurations={"feature_shards": shards_raw},
    )
    artifact_dir = str(root / "artifact")
    assert serve_main([
        "--model-dir", model_dir, "--export-artifact-dir", artifact_dir,
    ]) == 0
    cfg = {
        "feature_shards": shards_raw,
        "coordinates": {
            "fixed": {
                "type": "fixed", "feature_shard": "global",
                "optimizer": {"regularization": "L2",
                              "regularization_weight": 0.1},
            },
            "per_user": {
                "type": "random", "feature_shard": "per_user",
                "random_effect_type": "userId",
                "optimizer": {"regularization": "L2",
                              "regularization_weight": 1.0},
            },
        },
    }
    cfg_path = str(root / "game.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    return {
        "model_dir": model_dir,
        "artifact_dir": artifact_dir,
        "config": cfg_path,
    }


class TestNearlineCli:
    def test_update_game_publishes_chained_deltas(
        self, ratings_artifact, tmp_path, capsys
    ):
        """update_game publishes delta-000001, a second run auto-chains
        delta-000002 to it, and serve_game --watch-deltas swaps both into
        the live scorer mid-replay."""
        from photon_ml_tpu.cli.serve_game import main as serve_main
        from photon_ml_tpu.cli.update_game import main as update_main

        deltas = str(tmp_path / "deltas")
        argv = [
            "--base-artifact-dir", ratings_artifact["artifact_dir"],
            "--model-dir", ratings_artifact["model_dir"],
            "--coordinate-config", ratings_artifact["config"],
            "--events-data-dirs", os.path.join(RATINGS, "train"),
            "--output-dir", deltas,
        ]
        assert update_main(argv) == 0
        first = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert first["generation"] == 1
        assert first["rows_updated"] > 0
        assert first["base_fingerprint"] == fingerprint_dir(
            ratings_artifact["artifact_dir"]
        )
        assert os.path.isdir(os.path.join(deltas, "delta-000001"))

        assert update_main(argv + ["--refresh-fixed-iterations", "1"]) == 0
        second = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert second["generation"] == 2
        assert second["base_fingerprint"] == first["fingerprint"]
        assert second["fixed_effects_refreshed"] == ["fixed"]
        chain = [
            load_delta(d) for d in discover_deltas(deltas)
        ]
        verify_chain(
            fingerprint_dir(ratings_artifact["artifact_dir"]), chain
        )

        metrics_file = str(tmp_path / "metrics.json")
        assert serve_main([
            "--artifact-dir", ratings_artifact["artifact_dir"],
            "--data-dirs", os.path.join(RATINGS, "test"),
            "--max-requests", "100",
            "--bucket-sizes", "4,16",
            "--watch-deltas", deltas,
            "--watch-chunk", "64",
            "--metrics-output", metrics_file,
        ]) == 0
        capsys.readouterr()
        with open(metrics_file) as f:
            snap = json.load(f)
        assert [r["generation"] for r in snap["swap_reports"]] == [1, 2]
        assert not any(r["rolled_back"] for r in snap["swap_reports"])
        assert snap["swaps"]["current_generation"] == 2
        assert snap["swaps"]["num_rollbacks"] == 0

    def test_update_game_compacts_chain(
        self, ratings_artifact, tmp_path, capsys
    ):
        from photon_ml_tpu.cli.update_game import main as update_main

        deltas = str(tmp_path / "deltas")
        compacted = str(tmp_path / "compacted")
        assert update_main([
            "--base-artifact-dir", ratings_artifact["artifact_dir"],
            "--model-dir", ratings_artifact["model_dir"],
            "--coordinate-config", ratings_artifact["config"],
            "--events-data-dirs", os.path.join(RATINGS, "train"),
            "--output-dir", deltas,
            "--compact-into", compacted,
        ]) == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert summary["compacted_fingerprint"] == fingerprint_dir(compacted)
        load_artifact(compacted)  # the folded chain is a full artifact


@pytest.mark.slow
def test_bench_incremental_smoke_contract():
    """bench.py --incremental emits one machine-readable JSON line with the
    nearline metrics (same contract as the training/serving benches)."""
    env = dict(
        os.environ, BENCH_SMOKE="1", JAX_PLATFORMS="cpu",
        BENCH_PLAN_CACHE="", PHOTON_ML_TPU_COMPILE_CACHE="",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--incremental"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "incremental_update_latency_s"
    assert payload["unit"] == "seconds"
    assert payload["value"] > 0
    assert payload["publish_s"] > 0
    assert payload["swap_blackout_s"] > 0
    assert payload["swap_compiles_added"] == 0
    assert payload["swap_regrew"] == []
    assert payload["rows_updated"] > 0
    assert "error" not in payload
    # smoke mode must not write the results file
    assert not os.path.exists(os.path.join(REPO, "BENCH_INCREMENTAL.json"))
