"""Tests for the Benes-routed static permutation engine and sparse features.

These cover the TPU-native replacement for the reference's per-partition
sparse axpy hot loop (ValueAndGradientAggregator.scala:132-153): routing
correctness (proper coloring, plan/inverse round-trips), device execution
via the XLA fallback path, and matvec/rmatvec equivalence against the
straightforward ELL implementation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.ops import routing
from photon_ml_tpu.ops.features import EllFeatures, from_scipy_like
from photon_ml_tpu.ops.permute_net import apply_plan, device_plan
from photon_ml_tpu.ops.sparse_perm import BenesSparseFeatures, from_coo, from_ell


class TestEulerColor:
    def _check_proper(self, src, dst, deg, n_src, n_dst):
        color = routing.euler_color(src, dst, deg, n_src, n_dst)
        assert color.min() >= 0 and color.max() < deg
        # proper on both sides: (node, color) pairs unique
        assert len(set(zip(src.tolist(), color.tolist()))) == len(src)
        assert len(set(zip(dst.tolist(), color.tolist()))) == len(dst)

    def test_permutation_graph(self, rng):
        # regular bipartite from a permutation over [R, deg] grid
        deg, R = 8, 16
        perm = rng.permutation(R * deg)
        src = (perm // deg).astype(np.int32)
        dst = np.repeat(np.arange(R, dtype=np.int32), deg)
        self._check_proper(src, dst, deg, R, R)

    def test_multigraph_with_repeats(self, rng):
        deg, R = 16, 8
        # random regular bipartite multigraph: connect i-th edge stubs
        src = np.repeat(np.arange(R, dtype=np.int32), deg)
        dst = np.repeat(np.arange(R, dtype=np.int32), deg)
        rng.shuffle(dst)
        self._check_proper(src, dst, deg, R, R)

    def test_numpy_fallback_matches_contract(self, rng):
        deg, R = 4, 8
        src = np.repeat(np.arange(R, dtype=np.int32), deg)
        dst = np.repeat(np.arange(R, dtype=np.int32), deg)
        rng.shuffle(dst)
        color = routing._euler_color_numpy(src, dst, deg, R, R)
        assert len(set(zip(src.tolist(), color.tolist()))) == len(src)
        assert len(set(zip(dst.tolist(), color.tolist()))) == len(dst)


class TestRoutingPlan:
    @pytest.mark.parametrize("n", [128, 256, 1024, 16384, 49152])
    def test_host_apply_matches_perm(self, rng, n):
        perm = rng.permutation(n)
        plan = routing.build_plan(perm)
        x = rng.standard_normal(plan.size).astype(np.float32)
        got = routing.host_apply(plan, x)
        assert np.array_equal(got, x[: plan.size][_pad_perm(perm, plan.size)])

    def test_invert_roundtrip(self, rng):
        n = 16384
        perm = rng.permutation(n)
        plan = routing.build_plan(perm)
        inv = plan.invert()
        x = rng.standard_normal(n).astype(np.float32)
        y = routing.host_apply(plan, x)
        back = routing.host_apply(inv, y)
        assert np.array_equal(back[:n], x)

    def test_valid_size(self):
        assert routing.valid_size(1) == 128
        assert routing.valid_size(128) == 128
        assert routing.valid_size(129) == 256
        assert routing.valid_size(1024) == 1024
        assert routing.valid_size(1025) == 16384
        assert routing.valid_size(16384 * 8 + 1) == 128**3

    def test_rejects_non_bijection(self):
        with pytest.raises(ValueError):
            routing.build_plan(np.array([0, 0, 1]))


def _pad_perm(perm, size):
    full = np.arange(size, dtype=np.int64)
    full[: perm.shape[0]] = perm
    return full


class TestDeviceApply:
    @pytest.mark.parametrize("n", [1024, 16384])
    def test_matches_host(self, rng, n):
        perm = rng.permutation(n)
        plan = routing.build_plan(perm)
        dp = device_plan(plan)
        x = rng.standard_normal(plan.size).astype(np.float32)
        got = jax.jit(lambda v: apply_plan(dp, v))(jnp.asarray(x))
        assert np.array_equal(np.asarray(got), routing.host_apply(plan, x))

    def test_under_jit_with_grad_flow(self, rng):
        # permutation apply is linear; check it traces inside larger programs
        n = 1024
        perm = rng.permutation(n)
        dp = device_plan(routing.build_plan(perm))
        x = jnp.asarray(rng.standard_normal(n).astype(np.float32))

        def f(v):
            return jnp.sum(apply_plan(dp, v) ** 2)

        g = jax.grad(f)(x)
        assert np.allclose(np.asarray(g), 2 * np.asarray(x), atol=1e-5)


class TestBenesSparseFeatures:
    def _random_problem(self, rng, n=512, d=384, k=8):
        rows = np.repeat(np.arange(n), k)
        cols = rng.integers(0, d, n * k)
        vals = rng.standard_normal(n * k).astype(np.float32)
        return rows, cols, vals, (n, d)

    def test_matches_ell(self, rng):
        rows, cols, vals, shape = self._random_problem(rng)
        ell = from_scipy_like(rows, cols, vals, shape)
        bsf = from_coo(rows, cols, vals, shape)
        w = jnp.asarray(rng.standard_normal(shape[1]).astype(np.float32))
        c = jnp.asarray(rng.standard_normal(shape[0]).astype(np.float32))
        assert np.allclose(ell.matvec(w), bsf.matvec(w), atol=1e-4)
        assert np.allclose(ell.rmatvec(c), bsf.rmatvec(c), atol=1e-4)
        assert np.allclose(ell.rmatvec_sq(c), bsf.rmatvec_sq(c), atol=1e-4)
        assert np.allclose(ell.row_norms_sq(), bsf.row_norms_sq(), atol=1e-4)

    def test_duplicate_coalescing(self, rng):
        rows = np.array([0, 0, 1, 1, 1])
        cols = np.array([3, 3, 2, 2, 0])
        vals = np.array([1.0, 2.0, 0.5, 0.25, 4.0], dtype=np.float32)
        bsf = from_coo(rows, cols, vals, (2, 4))
        dense = np.zeros((2, 4), dtype=np.float32)
        np.add.at(dense, (rows, cols), vals)
        w = jnp.asarray(rng.standard_normal(4).astype(np.float32))
        assert np.allclose(bsf.matvec(w), dense @ np.asarray(w), atol=1e-5)
        c = jnp.asarray(rng.standard_normal(2).astype(np.float32))
        assert np.allclose(bsf.rmatvec(c), dense.T @ np.asarray(c), atol=1e-5)

    def test_from_ell_roundtrip(self, rng):
        rows, cols, vals, shape = self._random_problem(rng, n=128, d=96, k=4)
        ell = from_scipy_like(rows, cols, vals, shape)
        bsf = from_ell(ell)
        w = jnp.asarray(rng.standard_normal(shape[1]).astype(np.float32))
        assert np.allclose(ell.matvec(w), bsf.matvec(w), atol=1e-4)

    def test_plan_cache(self, rng, tmp_path):
        rows, cols, vals, shape = self._random_problem(rng, n=128, d=96, k=4)
        b1 = from_coo(rows, cols, vals, shape, plan_cache=str(tmp_path))
        files = list(tmp_path.glob("benesplan_*.npz"))
        assert len(files) == 1
        b2 = from_coo(rows, cols, vals, shape, plan_cache=str(tmp_path))
        w = jnp.asarray(rng.standard_normal(shape[1]).astype(np.float32))
        assert np.allclose(b1.matvec(w), b2.matvec(w), atol=1e-6)

    def test_default_plan_cache_env(self, rng, tmp_path, monkeypatch):
        """plan_cache=None uses $PHOTON_ML_TPU_PLAN_CACHE; "" disables."""
        rows, cols, vals, shape = self._random_problem(rng, n=128, d=96, k=4)
        monkeypatch.setenv("PHOTON_ML_TPU_PLAN_CACHE", str(tmp_path))
        b1 = from_coo(rows, cols, vals, shape)
        files = list(tmp_path.glob("benesplan_*.npz"))
        assert len(files) == 1
        # int8 on-disk stage indices (quartered footprint)
        data = np.load(files[0])
        assert data["idx0"].dtype == np.int8
        b2 = from_coo(rows, cols, vals, shape)  # second build loads the cache
        w = jnp.asarray(rng.standard_normal(shape[1]).astype(np.float32))
        assert np.allclose(b1.matvec(w), b2.matvec(w), atol=1e-6)

        monkeypatch.setenv("PHOTON_ML_TPU_PLAN_CACHE", "")
        from photon_ml_tpu.ops.sparse_perm import default_plan_cache

        assert default_plan_cache() is None

    def test_solver_equivalence(self, rng):
        """A full L-BFGS logistic solve must reach the same optimum through
        either sparse engine (reference-parity: same math as
        ValueAndGradientAggregator + LBFGS.scala defaults)."""
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.ops.data import LabeledData

        n, d, k = 256, 64, 8
        rows = np.repeat(np.arange(n), k)
        cols = rng.integers(0, d, n * k)
        vals = rng.standard_normal(n * k).astype(np.float32)
        w_true = rng.standard_normal(d).astype(np.float32) * 0.3
        dense = np.zeros((n, d), dtype=np.float32)
        np.add.at(dense, (rows, cols), vals)
        z = dense @ w_true
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

        objective = make_glm_objective(LogisticLoss)
        cfg = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=40),
            regularization_weight=1.0,
        )
        results = {}
        for name, feats in {
            "ell": from_scipy_like(rows, cols, vals, (n, d)),
            "benes": from_coo(rows, cols, vals, (n, d)),
        }.items():
            data = LabeledData.create(feats, jnp.asarray(y))
            res = jax.jit(
                lambda dd, feats=feats: solve(
                    objective,
                    jnp.zeros(d, jnp.float32),
                    dd,
                    cfg,
                    l2_weight=jnp.float32(1.0),
                )
            )(data)
            results[name] = res
        assert np.allclose(
            results["ell"].value, results["benes"].value, rtol=1e-4
        ), (results["ell"].value, results["benes"].value)
        assert np.allclose(
            results["ell"].w, results["benes"].w, atol=2e-3
        )


class TestPallasKernelsInterpret:
    """Interpreter-mode coverage of the TPU shuffle kernels (the 8-virtual-
    device harness can't run Mosaic natively; semantics still must match the
    XLA fallback exactly)."""

    def _with_interpret(self, fn):
        from photon_ml_tpu.ops import permute_net

        old = permute_net._INTERPRET
        permute_net._INTERPRET = True
        try:
            return fn()
        finally:
            permute_net._INTERPRET = old

    def test_lane_shuffle_kernel(self, rng):
        from photon_ml_tpu.ops import permute_net

        m = 256
        v = jnp.asarray(rng.standard_normal((m, 128)), dtype=jnp.float32)
        idx = jnp.asarray(rng.integers(0, 128, (m, 128)), dtype=jnp.int8)
        got = self._with_interpret(
            lambda: permute_net._lane_shuffle_pallas(v, idx)
        )
        want = permute_net._lane_shuffle_xla(v, idx)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("rows", [2, 4, 8])
    def test_sublane_shuffle_kernel(self, rng, rows):
        from photon_ml_tpu.ops import permute_net

        m = 256
        v = jnp.asarray(rng.standard_normal((m, 128)), dtype=jnp.float32)
        idx = jnp.asarray(rng.integers(0, rows, (m, 128)), dtype=jnp.int8)
        got = self._with_interpret(
            lambda: permute_net._sublane_shuffle_pallas(v, idx, rows)
        )
        want = permute_net._sublane_shuffle_xla(v, idx, rows)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestHotColumnSplit:
    def test_intercept_column_goes_dense(self, rng):
        """An intercept (degree n) column must not inflate the CSC padding:
        it rides the dense MXU side channel (reference data always carries
        an intercept feature, Constants.scala INTERCEPT_KEY)."""
        n, d, k = 512, 256, 4
        rows = np.repeat(np.arange(n), k + 1)
        cols = np.concatenate(
            [rng.integers(1, d, (n, k)), np.zeros((n, 1), np.int64)], axis=1
        ).reshape(-1)
        vals = rng.standard_normal(n * (k + 1)).astype(np.float32)
        bsf = from_coo(rows, cols, vals, (n, d))
        assert bsf.hot_matrix is not None
        assert 0 in np.asarray(bsf.hot_cols)  # intercept column split out
        # CSC padding tracks the tail, not the intercept
        assert bsf.csc_values.shape[1] < n // 4

        dense = np.zeros((n, d), dtype=np.float32)
        np.add.at(dense, (rows, cols), vals)
        w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
        c = jnp.asarray(rng.standard_normal(n).astype(np.float32))
        assert np.allclose(bsf.matvec(w), dense @ np.asarray(w), atol=1e-4)
        assert np.allclose(bsf.rmatvec(c), dense.T @ np.asarray(c), atol=1e-4)
        assert np.allclose(
            bsf.rmatvec_sq(c), (dense * dense).T @ np.asarray(c), atol=1e-4
        )
        assert np.allclose(
            bsf.row_norms_sq(), (dense * dense).sum(1), atol=1e-4
        )

    def test_disable_hot_split(self, rng):
        n, d, k = 64, 32, 2
        rows = np.repeat(np.arange(n), k)
        cols = rng.integers(0, d, n * k)
        vals = rng.standard_normal(n * k).astype(np.float32)
        bsf = from_coo(rows, cols, vals, (n, d), max_hot_cols=0)
        assert bsf.hot_matrix is None
        dense = np.zeros((n, d), dtype=np.float32)
        np.add.at(dense, (rows, cols), vals)
        w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
        assert np.allclose(bsf.matvec(w), dense @ np.asarray(w), atol=1e-4)


class TestBenesAuxPaths:
    """Validation and feature-summary must accept the Benes engine (the
    auto-engine TPU path feeds it into both before training starts)."""

    def _data(self, rng, weights=None):
        from photon_ml_tpu.ops.data import LabeledData

        n, d, k = 256, 96, 4
        rows = np.repeat(np.arange(n), k + 1)
        cols = np.concatenate(
            [rng.integers(1, d, (n, k)), np.zeros((n, 1), np.int64)], axis=1
        ).reshape(-1)
        vals = rng.standard_normal(rows.size).astype(np.float32)
        y = rng.integers(0, 2, n).astype(np.float32)
        mk = lambda feats: LabeledData.create(
            feats, jnp.asarray(y),
            weights=None if weights is None else jnp.asarray(weights),
        )
        return (
            mk(from_scipy_like(rows, cols, vals, (n, d))),
            mk(from_coo(rows, cols, vals, (n, d))),
        )

    def test_summarize_matches_ell(self, rng):
        from photon_ml_tpu.stat.summary import summarize

        w = np.ones(256, np.float32)
        w[::7] = 0.0  # padding rows exercise the live-mask routing
        ell_data, benes_data = self._data(rng, weights=w)
        a = summarize(ell_data)
        b = summarize(benes_data)
        for field in (
            "mean", "variance", "num_nonzeros", "max_abs", "min_val",
            "max_val", "mean_abs",
        ):
            np.testing.assert_allclose(
                np.asarray(getattr(b, field)),
                np.asarray(getattr(a, field)),
                atol=1e-4,
                err_msg=field,
            )

    def test_validation_accepts_benes(self, rng):
        from photon_ml_tpu.data.validators import (
            DataValidationType,
            validate_labeled_data,
        )
        from photon_ml_tpu.types import TaskType

        _, benes_data = self._data(rng)
        validate_labeled_data(
            benes_data, TaskType.LOGISTIC_REGRESSION,
            DataValidationType.VALIDATE_FULL,
        )
        validate_labeled_data(
            benes_data, TaskType.LOGISTIC_REGRESSION,
            DataValidationType.VALIDATE_SAMPLE,
        )


class TestEulerColorAtScale:
    def test_multithreaded_path(self, rng):
        """>= 2^20 edges takes the threaded branch of the native colorer
        (worker-per-segment with per-thread scratch); the coloring must stay
        proper and deterministic."""
        deg, R = 128, 8192  # 1,048,576 edges
        src = np.repeat(np.arange(R, dtype=np.int32), deg)
        dst = np.repeat(np.arange(R, dtype=np.int32), deg)
        rng.shuffle(dst)
        c1 = routing.euler_color(src, dst, deg, R, R)
        assert c1.min() >= 0 and c1.max() < deg
        # proper on both sides without materializing python sets of 1M pairs
        assert np.unique(src.astype(np.int64) * deg + c1).size == src.size
        assert np.unique(dst.astype(np.int64) * deg + c1).size == dst.size
        c2 = routing.euler_color(src, dst, deg, R, R)
        np.testing.assert_array_equal(c1, c2)


class TestKpCapSpill:
    """KP cap + spill-COO side (sparse_perm.auto_kp_cap): thin column-degree
    tails — the 1B-coefficient grid shard's ~1 nnz/col — must not pad the
    routed network by max/mean degree. Every linear map and the stats path
    must stay exact with entries spilled to the scatter side."""

    def _thin_tail_problem(self, rng, n=512, d=4096, nnz=4096):
        rows = rng.integers(0, n, nnz).astype(np.int64)
        cols = rng.integers(0, d, nnz).astype(np.int64)
        vals = rng.standard_normal(nnz).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        return rows, cols, vals, dense

    @pytest.mark.parametrize("engine", ["benes", "fused"])
    def test_capped_maps_match_dense(self, rng, engine):
        from photon_ml_tpu.ops import fused_perm

        rows, cols, vals, dense = self._thin_tail_problem(rng)
        n, d = dense.shape
        builder = from_coo if engine == "benes" else fused_perm.from_coo
        f_cap = builder(rows, cols, vals, (n, d), plan_cache="",
                        max_hot_cols=0, kp_cap="auto")
        f_unc = builder(rows, cols, vals, (n, d), plan_cache="",
                        max_hot_cols=0, kp_cap=None)
        # the cap must engage on this degree profile and shrink the network
        assert f_cap.spill_rows is not None
        assert f_cap.plan.size < f_unc.plan.size
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f_cap.matvec(jnp.asarray(w))), dense @ w,
            atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(f_cap.rmatvec(jnp.asarray(c))), dense.T @ c,
            atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(f_cap.rmatvec_sq(jnp.asarray(c))),
            (dense * dense).T @ c, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(f_cap.row_norms_sq()), (dense * dense).sum(1),
            atol=2e-4,
        )

    @pytest.mark.parametrize("engine", ["benes", "fused"])
    def test_capped_stats_match_dense(self, rng, engine):
        from photon_ml_tpu.ops import fused_perm
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.stat.summary import summarize

        rows, cols, vals, dense = self._thin_tail_problem(rng)
        n, d = dense.shape
        builder = from_coo if engine == "benes" else fused_perm.from_coo
        f_cap = builder(rows, cols, vals, (n, d), plan_cache="",
                        max_hot_cols=0, kp_cap="auto")
        assert f_cap.spill_rows is not None
        wts = rng.random(n).astype(np.float32)
        y = rng.random(n).astype(np.float32)
        ref = summarize(LabeledData.create(
            DenseFeatures(matrix=jnp.asarray(dense)), jnp.asarray(y),
            weights=jnp.asarray(wts),
        ))
        got = summarize(LabeledData.create(
            f_cap, jnp.asarray(y), weights=jnp.asarray(wts),
        ))
        for fld in ("mean", "variance", "num_nonzeros", "max_abs",
                    "min_val", "max_val", "mean_abs"):
            np.testing.assert_allclose(
                np.asarray(getattr(got, fld)),
                np.asarray(getattr(ref, fld)),
                atol=3e-4, err_msg=fld,
            )

    def test_planner_escapes_ladder_cliff_on_thin_tails(self):
        """The r5 planner fix: a thin-tailed wide shard whose spill at a
        small cap slightly exceeds the old hard budget (nnz/128) must NOT
        fall back to the flat 16x-padded network above the valid-size
        ladder cliff — spill is a cost, not a gate. Shape mirrors the
        2^26-column memory-envelope tile scaled down."""
        from photon_ml_tpu.ops import routing
        from photon_ml_tpu.ops.sparse_perm import (
            make_row_block_k,
            resolve_layout,
        )

        rng = np.random.default_rng(11)
        n, k, d = 1 << 14, 16, 1 << 20  # nnz = 262144, ~0.25 nnz/col
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = rng.integers(0, d, n * k).astype(np.int64)
        cc = np.bincount(cols, minlength=d)
        kp_full = 1 << int(np.ceil(np.log2(cc.max())))
        cap, t = resolve_layout(
            "auto", "auto", cc, n, d, k, kp_full,
            row_block_k=make_row_block_k(rows, cols, n, d),
        )
        eff = cap if cap else kp_full
        total = t * routing.valid_size(max(n * k, -(-d // t) * eff))
        flat = routing.valid_size(max(n * k, d * kp_full))
        nnz = n * k
        # the flat network pads ~16x past the ladder step; the planned
        # layout must stay within 8x of nnz and beat flat by >= 2x
        assert total <= 8 * nnz, (cap, t, total, nnz)
        assert total * 2 <= flat, (total, flat)
        # spill stays within the sanity fraction
        spill = int(np.maximum(cc - eff, 0).sum())
        assert spill <= nnz // 8

    def test_planner_keeps_uncapped_split_for_non_pow2_kp(self):
        """kp_full is the raw max column degree (not a power of two) in
        sparse_perm.from_coo; the uncapped candidate must still enter the
        joint search so an uncapped multi-block split survives when every
        pow2 cap would spill too much (r5 review regression)."""
        from photon_ml_tpu.ops import routing
        from photon_ml_tpu.ops.sparse_perm import plan_column_layout

        n, K, d = 1024, 96, 65536
        # 8192 columns of degree exactly 12: kp_full = 12; spill at any
        # pow2 cap below 12 exceeds nnz/8
        cc = np.zeros(d, dtype=np.int64)
        cc[:8192] = 12
        cap, t = plan_column_layout(cc, n, d, K, kp_full=12)
        eff = cap if cap else 12
        total = t * routing.valid_size(max(n * K, -(-d // t) * eff))
        flat = routing.valid_size(max(n * K, d * 12))
        assert total * 2 <= flat, (cap, t, total, flat)

    def test_explicit_cap_and_disable(self, rng):
        rows, cols, vals, dense = self._thin_tail_problem(rng)
        n, d = dense.shape
        f2 = from_coo(rows, cols, vals, (n, d), plan_cache="",
                      max_hot_cols=0, kp_cap=2)
        assert f2.csc_values.shape[1] == 2
        w = rng.standard_normal(d).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f2.matvec(jnp.asarray(w))), dense @ w, atol=2e-4
        )
        f_off = from_coo(rows, cols, vals, (n, d), plan_cache="",
                         max_hot_cols=0, kp_cap=None)
        assert f_off.spill_rows is None
        with pytest.raises(ValueError, match="power of two"):
            from_coo(rows, cols, vals, (n, d), plan_cache="",
                     max_hot_cols=0, kp_cap=3)

    def test_cap_composes_with_hot_columns(self, rng):
        """Hot-column dense split and the spill side together: a matrix with
        an intercept-like full column AND a thin tail."""
        rows, cols, vals, dense = self._thin_tail_problem(rng, n=256, d=2048)
        n, d = dense.shape
        icpt_rows = np.arange(n, dtype=np.int64)
        rows = np.concatenate([rows, icpt_rows])
        cols = np.concatenate([cols, np.full(n, d - 1, dtype=np.int64)])
        ones = np.ones(n, dtype=np.float32)
        vals = np.concatenate([vals, ones])
        dense[:, d - 1] += 1.0
        f = from_coo(rows, cols, vals, (n, d), plan_cache="", kp_cap="auto")
        assert f.hot_matrix is not None
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f.matvec(jnp.asarray(w))), dense @ w, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(f.rmatvec(jnp.asarray(c))), dense.T @ c, atol=2e-4
        )

    def test_grid_cap_engages_and_matches_dense(self, rng):
        from photon_ml_tpu.parallel.grid_features import (
            grid_from_coo,
            grid_mesh,
            shard_vector_data,
            shard_vector_feat,
        )

        rows, cols, vals, dense = self._thin_tail_problem(
            rng, n=512, d=2048, nnz=3000
        )
        n, d = dense.shape
        mesh = grid_mesh(2, 4)
        gf = grid_from_coo(rows, cols, vals, (n, d), mesh, engine="benes",
                           plan_cache="")
        gf_unc = grid_from_coo(rows, cols, vals, (n, d), mesh,
                               engine="benes", plan_cache="", kp_cap=None)
        tile = jax.tree.map(lambda a: a[0, 0], gf.shards)
        tile_unc = jax.tree.map(lambda a: a[0, 0], gf_unc.shards)

        def _tile_slots(t):
            # flat tile or ColumnSplitFeatures (the auto planner may pick
            # either depending on the cost model) — total routed slots
            if hasattr(t, "plan"):
                return t.plan.size
            return sum(
                b.plan.size for b in t.blocks if hasattr(b, "plan")
            )

        assert _tile_slots(tile) <= _tile_slots(tile_unc)
        w = rng.standard_normal(gf.dim).astype(np.float32)
        w[d:] = 0
        c = rng.standard_normal(gf.num_rows).astype(np.float32)
        c[n:] = 0
        z = np.asarray(gf.matvec(shard_vector_feat(jnp.asarray(w), mesh)))[:n]
        g = np.asarray(gf.rmatvec(shard_vector_data(jnp.asarray(c), mesh)))[:d]
        np.testing.assert_allclose(z, dense @ w[:d], atol=3e-4)
        np.testing.assert_allclose(g, dense.T @ c[:n], atol=3e-4)

    @pytest.mark.parametrize("engine", ["benes", "fused"])
    def test_column_split_engages_and_matches_dense(self, rng, engine):
        """The 1B-coef chip-tile profile (n*K ~ d, ~1 nnz/col): the joint
        layout planner must land under the plain network's slot count and
        stay exact (ColumnSplitFeatures or cap-only, whichever wins)."""
        from photon_ml_tpu.ops import fused_perm
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        n, d, k = 1024, 16384, 16
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = rng.integers(0, d, n * k).astype(np.int64)
        vals = rng.standard_normal(n * k).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        builder = from_coo if engine == "benes" else fused_perm.from_coo
        f = builder(rows, cols, vals, (n, d), plan_cache="", max_hot_cols=0)
        f_plain = builder(rows, cols, vals, (n, d), plan_cache="",
                          max_hot_cols=0, kp_cap=None, col_split=1)
        if isinstance(f, ColumnSplitFeatures):
            tot = sum(
                b.plan.size for b in f.blocks if hasattr(b, "plan")
            )
        else:
            tot = f.plan.size
        assert tot < f_plain.plan.size
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f.matvec(jnp.asarray(w))), dense @ w, atol=3e-4
        )
        np.testing.assert_allclose(
            np.asarray(f.rmatvec(jnp.asarray(c))), dense.T @ c, atol=3e-4
        )
        np.testing.assert_allclose(
            np.asarray(f.rmatvec_sq(jnp.asarray(c))), (dense * dense).T @ c,
            atol=3e-4,
        )

    def test_explicit_column_split(self, rng):
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        rows, cols, vals, dense = self._thin_tail_problem(rng)
        n, d = dense.shape
        f = from_coo(rows, cols, vals, (n, d), plan_cache="",
                     max_hot_cols=0, kp_cap=None, col_split=4)
        assert isinstance(f, ColumnSplitFeatures)
        assert len(f.blocks) == 4
        w = rng.standard_normal(d).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f.matvec(jnp.asarray(w))), dense @ w, atol=2e-4
        )
        with pytest.raises(ValueError, match="power of two"):
            from_coo(rows, cols, vals, (n, d), plan_cache="",
                     max_hot_cols=0, col_split=3)

    def test_column_split_stats_and_validation(self, rng):
        from photon_ml_tpu.data.validators import validate_labeled_data
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures
        from photon_ml_tpu.stat.summary import summarize
        from photon_ml_tpu.types import TaskType

        rows, cols, vals, dense = self._thin_tail_problem(rng)
        n, d = dense.shape
        f = from_coo(rows, cols, vals, (n, d), plan_cache="",
                     max_hot_cols=0, col_split=4)
        assert isinstance(f, ColumnSplitFeatures)
        wts = rng.random(n).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        ld = LabeledData.create(f, jnp.asarray(y), weights=jnp.asarray(wts))
        got = summarize(ld)
        ref = summarize(LabeledData.create(
            DenseFeatures(matrix=jnp.asarray(dense)), jnp.asarray(y),
            weights=jnp.asarray(wts),
        ))
        for fld in ("mean", "variance", "num_nonzeros", "max_abs",
                    "min_val", "max_val", "mean_abs"):
            np.testing.assert_allclose(
                np.asarray(getattr(got, fld)),
                np.asarray(getattr(ref, fld)),
                atol=3e-4, err_msg=fld,
            )
        validate_labeled_data(ld, TaskType.LOGISTIC_REGRESSION)

    @pytest.mark.parametrize("engine", ["benes", "fused"])
    def test_multi_tile_grid_pinned_column_split(self, rng, engine):
        """Multi-tile grids support the column split with globally pinned
        per-block shapes: every (tile, block) stacks leaf-by-leaf and the
        sharded maps stay exact (the v5e-64 1B-coef tiles hit the same
        ladder overshoot as single-chip shards)."""
        from photon_ml_tpu.parallel.grid_features import (
            grid_from_coo,
            grid_mesh,
            shard_vector_data,
            shard_vector_feat,
        )
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        n, d, k = 1024, 8192, 8
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = rng.integers(0, d, n * k).astype(np.int64)
        vals = rng.standard_normal(n * k).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        mesh = grid_mesh(2, 2)
        gf = grid_from_coo(rows, cols, vals, (n, d), mesh, engine=engine,
                           plan_cache="", col_split=2)
        tile = jax.tree.map(lambda a: a[0, 0], gf.shards)
        assert isinstance(tile, ColumnSplitFeatures)
        assert len(tile.blocks) == 2
        w = rng.standard_normal(gf.dim).astype(np.float32)
        w[d:] = 0
        c = rng.standard_normal(gf.num_rows).astype(np.float32)
        c[n:] = 0
        z = np.asarray(gf.matvec(shard_vector_feat(jnp.asarray(w), mesh)))[:n]
        g = np.asarray(gf.rmatvec(shard_vector_data(jnp.asarray(c), mesh)))[:d]
        g2 = np.asarray(
            gf.rmatvec_sq(shard_vector_data(jnp.asarray(c), mesh))
        )[:d]
        rn = np.asarray(gf.row_norms_sq())[:n]
        np.testing.assert_allclose(z, dense @ w[:d], atol=3e-4)
        np.testing.assert_allclose(g, dense.T @ c[:n], atol=3e-4)
        np.testing.assert_allclose(g2, (dense * dense).T @ c[:n], atol=3e-4)
        np.testing.assert_allclose(rn, (dense * dense).sum(1), atol=3e-4)
