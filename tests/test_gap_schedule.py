"""Gap-guided block scheduling (DuHL) for stochastic streaming.

The CI "Gap scheduler parity gate" runs this module. The load-bearing
contract: with ``gap_schedule`` OFF (the default) the stochastic visit
order is bitwise-identical to the historical blind per-epoch
``rng.permutation`` trajectory — the scheduler must be impossible to
observe unless opted into. With it ON, the scheduler's invariants hold:
bootstrap epochs cover every block, stale scores decay, the exploration
floor refreshes every block within ``~1/explore`` epochs, and selected
blocks are grouped by part file so the decode LRU decodes each part file
at most once per epoch.
"""

import numpy as np
import pytest

from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    build_index_maps,
    write_training_examples,
)
from photon_ml_tpu.streaming import (
    BlockPrefetcher,
    GapScheduler,
    StreamingSource,
    group_by_part_file,
    solve_streaming_stochastic,
)

# Aligned layout on purpose: block_rows divides every file's rows, so no
# block straddles a file boundary and "one decode per file per epoch" is
# an exact guarantee (not just the expected case).
FILE_ROWS = (64, 64, 64)
N_ROWS = sum(FILE_ROWS)
D = 6
BLOCK_ROWS = 32  # 192 rows -> 6 blocks, 2 per file, none ragged

SHARDS = {
    "global": FeatureShardConfiguration(
        feature_bags=("features",), add_intercept=True
    ),
}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    rng = np.random.default_rng(7)
    root = tmp_path_factory.mktemp("gapsched")
    X = rng.normal(size=(N_ROWS, D)).astype(np.float32)
    w = rng.normal(size=D).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-(X @ w))) > rng.random(N_ROWS)).astype(
        np.float32
    )
    paths = []
    row = 0
    for fi, n in enumerate(FILE_ROWS):
        recs = [
            {
                "uid": f"r{i}",
                "label": float(y[i]),
                "weight": 1.0,
                "features": [
                    ("g", str(j), float(X[i, j])) for j in range(D)
                ],
            }
            for i in range(row, row + n)
        ]
        p = str(root / f"part-{fi:05d}.avro")
        write_training_examples(p, recs)
        paths.append(p)
        row += n
    return {"paths": paths, "index_maps": build_index_maps(paths, SHARDS)}


@pytest.fixture()
def source(dataset):
    return StreamingSource.open(
        dataset["paths"], SHARDS, index_maps=dataset["index_maps"],
        block_rows=BLOCK_ROWS,
    )


# ------------------------------------------------------- scheduler unit
class TestGapScheduler:
    def test_ctor_validation(self):
        with pytest.raises(ValueError, match="num_blocks"):
            GapScheduler(0)
        with pytest.raises(ValueError, match="decay"):
            GapScheduler(4, decay=0.0)
        with pytest.raises(ValueError, match="decay"):
            GapScheduler(4, decay=1.5)
        with pytest.raises(ValueError, match="explore"):
            GapScheduler(4, explore=-0.1)
        with pytest.raises(ValueError, match="visit_fraction"):
            GapScheduler(4, visit_fraction=0.0)

    def test_bootstrap_epoch_visits_every_block(self):
        sched = GapScheduler(10, visit_fraction=0.3)
        order = sched.epoch_order()
        assert sorted(order.tolist()) == list(range(10))

    def test_visit_fraction_sizes_scheduled_epochs(self):
        sched = GapScheduler(10, visit_fraction=0.4, explore=0.1)
        first = sched.epoch_order()
        sched.update({int(b): 1.0 + int(b) for b in first})
        order = sched.epoch_order()
        # ceil(0.4 * 10) selected + 1 exploration pick
        assert order.size == 5
        # the four largest measured gaps are all in the visit set
        assert {9, 8, 7, 6} <= set(order.tolist())

    def test_unvisited_blocks_outrank_measured_ones(self):
        sched = GapScheduler(6, visit_fraction=0.5)
        first = sched.epoch_order()
        # feed back gaps for only half the visited blocks: the rest stay
        # at the +inf sentinel and must be re-selected next epoch
        sched.update({int(b): 5.0 for b in first[:3]})
        unmeasured = set(int(b) for b in first[3:])
        order = sched.epoch_order()
        assert unmeasured <= set(order.tolist())

    def test_decay_discounts_stale_scores(self):
        sched = GapScheduler(4, decay=0.5, visit_fraction=0.25, explore=0.0)
        sched.epoch_order()
        sched.update({0: 8.0, 1: 1.0, 2: 1.0, 3: 1.0})
        eff0 = sched.effective_scores()
        assert eff0[0] == 8.0  # age 0: undiscounted
        # three epochs without visiting block 0 -> score halves each epoch
        for _ in range(3):
            sched.update({})
        eff3 = sched.effective_scores()
        assert eff3[0] == pytest.approx(8.0 * 0.5 ** 3)

    def test_exploration_refreshes_stale_blocks(self):
        # Block 0 measures a tiny gap once; blocks 1..9 always measure
        # large gaps. Greedy-only scheduling would starve block 0 forever;
        # the epsilon floor must re-visit it within ~1/explore epochs.
        sched = GapScheduler(
            10, decay=1.0, explore=0.1, visit_fraction=0.5, seed=3
        )
        first = sched.epoch_order()
        sched.update({int(b): (0.001 if b == 0 else 10.0) for b in first})
        revisited_at = None
        for epoch in range(1, 21):
            order = sched.epoch_order()
            if 0 in order.tolist():
                revisited_at = epoch
                break
            sched.update({int(b): 10.0 for b in order})
        assert revisited_at is not None and revisited_at <= 12

    def test_update_rejects_out_of_range_blocks(self):
        sched = GapScheduler(4)
        with pytest.raises(IndexError, match="outside"):
            sched.update({4: 1.0})

    def test_drain_decisions_records_and_clears(self):
        sched = GapScheduler(5, visit_fraction=0.4)
        sched.epoch_order()
        sched.update({0: 1.0, 1: 2.0, 2: 3.0, 3: 4.0, 4: 5.0})
        sched.epoch_order()
        decisions = sched.drain_decisions()
        assert [d["epoch"] for d in decisions] == [0, 1]
        assert decisions[0]["visited"] == 5  # bootstrap covers everything
        assert decisions[1]["unvisited"] == 0
        assert decisions[1]["score_max"] == 5.0
        assert sched.drain_decisions() == []

    def test_gauges_exported(self):
        from photon_ml_tpu.telemetry import get_registry

        sched = GapScheduler(8)
        sched.epoch_order()
        gauges = get_registry().snapshot()["gauges"]
        assert gauges["stream.gap_sched.visited_blocks"]["last"] == 8.0
        assert "stream.gap_sched.visit_fraction" in gauges


# --------------------------------------------- part-file-aware ordering
class TestGroupByPartFile:
    def test_groups_same_file_blocks_adjacently(self, source):
        plan = source.plan
        # blocks 0,1 -> file 0; 2,3 -> file 1; 4,5 -> file 2
        got = group_by_part_file([5, 0, 3, 1, 4, 2], plan)
        assert got == [4, 5, 0, 1, 2, 3]
        # file order follows each file's highest-priority block; within a
        # file blocks ascend so the decode walk is monotone — and only the
        # given blocks appear (reordering never widens the visit set)
        assert group_by_part_file([2, 5, 3], plan) == [2, 3, 5]
        assert group_by_part_file([], plan) == []

    def test_one_decode_per_file_per_epoch(self, source):
        """The re-decode hazard fix: a grouped shuffled visit order must
        not decode any part file more than once per pass (aligned blocks,
        so the guarantee is exact, not amortized)."""
        plan = source.plan
        rng = np.random.default_rng(0)
        worst = rng.permutation(plan.num_blocks)  # interleaves files
        order = group_by_part_file(worst, plan)
        before = source.files_decoded
        for _ in BlockPrefetcher(
            source, shards=("global",), order=list(order)
        ):
            pass
        assert source.files_decoded - before <= len(plan.files)


# ------------------------------------------------- solver off/on paths
def _stochastic_fixture(source):
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.opt import GlmOptimizationConfiguration
    from photon_ml_tpu.opt.config import RegularizationContext
    from photon_ml_tpu.types import RegularizationType

    cfg = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.5,
    )
    objective = make_glm_objective(LogisticLoss)
    dim = source.plan.shard_dims["global"]
    w0 = jnp.zeros((dim,), jnp.float32)
    return objective, cfg, w0


class TestSolverScheduling:
    def _run(self, source, scheduler, seed=5, epochs=4):
        objective, cfg, w0 = _stochastic_fixture(source)
        orders = []

        class _Shard:
            def __init__(self, blk):
                self.data = blk.data["global"]
                self.weight_sum = blk.weight_sum

        def make_blocks(order):
            orders.append(np.asarray(order).copy())

            def gen():
                for blk in BlockPrefetcher(
                    source, shards=("global",), order=list(order)
                ):
                    yield _Shard(blk)

            return gen()

        result = solve_streaming_stochastic(
            objective, w0, make_blocks,
            configuration=cfg,
            num_blocks=source.plan.num_blocks,
            total_weight=float(N_ROWS),
            epochs=epochs, chunk_iters=2, blocks_per_update=2, seed=seed,
            scheduler=scheduler,
        )
        return result, orders

    def test_off_path_orders_are_the_blind_permutation(self, source):
        """gap_schedule off MUST reproduce the historical trajectory
        bitwise: per-epoch orders equal a fresh rng's permutation stream
        and the solved w is bit-for-bit deterministic across runs."""
        result_a, orders_a = self._run(source, scheduler=None, seed=5)
        rng = np.random.default_rng(5)
        for order in orders_a:
            np.testing.assert_array_equal(
                order, rng.permutation(source.plan.num_blocks)
            )
        result_b, orders_b = self._run(source, scheduler=None, seed=5)
        for oa, ob in zip(orders_a, orders_b):
            np.testing.assert_array_equal(oa, ob)
        np.testing.assert_array_equal(
            np.asarray(result_a.w), np.asarray(result_b.w)
        )

    def test_gap_path_bootstraps_then_schedules(self, source):
        n = source.plan.num_blocks
        sched = GapScheduler(
            n, plan=source.plan, visit_fraction=0.5, explore=0.0, seed=0
        )
        result, orders = self._run(source, scheduler=sched, epochs=3)
        # epoch 0 bootstraps every block; later epochs visit the
        # visit_fraction working set (3 of 6) plus the minimum single
        # exploration pick the floor guarantees even at explore=0
        assert sorted(orders[0].tolist()) == list(range(n))
        assert all(o.size == 4 for o in orders[1:])
        # the solver fed measured gaps back: nothing left unmeasured
        assert np.all(np.isfinite(sched.scores))
        assert np.asarray(result.w).shape == (source.plan.shard_dims["global"],)

    def test_gap_orders_are_file_grouped(self, source):
        sched = GapScheduler(source.plan.num_blocks, plan=source.plan, seed=1)
        _, orders = self._run(source, scheduler=sched, epochs=3)
        for order in orders:
            starts = [source.plan.spans(int(b))[0][0] for b in order]
            # each part file appears as one contiguous run
            runs = [f for i, f in enumerate(starts) if i == 0 or starts[i - 1] != f]
            assert len(runs) == len(set(runs)), (order, starts)


# ------------------------------------------------- coordinate/estimator
class TestCoordinateWiring:
    def test_gap_schedule_requires_stochastic_mode(self, source):
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.streaming.coordinate import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        with pytest.raises(ValueError, match="stochastic"):
            StreamingFixedEffectCoordinate(
                source=source,
                shard_id="global",
                task=TaskType.LOGISTIC_REGRESSION,
                configuration=GlmOptimizationConfiguration(
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                    regularization_weight=0.1,
                ),
                mode="full",
                gap_schedule=True,
            )

    def test_estimator_gap_schedule_end_to_end(self, source):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType, TaskType

        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration(
                    "global",
                    GlmOptimizationConfiguration(
                        regularization=RegularizationContext(
                            RegularizationType.L2
                        ),
                        regularization_weight=0.1,
                    ),
                )
            },
            update_order=["fixed"],
            num_outer_iterations=1,
        )
        fit = est.fit_streaming(
            source, mode="stochastic", stochastic_epochs=4,
            stochastic_chunk_iters=2, gap_schedule=True,
        )
        coord = fit.model  # smoke: the fit produced a scoreable model
        assert coord is not None
        from photon_ml_tpu.telemetry import get_registry

        gauges = get_registry().snapshot()["gauges"]
        assert "stream.gap_sched.visited_blocks" in gauges
