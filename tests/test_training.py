"""Fixed-effect trainer tests: λ sweep warm start, normalization round-trip,
summary stats, and single-device vs 8-device-mesh equivalence (the analog of
the reference's NormalizationTest + OptimizerIntegTest on local[4] Spark).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.estimators import train_glm
from photon_ml_tpu.normalization import build_normalization_context
from photon_ml_tpu.ops import DenseFeatures, LabeledData
from photon_ml_tpu.ops.features import from_scipy_like
from photon_ml_tpu.opt import (
    GlmOptimizationConfiguration,
    OptimizerConfig,
    RegularizationContext,
)
from photon_ml_tpu.parallel import data_parallel_mesh, pad_batch_to_multiple, shard_batch
from photon_ml_tpu.stat import summarize
from photon_ml_tpu.types import NormalizationType, RegularizationType, TaskType


def _logreg(rng, n=256, d=8, intercept=True):
    X = rng.normal(size=(n, d)).astype(np.float32) * 2 + 0.5
    if intercept:
        X[:, -1] = 1.0
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1 / (1 + np.exp(-(X @ w_true)))
    y = (rng.random(n) < p).astype(np.float32)
    return X, y


L2CFG = GlmOptimizationConfiguration(
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def test_lambda_sweep_order_and_shrinkage(rng):
    X, y = _logreg(rng)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    lams = [0.1, 100.0, 10.0]
    fits = train_glm(data, TaskType.LOGISTIC_REGRESSION, L2CFG, regularization_weights=lams)
    # returned in requested order
    assert [f.regularization_weight for f in fits] == lams
    # heavier regularization => smaller coefficients
    norms = {f.regularization_weight: float(f.model.coefficients.l2_norm()) for f in fits}
    assert norms[100.0] < norms[10.0] < norms[0.1]


def test_normalization_returns_original_space_coefficients(rng):
    """Training with STANDARDIZATION must produce (near-)identical
    original-space models to training without normalization (the reference's
    NormalizationTest invariant: all normalization types reach the same
    optimum up to tolerance when unregularized)."""
    X, y = _logreg(rng, n=512, d=6)
    data_plain = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    summ = summarize(data_plain)
    norm = build_normalization_context(
        NormalizationType.STANDARDIZATION,
        summ.mean,
        summ.variance,
        summ.max_abs,
        intercept_index=5,
    )
    data_norm = LabeledData.create(
        DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y), norm=norm
    )
    cfg = GlmOptimizationConfiguration()  # unregularized LBFGS
    fit_plain = train_glm(data_plain, TaskType.LOGISTIC_REGRESSION, cfg)[0]
    fit_norm = train_glm(
        data_norm, TaskType.LOGISTIC_REGRESSION, cfg, intercept_index=5
    )[0]
    np.testing.assert_allclose(
        fit_norm.model.coefficients.means,
        fit_plain.model.coefficients.means,
        rtol=5e-2,
        atol=5e-3,
    )


def test_variances_inverse_hessian(rng):
    X, y = _logreg(rng, n=128, d=4, intercept=False)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    fit = train_glm(
        data, TaskType.LOGISTIC_REGRESSION, L2CFG, compute_variances=True
    )[0]
    v = fit.model.coefficients.variances
    assert v is not None and v.shape == (4,)
    assert float(jnp.min(v)) > 0


def test_summary_matches_numpy(rng):
    X = rng.normal(size=(64, 5)).astype(np.float32)
    X[rng.random((64, 5)) < 0.5] = 0.0
    w = rng.random(64).astype(np.float32) + 0.1
    data_dense = LabeledData.create(
        DenseFeatures(matrix=jnp.asarray(X)), jnp.zeros(64), weights=jnp.asarray(w)
    )
    rows, cols = np.nonzero(X)
    ell = from_scipy_like(rows, cols, X[rows, cols], X.shape)
    data_ell = LabeledData.create(ell, jnp.zeros(64), weights=jnp.asarray(w))

    for data in (data_dense, data_ell):
        s = summarize(data)
        wsum = w.sum()
        mean_np = (w[:, None] * X).sum(0) / wsum
        np.testing.assert_allclose(s.mean, mean_np, rtol=1e-4, atol=1e-5)
        var_np = ((w[:, None] * (X - mean_np) ** 2).sum(0)) / (wsum - 1)
        np.testing.assert_allclose(s.variance, var_np, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(s.max_val, X.max(0), rtol=1e-5)
        np.testing.assert_allclose(s.min_val, X.min(0), rtol=1e-5)
        np.testing.assert_allclose(s.max_abs, np.abs(X).max(0), rtol=1e-5)
        np.testing.assert_allclose(s.count, wsum, rtol=1e-5)


def test_pad_batch_is_noop_algebraically(rng):
    X, y = _logreg(rng, n=30, d=4, intercept=False)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    padded = pad_batch_to_multiple(data, 8)
    assert padded.num_rows == 32
    fit_a = train_glm(data, TaskType.LOGISTIC_REGRESSION, L2CFG)[0]
    fit_b = train_glm(padded, TaskType.LOGISTIC_REGRESSION, L2CFG)[0]
    np.testing.assert_allclose(
        fit_a.model.coefficients.means, fit_b.model.coefficients.means, rtol=1e-4, atol=1e-5
    )


def test_sharded_training_matches_single_device(rng):
    """The core distributed invariant: training over an 8-device mesh (batch
    sharded, XLA-inserted psums) must reproduce the single-device result.
    Replaces the reference's treeAggregate-vs-local equivalence testing."""
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    X, y = _logreg(rng, n=256, d=8)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    fit_single = train_glm(data, TaskType.LOGISTIC_REGRESSION, L2CFG)[0]

    mesh = data_parallel_mesh(8)
    data_sharded = shard_batch(data, mesh)
    fit_sharded = train_glm(data_sharded, TaskType.LOGISTIC_REGRESSION, L2CFG)[0]
    np.testing.assert_allclose(
        fit_sharded.model.coefficients.means,
        fit_single.model.coefficients.means,
        rtol=1e-3,
        atol=1e-4,
    )


def test_sharded_ell_training(rng):
    X, y = _logreg(rng, n=128, d=16, intercept=False)
    X[rng.random(X.shape) < 0.6] = 0.0
    rows, cols = np.nonzero(X)
    ell = from_scipy_like(rows, cols, X[rows, cols], X.shape)
    data = LabeledData.create(ell, jnp.asarray(y))
    fit_single = train_glm(data, TaskType.LOGISTIC_REGRESSION, L2CFG)[0]
    mesh = data_parallel_mesh(8)
    fit_sharded = train_glm(
        shard_batch(data, mesh), TaskType.LOGISTIC_REGRESSION, L2CFG
    )[0]
    np.testing.assert_allclose(
        fit_sharded.model.coefficients.means,
        fit_single.model.coefficients.means,
        rtol=1e-3,
        atol=1e-4,
    )


def test_zero_sweep_weight_disables_l1(rng):
    """regularization_weights=[0.0] with an L1 configuration must NOT apply
    the configuration's own weight (review finding)."""
    X, y = _logreg(rng, n=128, d=6, intercept=False)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    cfg_l1 = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L1),
        regularization_weight=5.0,
    )
    fit_zero = train_glm(
        data, TaskType.LOGISTIC_REGRESSION, cfg_l1, regularization_weights=[0.0]
    )[0]
    fit_plain = train_glm(
        data, TaskType.LOGISTIC_REGRESSION, GlmOptimizationConfiguration()
    )[0]
    np.testing.assert_allclose(
        fit_zero.model.coefficients.means,
        fit_plain.model.coefficients.means,
        rtol=1e-2,
        atol=1e-3,
    )


def test_warm_start_roundtrip_with_normalization(rng):
    """Feeding a returned (original-space) model back as initial_model with
    normalized data must start AT the optimum: 0-2 extra iterations."""
    X, y = _logreg(rng, n=256, d=6)
    data_plain = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    summ = summarize(data_plain)
    norm = build_normalization_context(
        NormalizationType.STANDARDIZATION, summ.mean, summ.variance, summ.max_abs, 5
    )
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y), norm=norm)
    fit1 = train_glm(data, TaskType.LOGISTIC_REGRESSION, L2CFG, intercept_index=5)[0]
    fit2 = train_glm(
        data,
        TaskType.LOGISTIC_REGRESSION,
        L2CFG,
        initial_model=fit1.model,
        intercept_index=5,
    )[0]
    assert int(fit2.result.iterations) <= 2
    np.testing.assert_allclose(
        fit2.model.coefficients.means, fit1.model.coefficients.means, rtol=1e-3, atol=1e-4
    )


def test_variances_transformed_to_original_space(rng):
    """Variances must scale by factor^2 when mapped back (delta method)."""
    X, y = _logreg(rng, n=256, d=4, intercept=False)
    X[:, 0] *= 10.0  # large-std feature: factor ~ 0.1
    data_plain = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    summ = summarize(data_plain)
    norm = build_normalization_context(
        NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
        summ.mean, summ.variance, summ.max_abs, None,
    )
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y), norm=norm)
    fit_n = train_glm(
        data, TaskType.LOGISTIC_REGRESSION, L2CFG, compute_variances=True
    )[0]
    fit_p = train_glm(
        data_plain, TaskType.LOGISTIC_REGRESSION, L2CFG, compute_variances=True
    )[0]
    # original-space variances from both paths should be on the same scale
    ratio = np.asarray(fit_n.model.coefficients.variances) / np.asarray(
        fit_p.model.coefficients.variances
    )
    assert np.all(ratio > 0.2) and np.all(ratio < 5.0), ratio
