"""Optimizer tests, modeled on the reference's OptimizerTest/OptimizerIntegTest
(photon-lib src/test + src/integTest): drive each solver against known
objectives and check convergence invariants, cross-solver agreement, and
vmap batchability (the random-effect execution mode).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.losses import (
    GlmObjective,
    LogisticLoss,
    SquaredLoss,
    make_glm_objective,
)
from photon_ml_tpu.ops import DenseFeatures, LabeledData
from photon_ml_tpu.opt import (
    GlmOptimizationConfiguration,
    OptimizerConfig,
    RegularizationContext,
    lbfgs_solve,
    owlqn_solve,
    solve,
    tron_solve,
)
from photon_ml_tpu.types import ConvergenceReason, RegularizationType


def _linreg_problem(rng, n=64, d=8, noise=0.01):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = X @ w_true + noise * rng.normal(size=n).astype(np.float32)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    return data, w_true


def _logreg_problem(rng, n=256, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 2
    p = 1 / (1 + np.exp(-(X @ w_true)))
    y = (rng.random(n) < p).astype(np.float32)
    data = LabeledData.create(DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y))
    return data, w_true


@pytest.mark.parametrize("solver", [lbfgs_solve, tron_solve])
def test_quadratic_exact_solution(rng, solver):
    """Least squares with tiny L2 has a closed-form optimum; both second-order
    capable solvers must find it."""
    data, w_true = _linreg_problem(rng)
    obj = make_glm_objective(SquaredLoss)
    l2 = jnp.float32(1e-3)
    res = solver(obj, jnp.zeros(8), data, l2)
    X = np.asarray(data.features.matrix)
    y = np.asarray(data.labels)
    w_exact = np.linalg.solve(X.T @ X + 1e-3 * np.eye(8), X.T @ y)
    np.testing.assert_allclose(res.w, w_exact, rtol=1e-3, atol=1e-3)
    assert int(res.reason) != ConvergenceReason.NOT_CONVERGED.value


@pytest.mark.parametrize("solver", [lbfgs_solve, tron_solve])
def test_logistic_converges_and_gradient_small(rng, solver):
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    res = solver(obj, jnp.zeros(6), data, jnp.float32(1.0))
    # gradient at the optimum must be tiny relative to the initial one
    _, g0 = obj.value_and_grad(jnp.zeros(6), data, jnp.float32(1.0))
    assert float(res.grad_norm) < 1e-3 * float(jnp.linalg.norm(g0))


def test_lbfgs_tron_agree(rng):
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    l2 = jnp.float32(0.5)
    r1 = lbfgs_solve(obj, jnp.zeros(6), data, l2)
    r2 = tron_solve(obj, jnp.zeros(6), data, l2)
    np.testing.assert_allclose(r1.w, r2.w, rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(r1.value, r2.value, rtol=1e-4)


def test_monotone_decrease(rng):
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    res = lbfgs_solve(obj, jnp.zeros(6), data, jnp.float32(0.1))
    h = np.asarray(res.value_history)
    h = h[~np.isnan(h)]
    assert len(h) >= 2
    assert np.all(np.diff(h) <= 1e-5), f"objective increased: {h}"


def test_owlqn_produces_sparse_solution(rng):
    """Strong L1 must zero out coefficients; weak L1 must fit well."""
    data, w_true = _linreg_problem(rng, n=128, d=10, noise=0.0)
    obj = make_glm_objective(SquaredLoss)
    strong = owlqn_solve(obj, jnp.zeros(10), data, jnp.float32(0.0), jnp.float32(500.0))
    weak = owlqn_solve(obj, jnp.zeros(10), data, jnp.float32(0.0), jnp.float32(1e-4))
    n_zero_strong = int(jnp.sum(jnp.abs(strong.w) < 1e-8))
    assert n_zero_strong >= 5, f"strong L1 left {10 - n_zero_strong} nonzeros"
    np.testing.assert_allclose(weak.w, w_true, rtol=1e-2, atol=1e-2)


def test_owlqn_matches_lbfgs_when_l1_zero(rng):
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    l2 = jnp.float32(0.5)
    r_owl = owlqn_solve(obj, jnp.zeros(6), data, l2, jnp.float32(0.0))
    r_lb = lbfgs_solve(obj, jnp.zeros(6), data, l2)
    np.testing.assert_allclose(r_owl.value, r_lb.value, rtol=1e-3)


def test_box_constraints_respected(rng):
    data, _ = _linreg_problem(rng)
    cfg = OptimizerConfig.lbfgs(constraint_lower=-0.1, constraint_upper=0.1)
    obj = make_glm_objective(SquaredLoss)
    res = lbfgs_solve(obj, jnp.zeros(8), data, jnp.float32(0.0), cfg)
    assert float(jnp.max(res.w)) <= 0.1 + 1e-6
    assert float(jnp.min(res.w)) >= -0.1 - 1e-6
    # and some coefficient should be AT the boundary (active constraint)
    assert float(jnp.max(jnp.abs(res.w))) > 0.1 - 1e-4


def test_bf16_history_reaches_same_optimum(rng):
    """bfloat16 s/y history (half the dominant memory term of huge-d
    solves, SCALING.md) must land on the same optimum within bf16 noise."""
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    l2 = jnp.float32(0.5)
    f32 = lbfgs_solve(obj, jnp.zeros(6), data, l2)
    cfg = OptimizerConfig.lbfgs(history_dtype="bfloat16")
    bf16 = lbfgs_solve(obj, jnp.zeros(6), data, l2, cfg)
    np.testing.assert_allclose(
        np.asarray(bf16.w), np.asarray(f32.w), rtol=5e-3, atol=5e-3
    )
    owl = owlqn_solve(obj, jnp.zeros(6), data, l2, jnp.float32(0.01), cfg)
    assert np.all(np.isfinite(np.asarray(owl.w)))

    with pytest.raises(ValueError, match="history_dtype"):
        OptimizerConfig.lbfgs(history_dtype="float64")


def test_owlqn_box_constraints(rng):
    """L1 + box compose (reference OWLQN.scala:46 passes the constraint map
    to LBFGS.scala:72's post-step projection): iterates stay in the box,
    some constraint binds, and an inactive box changes nothing."""
    data, _ = _linreg_problem(rng)
    obj = make_glm_objective(SquaredLoss)
    l1 = jnp.float32(0.05)
    cfg = OptimizerConfig.lbfgs(constraint_lower=-0.1, constraint_upper=0.1)
    res = owlqn_solve(obj, jnp.zeros(8), data, jnp.float32(0.0), l1, cfg)
    assert float(jnp.max(res.w)) <= 0.1 + 1e-6
    assert float(jnp.min(res.w)) >= -0.1 - 1e-6
    assert float(jnp.max(jnp.abs(res.w))) > 0.1 - 1e-4  # a bound binds

    wide = OptimizerConfig.lbfgs(constraint_lower=-100.0, constraint_upper=100.0)
    r_wide = owlqn_solve(obj, jnp.zeros(8), data, jnp.float32(0.0), l1, wide)
    r_free = owlqn_solve(obj, jnp.zeros(8), data, jnp.float32(0.0), l1)
    np.testing.assert_allclose(
        np.asarray(r_wide.w), np.asarray(r_free.w), atol=1e-5
    )


def test_vmap_batched_solves(rng):
    """vmap over independent problems == solving each separately — the
    random-effect execution mode (reference RandomEffectCoordinate's
    mapValues local solves)."""
    obj = make_glm_objective(SquaredLoss)
    n_prob, n, d = 5, 32, 4
    Xs = rng.normal(size=(n_prob, n, d)).astype(np.float32)
    ws = rng.normal(size=(n_prob, d)).astype(np.float32)
    ys = np.einsum("pnd,pd->pn", Xs, ws).astype(np.float32)
    datas = LabeledData.create(
        DenseFeatures(matrix=jnp.asarray(Xs)),
        jnp.asarray(ys),
        offsets=jnp.zeros((n_prob, n)),
        weights=jnp.ones((n_prob, n)),
    )
    l2 = jnp.float32(1e-3)
    batched = jax.vmap(lambda dd: lbfgs_solve(obj, jnp.zeros(d), dd, l2))(datas)
    for p in range(n_prob):
        single = lbfgs_solve(
            obj,
            jnp.zeros(d),
            jax.tree.map(lambda a: a[p], datas),
            l2,
        )
        np.testing.assert_allclose(batched.w[p], single.w, rtol=5e-2, atol=5e-3)
        np.testing.assert_allclose(batched.w[p], ws[p], rtol=5e-2, atol=5e-3)


def test_solve_dispatch(rng):
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    cfg_l1 = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.ELASTIC_NET, alpha=0.5),
        regularization_weight=1.0,
    )
    res = solve(obj, jnp.zeros(6), data, cfg_l1)
    assert res.w.shape == (6,)
    cfg_tron = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.tron(),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    res2 = solve(obj, jnp.zeros(6), data, cfg_tron)
    np.testing.assert_allclose(res2.grad_norm, 0.0, atol=5e-2)
    with pytest.raises(ValueError, match="TRON does not support L1"):
        solve(
            obj,
            jnp.zeros(6),
            data,
            GlmOptimizationConfiguration(
                optimizer_config=OptimizerConfig.tron(),
                regularization=RegularizationContext(RegularizationType.L1),
                regularization_weight=1.0,
            ),
        )


def test_warm_start_lambda_sweep_no_recompile(rng):
    """l2_weight is traced: two λ values must hit the same compiled program
    (the reference's warm-start sweep, ModelTraining.scala:160-206)."""
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    jitted = jax.jit(lambda w0, dd, l2: lbfgs_solve(obj, w0, dd, l2))
    r_high = jitted(jnp.zeros(6), data, jnp.float32(100.0))
    r_low = jitted(r_high.w, data, jnp.float32(0.1))
    assert jitted._cache_size() == 1
    assert float(r_low.value) < float(r_high.value)


# ---------------------------------------------------------------------------
# Reference OptimizerIntegTest.scala:120-200: convergence-state invariants
# over 100 random starts on the fake centroid objective (TestObjective.scala:
# f(w) = 0.5*||w - CENTROID||^2, CENTROID = 4.0), vmapped into one batched
# solve per optimizer instead of 100 sequential Spark jobs.
# ---------------------------------------------------------------------------

_CENTROID = 4.0


@pytest.mark.parametrize("name", ["lbfgs", "tron", "owlqn"])
def test_track_coefficients_history(rng, name):
    """OptimizerConfig.track_coefficients records the per-iteration w path
    (reference ModelTracker): last recorded iterate == final w, the path is
    finite up to `iterations`, NaN-padded after, and off by default."""
    data, _ = _logreg_problem(rng)
    obj = make_glm_objective(LogisticLoss)
    l2 = jnp.float32(0.1)
    cfg = OptimizerConfig(max_iterations=40, track_coefficients=True)
    if name == "lbfgs":
        res = lbfgs_solve(obj, jnp.zeros(6), data, l2, cfg)
        res_off = lbfgs_solve(obj, jnp.zeros(6), data, l2)
    elif name == "tron":
        res = tron_solve(obj, jnp.zeros(6), data, l2, cfg)
        res_off = tron_solve(obj, jnp.zeros(6), data, l2)
    else:
        res = owlqn_solve(obj, jnp.zeros(6), data, l2, jnp.float32(0.01), cfg)
        res_off = owlqn_solve(obj, jnp.zeros(6), data, l2, jnp.float32(0.01))
    assert res_off.w_history is None
    assert res.w_history is not None
    hist = np.asarray(res.w_history)
    iters = int(res.iterations)
    assert hist.shape == (41, 6)
    assert np.isfinite(hist[: iters + 1]).all()
    np.testing.assert_allclose(hist[iters], np.asarray(res.w), rtol=1e-6)
    if iters < 40:
        assert np.isnan(hist[iters + 1 :]).all()
    # the recorded start is the initial point
    np.testing.assert_allclose(hist[0], 0.0)


def test_track_models_through_train_glm(rng):
    """train_glm(track_models=True) yields per-iteration models whose last
    entry equals the fit model, mapped back through normalization."""
    from photon_ml_tpu.estimators.model_training import train_glm
    from photon_ml_tpu.normalization import build_normalization_context
    from photon_ml_tpu.stat.summary import summarize
    from photon_ml_tpu.types import NormalizationType, TaskType

    data, _ = _logreg_problem(rng)
    labeled = data
    summary = summarize(labeled)
    norm = build_normalization_context(
        NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
        mean=summary.mean,
        variance=summary.variance,
        max_magnitude=summary.max_abs,
        intercept_index=None,
    )
    labeled = labeled.replace(norm=norm)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig(max_iterations=30),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.1,
    )
    fit = train_glm(labeled, TaskType.LOGISTIC_REGRESSION, cfg,
                    track_models=True)[0]
    assert fit.tracked_models is not None
    assert len(fit.tracked_models) == int(fit.result.iterations) + 1
    np.testing.assert_allclose(
        np.asarray(fit.tracked_models[-1].coefficients.means),
        np.asarray(fit.model.coefficients.means),
        rtol=2e-4, atol=1e-6,
    )


def _centroid_objective():
    def value(w, data, l2):
        d = w - _CENTROID
        return 0.5 * jnp.dot(d, d)

    def value_and_grad(w, data, l2):
        d = w - _CENTROID
        return 0.5 * jnp.dot(d, d), d

    def hessian_vec(w, v, data, l2):
        return v

    def hessian_diag(w, data, l2):
        return jnp.ones_like(w)

    return GlmObjective(
        value=value,
        value_and_grad=value_and_grad,
        hessian_vec=hessian_vec,
        hessian_diag=hessian_diag,
        has_hessian=True,
    )


@pytest.mark.parametrize(
    "name,batched_solver",
    [
        (
            "lbfgs",
            lambda obj, cfg: jax.jit(jax.vmap(
                lambda w0: lbfgs_solve(obj, w0, jnp.zeros(1), jnp.float32(0.0), cfg)
            )),
        ),
        (
            "tron",
            lambda obj, cfg: jax.jit(jax.vmap(
                lambda w0: tron_solve(obj, w0, jnp.zeros(1), jnp.float32(0.0), cfg)
            )),
        ),
        (
            "owlqn",
            lambda obj, cfg: jax.jit(jax.vmap(
                lambda w0: owlqn_solve(
                    obj, w0, jnp.zeros(1), jnp.float32(0.0), jnp.float32(0.0), cfg
                )
            )),
        ),
    ],
)
def test_invariants_100_random_starts(rng, name, batched_solver):
    """Every start must converge to the centroid with a monotone value
    history and a reason consistent with its final state."""
    d, n_starts = 10, 100
    obj = _centroid_objective()
    cfg = (
        OptimizerConfig.tron(tolerance=1e-7, max_iterations=100)
        if name == "tron"
        else OptimizerConfig.lbfgs(tolerance=1e-7, max_iterations=200)
    )
    starts = jnp.asarray(rng.normal(size=(n_starts, d)).astype(np.float32) * 10)
    res = batched_solver(obj, cfg)(starts)

    reasons = np.asarray(res.reason)
    assert np.all(reasons != ConvergenceReason.NOT_CONVERGED.value)
    assert np.all(reasons != ConvergenceReason.MAX_ITERATIONS.value), (
        f"{name}: some starts hit max iterations: "
        f"{np.bincount(reasons, minlength=5)}"
    )
    # expected parameters (reference PARAMETER_TOLERANCE=1e-4, f64; f32 here)
    w = np.asarray(res.w)
    np.testing.assert_allclose(w, _CENTROID, atol=5e-3)

    # reason-consistent final state (OBJECTIVE/GRADIENT_TOLERANCE analogs)
    values = np.asarray(res.value)
    gnorms = np.asarray(res.grad_norm)
    f_conv = reasons == ConvergenceReason.FUNCTION_VALUES_CONVERGED.value
    g_conv = reasons == ConvergenceReason.GRADIENT_CONVERGED.value
    assert np.all(values[f_conv] < 1e-4)
    assert np.all(gnorms[g_conv] < 1e-2)

    # monotone non-increasing value history over the tracked prefix
    hist = np.asarray(res.value_history)  # [starts, max_iter+1], NaN padded
    valid = ~np.isnan(hist)
    diffs = np.diff(hist, axis=1)
    ok = np.isnan(diffs) | (diffs <= 1e-5)
    assert np.all(ok[valid[:, :-1] & valid[:, 1:]]), (
        f"{name}: objective increased somewhere in the tracked history"
    )


@pytest.mark.parametrize("task_name", ["LINEAR_REGRESSION", "LOGISTIC_REGRESSION"])
@pytest.mark.parametrize("solver_name", ["lbfgs", "tron", "owlqn"])
def test_solvers_survive_ill_conditioned_data(task_name, solver_name):
    """Reference OptimizerIntegTest drives each optimizer over deliberately
    ill-conditioned ("outlier") draws: the solve must stay finite and end
    with a valid convergence reason — never NaN coefficients or a crash."""
    from photon_ml_tpu.losses import make_glm_objective
    from photon_ml_tpu.losses.pointwise import loss_for_task
    from photon_ml_tpu.testing import draw_sample
    from photon_ml_tpu.types import TaskType

    task = TaskType[task_name]
    X, y, _ = draw_sample(task, n=256, d=8, regime="outlier", seed=11)
    data = LabeledData.create(
        DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y)
    )
    obj = make_glm_objective(loss_for_task(task))
    cfg = (
        OptimizerConfig.tron(max_iterations=20)
        if solver_name == "tron"
        else OptimizerConfig.lbfgs(max_iterations=50)
    )
    l2 = jnp.float32(1.0)
    if solver_name == "lbfgs":
        res = lbfgs_solve(obj, jnp.zeros(8), data, l2, cfg)
    elif solver_name == "tron":
        res = tron_solve(obj, jnp.zeros(8), data, l2, cfg)
    else:
        res = owlqn_solve(obj, jnp.zeros(8), data, l2, jnp.float32(0.1), cfg)
    w = np.asarray(res.w)
    assert np.all(np.isfinite(w)), f"{solver_name} produced non-finite w"
    assert np.isfinite(float(res.value))
    assert int(res.reason) in {r.value for r in ConvergenceReason}
    # the solve must improve on w=0
    f0 = float(obj.value(jnp.zeros(8), data, l2))
    assert float(res.value) <= f0 + 1e-6


@pytest.mark.parametrize("name", ["lbfgs", "owlqn", "tron"])
def test_chunked_resume_matches_oneshot(rng, name):
    """init -> chunk(K) ... -> finalize must follow the EXACT trajectory of
    the uninterrupted solve: the chunk boundary only caps the while_loop's
    trip count, it never perturbs the carried state (L-BFGS history ring,
    TRON trust radius, OWL-QN pseudo-gradient bookkeeping)."""
    from photon_ml_tpu.opt import solve, solve_chunk, solve_finalize, solve_init

    if name == "tron":
        data, _ = _linreg_problem(rng)
        obj = make_glm_objective(SquaredLoss)
        configuration = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.tron(),
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.1,
        )
    else:
        data, _ = _logreg_problem(rng)
        obj = make_glm_objective(LogisticLoss)
        reg = RegularizationType.L1 if name == "owlqn" else RegularizationType.L2
        configuration = GlmOptimizationConfiguration(
            regularization=RegularizationContext(reg),
            regularization_weight=0.01 if name == "owlqn" else 0.1,
        )
    d = data.features.matrix.shape[1]
    w0 = jnp.zeros(d)

    ref = solve(obj, w0, data, configuration)
    state = solve_init(obj, w0, data, configuration)
    for _ in range(40):  # 40 chunks x 3 iters covers max_iterations=100
        state = solve_chunk(obj, state, data, configuration, num_iters=3)
    res = solve_finalize(state, configuration)

    np.testing.assert_allclose(np.asarray(res.w), np.asarray(ref.w),
                               rtol=0, atol=1e-6)
    assert int(res.iterations) == int(ref.iterations)
    assert int(res.reason) == int(ref.reason)
