"""Tests for samplers, validators, events and trackers.

Mirrors reference unit tests: DownSamplerTest, DataValidators checks,
OptimizationStatesTracker/RandomEffectOptimizationTracker summaries.
"""

import numpy as np
import pytest

from photon_ml_tpu.data.validators import (
    DataValidationError,
    DataValidationType,
    validate_labeled_data,
)
from photon_ml_tpu.event import (
    EventEmitter,
    EventListener,
    PhotonOptimizationLogEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.sampler import (
    BinaryClassificationDownSampler,
    DefaultDownSampler,
    down_sampler_for,
)
from photon_ml_tpu.types import ConvergenceReason, TaskType


def _data(labels, weights=None, features=None, offsets=None):
    n = len(labels)
    x = np.ones((n, 2), np.float32) if features is None else np.asarray(features)
    return LabeledData.create(
        features=DenseFeatures(matrix=x),
        labels=np.asarray(labels, np.float32),
        weights=None if weights is None else np.asarray(weights, np.float32),
        offsets=None if offsets is None else np.asarray(offsets, np.float32),
    )


class TestDownSamplers:
    def test_default_preserves_expected_total_weight(self):
        labels = np.zeros(20000, np.float32)
        weights = np.ones(20000, np.float32)
        out = DefaultDownSampler(0.25).sample_weights(labels, weights, seed=1)
        kept = out > 0
        # survivors are re-scaled by 1/rate -> expected total weight unchanged
        assert np.isclose(kept.mean(), 0.25, atol=0.02)
        assert np.isclose(out.sum(), weights.sum(), rtol=0.05)
        assert np.allclose(out[kept], 4.0)

    def test_binary_keeps_all_positives(self):
        labels = np.array([1, 1, 0, 0, 0, 0, 0, 0] * 1000, np.float32)
        weights = np.full(labels.shape, 2.0, np.float32)
        out = BinaryClassificationDownSampler(0.5).sample_weights(
            labels, weights, seed=3
        )
        pos = labels >= 0.5
        assert np.allclose(out[pos], 2.0)  # positives untouched
        neg_kept = out[~pos] > 0
        assert np.isclose(neg_kept.mean(), 0.5, atol=0.03)
        assert np.allclose(out[~pos][neg_kept], 4.0)  # 2.0 / 0.5

    def test_factory_matches_task(self):
        assert isinstance(
            down_sampler_for(TaskType.LOGISTIC_REGRESSION, 0.5),
            BinaryClassificationDownSampler,
        )
        assert isinstance(
            down_sampler_for(TaskType.LINEAR_REGRESSION, 0.5), DefaultDownSampler
        )

    def test_invalid_rate_rejected(self):
        with pytest.raises(ValueError):
            DefaultDownSampler(1.0)
        with pytest.raises(ValueError):
            BinaryClassificationDownSampler(0.0)


class TestValidators:
    def test_clean_data_passes(self):
        validate_labeled_data(_data([0, 1, 0]), TaskType.LOGISTIC_REGRESSION)

    def test_nan_feature_rejected(self):
        d = _data([0, 1], features=np.array([[1, np.nan], [0, 1]], np.float32))
        with pytest.raises(DataValidationError, match="features contain NaN"):
            validate_labeled_data(d, TaskType.LOGISTIC_REGRESSION)

    def test_nonbinary_label_rejected_for_logistic(self):
        with pytest.raises(DataValidationError, match="must be 0 or 1"):
            validate_labeled_data(_data([0, 2]), TaskType.LOGISTIC_REGRESSION)

    def test_negative_label_rejected_for_poisson(self):
        with pytest.raises(DataValidationError, match="non-negative"):
            validate_labeled_data(_data([1, -1]), TaskType.POISSON_REGRESSION)

    def test_negative_weight_rejected(self):
        with pytest.raises(DataValidationError, match="negative"):
            validate_labeled_data(
                _data([0, 1], weights=[1, -1]), TaskType.LOGISTIC_REGRESSION
            )

    def test_multiple_failures_all_reported(self):
        d = _data(
            [5, 0],
            weights=[1, -1],
            features=np.array([[np.inf, 0], [0, 1]], np.float32),
        )
        with pytest.raises(DataValidationError) as err:
            validate_labeled_data(d, TaskType.LOGISTIC_REGRESSION)
        assert len(err.value.failures) == 3

    def test_padding_rows_exempt_from_label_checks(self):
        # weight-0 rows are padding; a junk label there must not fail
        validate_labeled_data(
            _data([0, 7], weights=[1, 0]), TaskType.LOGISTIC_REGRESSION
        )

    def test_disabled_mode_skips(self):
        validate_labeled_data(
            _data([0, 9]),
            TaskType.LOGISTIC_REGRESSION,
            mode=DataValidationType.VALIDATE_DISABLED,
        )

    def test_linear_regression_allows_any_finite_label(self):
        validate_labeled_data(_data([-3.5, 7.2]), TaskType.LINEAR_REGRESSION)


class _Recorder(EventListener):
    def __init__(self):
        self.events = []

    def on_event(self, event):
        self.events.append(event)


class _Exploder(EventListener):
    def on_event(self, event):
        raise RuntimeError("boom")


class TestEvents:
    def test_emit_reaches_all_listeners(self):
        em = EventEmitter()
        a, b = _Recorder(), _Recorder()
        em.register_listener(a)
        em.register_listener(b)
        ev = TrainingStartEvent(task="logistic_regression")
        em.send_event(ev)
        assert a.events == [ev] and b.events == [ev]

    def test_listener_exception_isolated(self):
        em = EventEmitter()
        rec = _Recorder()
        em.register_listener(_Exploder())
        em.register_listener(rec)
        em.send_event(
            PhotonOptimizationLogEvent(
                coordinate_id="fe",
                regularization_weight=1.0,
                objective_value=0.5,
                iterations=7,
                convergence_reason="FUNCTION_VALUES_CONVERGED",
            )
        )
        assert len(rec.events) == 1

    def test_register_by_class_name(self):
        em = EventEmitter()
        em.register_listener_class(f"{__name__}._Recorder")
        em.send_event(TrainingStartEvent(task="t"))
        assert len(em._listeners[0].events) == 1


class TestTrackers:
    def test_states_tracker_from_solve(self):
        import jax.numpy as jnp

        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
        from photon_ml_tpu.opt.solve import solve
        from photon_ml_tpu.opt.tracking import OptimizationStatesTracker

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x @ np.array([1.0, -1, 0.5, 0]) > 0).astype(np.float32)
        data = _data(y, features=x)
        obj = make_glm_objective(LogisticLoss)
        res = solve(
            obj,
            jnp.zeros(4),
            data,
            GlmOptimizationConfiguration(regularization_weight=0.1),
        )
        tr = OptimizationStatesTracker.from_result(res)
        assert tr.converged
        assert tr.values.shape[0] == tr.iterations + 1
        assert tr.values[-1] < tr.values[0]
        assert "reason=" in tr.to_summary_string()

    def test_random_effect_tracker_aggregates(self):
        import jax.numpy as jnp

        from photon_ml_tpu.opt.state import SolveResult
        from photon_ml_tpu.opt.tracking import RandomEffectOptimizationTracker

        def fake(reasons, iters):
            e = len(reasons)
            return SolveResult(
                w=jnp.zeros((e, 2)),
                value=jnp.ones(e),
                grad_norm=jnp.zeros(e),
                iterations=jnp.asarray(iters, jnp.int32),
                reason=jnp.asarray(reasons, jnp.int32),
                value_history=jnp.zeros((e, 3)),
            )

        tr = RandomEffectOptimizationTracker.from_results(
            [fake([2, 2, 1], [3, 5, 100]), fake([3], [7])]
        )
        assert tr.num_entities == 4
        assert tr.reason_counts[ConvergenceReason.FUNCTION_VALUES_CONVERGED] == 2
        assert tr.reason_counts[ConvergenceReason.MAX_ITERATIONS] == 1
        assert tr.reason_counts[ConvergenceReason.GRADIENT_CONVERGED] == 1
        assert tr.iteration_stats["max"] == 100
        assert "entities" in tr.to_summary_string()
