"""Convergence-adaptive random-effect solving (tier-1 parity gate).

The adaptive driver (estimators/random_effect.py) replaces the one-shot
lockstep ``vmap(solve)`` per bucket with chunked solver rounds + lane
compaction + pow2 re-dispatch. These tests pin down the contract:

- coefficients match the one-shot path to <=1e-5 for LBFGS / OWL-QN / TRON,
  including warm starts and proj_valid padding (the chunked while_loop
  follows the exact same per-lane trajectory as the uninterrupted loop);
- on a skewed-convergence warm-started workload the driver cuts executed
  lane-iterations >=2x vs lockstep (asserted from SolverStats);
- compiled-program count is bounded by the pow2 ladder (asserted via the
  module's jit-trace counter) and same-shape re-runs add zero retraces;
- SolverStats flows out through coordinate descent as SolverStatsEvent.

Deliberately NOT marked slow: this is the regression gate for the adaptive
path, so it runs in the fast lane.
"""

import numpy as np
import pytest

from photon_ml_tpu.algorithm.coordinate import RandomEffectCoordinate
from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_ml_tpu.data import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.estimators.random_effect import (
    solver_trace_counts,
    train_random_effects,
)
from photon_ml_tpu.event import EventEmitter, EventListener, SolverStatsEvent
from photon_ml_tpu.opt import (
    AdaptiveSolveConfig,
    GlmOptimizationConfiguration,
    OptimizerConfig,
    RegularizationContext,
)
from photon_ml_tpu.types import RegularizationType, TaskType

ADAPTIVE = AdaptiveSolveConfig(enabled=True, chunk_iters=8, min_lanes=8)
ONESHOT = AdaptiveSolveConfig(enabled=False)


def _cfg(optimizer="lbfgs", reg=RegularizationType.L2, weight=0.1,
         adaptive=ADAPTIVE):
    opt = (OptimizerConfig.tron() if optimizer == "tron"
           else OptimizerConfig.lbfgs())
    return GlmOptimizationConfiguration(
        optimizer_config=opt,
        regularization=RegularizationContext(reg),
        regularization_weight=weight,
        adaptive=adaptive,
    )


def _sparse_problem(rng, n_entities=20, samples=(5, 40), global_dim=30,
                    logistic=False):
    """Entities observe different slices of the global space, so the bucket
    carries proj_valid padding; sample counts are ragged, so cost-sorted
    packing and lane compaction both engage."""
    rows, cols, vals, ids, labels = [], [], [], [], []
    r = 0
    for e in range(n_entities):
        eid = f"ent{e:03d}"
        n_e = int(rng.integers(*samples))
        feats = np.sort(
            rng.choice(global_dim, size=int(rng.integers(3, 8)), replace=False)
        )
        w_e = rng.normal(size=len(feats)).astype(np.float32)
        for _ in range(n_e):
            x = rng.normal(size=len(feats)).astype(np.float32)
            z = float(x @ w_e)
            y = (1.0 if rng.random() < 1.0 / (1.0 + np.exp(-z)) else 0.0) \
                if logistic else z
            for c, v in zip(feats, x):
                rows.append(r)
                cols.append(c)
                vals.append(float(v))
            ids.append(eid)
            labels.append(y)
            r += 1
    return ids, np.array(rows), np.array(cols), np.array(vals, np.float32), \
        np.array(labels, np.float32), global_dim


def _build(ids, rows, cols, vals, gdim, labels, num_buckets=1):
    cfg = RandomEffectDataConfiguration(
        random_effect_type="ent", num_buckets=num_buckets
    )
    return build_random_effect_dataset(ids, rows, cols, vals, gdim, labels, cfg)


def _skewed_warm_pair(rng, n_entities=64, n_hard=6, d=6):
    """The nearline re-solve profile: warm model from batch A; batch B keeps
    the easy entities' labels (lanes converge in a couple of iterations) but
    gives the hard tail fresh near-separable labels (lanes run long)."""
    rows, cols, vals, ids = [], [], [], []
    labels_a, labels_b = [], []
    r = 0
    for e in range(n_entities):
        eid = f"m{e:05d}"
        hard = e < n_hard
        n_e = 500 if hard else int(rng.integers(5, 30))
        w_e = rng.normal(size=d).astype(np.float32) * 0.5
        w_fresh = rng.normal(size=d).astype(np.float32) * 10.0
        for _ in range(n_e):
            x = rng.normal(size=d).astype(np.float32)
            z = float(x @ w_e)
            ya = 1.0 if rng.random() < 1.0 / (1.0 + np.exp(-z)) else 0.0
            yb = ya if not hard else (1.0 if float(x @ w_fresh) > 0 else 0.0)
            for c in range(d):
                rows.append(r)
                cols.append(c)
                vals.append(float(x[c]))
            ids.append(eid)
            labels_a.append(ya)
            labels_b.append(yb)
            r += 1
    rows, cols = np.array(rows), np.array(cols)
    vals = np.array(vals, np.float32)
    ds_a = _build(ids, rows, cols, vals, d, np.array(labels_a, np.float32))
    ds_b = _build(ids, rows, cols, vals, d, np.array(labels_b, np.float32))
    return ds_a, ds_b


def _rows(model):
    return {str(eid): coefs for eid, coefs in model.items()}


def _assert_models_close(m_a, m_b, tol=1e-5):
    ra, rb = _rows(m_a), _rows(m_b)
    assert set(ra) == set(rb)
    for eid in ra:
        keys = set(ra[eid]) | set(rb[eid])
        for k in keys:
            assert abs(ra[eid].get(k, 0.0) - rb[eid].get(k, 0.0)) <= tol, (
                f"entity {eid} coef {k}: {ra[eid].get(k)} vs {rb[eid].get(k)}"
            )


@pytest.mark.parametrize(
    "optimizer,reg,task,logistic",
    [
        ("lbfgs", RegularizationType.L2, TaskType.LOGISTIC_REGRESSION, True),
        ("lbfgs", RegularizationType.L1, TaskType.LOGISTIC_REGRESSION, True),
        ("tron", RegularizationType.L2, TaskType.LINEAR_REGRESSION, False),
    ],
    ids=["lbfgs", "owlqn", "tron"],
)
def test_adaptive_matches_oneshot(rng, optimizer, reg, task, logistic):
    ids, rows, cols, vals, labels, gdim = _sparse_problem(rng, logistic=logistic)
    ds = _build(ids, rows, cols, vals, gdim, labels)
    weight = 0.01 if reg is RegularizationType.L1 else 0.1
    stats = []
    m_ad, res_ad = train_random_effects(
        ds, task, _cfg(optimizer, reg, weight, ADAPTIVE), stats_out=stats
    )
    m_os, res_os = train_random_effects(
        ds, task, _cfg(optimizer, reg, weight, ONESHOT)
    )
    _assert_models_close(m_ad, m_os)
    # the chunked loop follows the identical per-lane trajectory, so even
    # the iteration counts agree
    for a, b in zip(res_ad, res_os):
        np.testing.assert_array_equal(
            np.asarray(a.iterations), np.asarray(b.iterations)
        )
    assert stats and stats[0].rounds >= 1
    assert stats[0].converged == stats[0].num_entities


def test_adaptive_matches_oneshot_warm_start_and_variances(rng):
    ds_a, ds_b = _skewed_warm_pair(rng, n_entities=24, n_hard=3)
    cfg_os = _cfg("lbfgs", weight=1e-6, adaptive=ONESHOT)
    warm, _ = train_random_effects(
        ds_a, TaskType.LOGISTIC_REGRESSION, cfg_os
    )
    kw = dict(initial_model=warm, compute_variances=True)
    m_ad, _ = train_random_effects(
        ds_b, TaskType.LOGISTIC_REGRESSION,
        _cfg("lbfgs", weight=1e-6, adaptive=ADAPTIVE), **kw
    )
    m_os, _ = train_random_effects(
        ds_b, TaskType.LOGISTIC_REGRESSION, cfg_os, **kw
    )
    _assert_models_close(m_ad, m_os)


def test_lane_iteration_savings_at_least_2x(rng):
    """ISSUE acceptance: on the skewed-convergence warm-started workload the
    adaptive driver must cut executed lane-iterations >=2x vs lockstep."""
    ds_a, ds_b = _skewed_warm_pair(rng)
    cfg_os = _cfg("lbfgs", weight=1e-6, adaptive=ONESHOT)
    warm, _ = train_random_effects(ds_a, TaskType.LOGISTIC_REGRESSION, cfg_os)
    stats = []
    train_random_effects(
        ds_b, TaskType.LOGISTIC_REGRESSION,
        _cfg("lbfgs", weight=1e-6, adaptive=ADAPTIVE),
        initial_model=warm, stats_out=stats,
    )
    assert len(stats) == 1
    s = stats[0]
    assert s.converged == s.num_entities
    assert s.executed_lane_iterations > 0
    assert s.lane_iteration_savings >= 2.0, s.to_summary_string()
    assert s.rounds >= 2  # savings must come from compaction, not luck


def test_pow2_ladder_bounds_recompiles(rng):
    ids, rows, cols, vals, labels, gdim = _sparse_problem(
        rng, n_entities=24, logistic=True
    )
    ds1 = _build(ids, rows, cols, vals, gdim, labels)
    cfg = _cfg("lbfgs", weight=0.1, adaptive=ADAPTIVE)
    before = dict(solver_trace_counts())
    stats1 = []
    train_random_effects(
        ds1, TaskType.LOGISTIC_REGRESSION, cfg, stats_out=stats1
    )
    after = dict(solver_trace_counts())
    key = ("re_chunk", "lbfgs")
    delta1 = after.get(key, 0) - before.get(key, 0)

    s = stats1[0]
    widths = list(s.dispatch_widths)
    assert widths[0] == s.num_entities
    for w in widths[1:]:
        assert w & (w - 1) == 0, f"non-pow2 re-dispatch width {w}"
        assert w >= ADAPTIVE.min_lanes
    assert widths == sorted(widths, reverse=True)
    # ladder bound: the initial width plus at most one program per pow2
    # step between next_pow2(E) and min_lanes
    e_pow2 = 1 << (s.num_entities - 1).bit_length()
    ladder = 1 + max(0, e_pow2.bit_length() - ADAPTIVE.min_lanes.bit_length())
    assert delta1 <= ladder, (delta1, ladder, widths)
    assert s.chunk_retraces == delta1

    # same bucket shapes, different labels: every program is cache-hit
    labels2 = labels[::-1].copy()
    ds2 = _build(ids, rows, cols, vals, gdim, labels2)
    mid = dict(solver_trace_counts())
    stats2 = []
    train_random_effects(
        ds2, TaskType.LOGISTIC_REGRESSION, cfg, stats_out=stats2
    )
    end = dict(solver_trace_counts())
    assert end.get(key, 0) == mid.get(key, 0), "same-shape re-run retraced"
    assert stats2[0].chunk_retraces == 0


def test_small_buckets_fall_back_to_oneshot(rng):
    """Savings come only from compaction; at E <= min_lanes there is nothing
    to compact, so the driver must use the fused one-shot program."""
    ids, rows, cols, vals, labels, gdim = _sparse_problem(
        rng, n_entities=6, logistic=True
    )
    ds = _build(ids, rows, cols, vals, gdim, labels)
    stats = []
    train_random_effects(
        ds, TaskType.LOGISTIC_REGRESSION,
        _cfg("lbfgs", weight=0.1, adaptive=ADAPTIVE), stats_out=stats
    )
    assert stats[0].rounds == 1
    assert stats[0].dispatch_widths == (stats[0].num_entities,)
    assert stats[0].chunk_retraces == 0


class _Capture(EventListener):
    def __init__(self):
        self.events = []

    def on_event(self, event):
        self.events.append(event)


def test_solver_stats_event_emitted_from_cd(rng):
    ids, rows, cols, vals, labels, gdim = _sparse_problem(
        rng, n_entities=16, logistic=True
    )
    ds = _build(ids, rows, cols, vals, gdim, labels)
    n_rows = len(ids)
    coord = RandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        configuration=_cfg("lbfgs", weight=0.1, adaptive=ADAPTIVE),
        base_offsets=np.zeros(n_rows, dtype=np.float32),
    )
    emitter = EventEmitter()
    cap = _Capture()
    emitter.register_listener(cap)
    cd = CoordinateDescent({"per-ent": coord}, num_rows=n_rows, emitter=emitter)
    cd.run(1)
    ev = [e for e in cap.events if isinstance(e, SolverStatsEvent)]
    assert ev, "no SolverStatsEvent reached the listener"
    e = ev[0]
    assert e.coordinate_id == "per-ent"
    assert e.num_entities == 16
    assert e.executed_lane_iterations > 0
    assert e.lockstep_lane_iterations >= e.executed_lane_iterations
    assert 0.0 <= e.wasted_lane_fraction < 1.0
    assert len(e.dispatch_widths) == e.rounds
