"""Fused Benes execution (ops/fused_perm.py) vs the stage-by-stage engine.

Ground truth is dense numpy algebra on the same COO triplets; the fused
Pallas kernels run through the interpreter on CPU (the same 8-virtual-device
harness as everything else), exercising descend/base/ascend tiles and all
four prologue/epilogue fusions.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops import fused_perm
from photon_ml_tpu.ops.fused_perm import (
    Broadcast,
    FusedBenesFeatures,
    MulBroadcast,
    MulReduce,
    Reduce,
    from_coo,
    fused_execute,
    parse_plan,
    unfused_execute,
)


@pytest.fixture
def interpret_kernels():
    old = fused_perm._INTERPRET
    fused_perm._INTERPRET = True
    yield
    fused_perm._INTERPRET = old


def _random_coo(rng, n, d, nnz):
    rows = rng.integers(0, n, nnz)
    cols = rng.integers(0, d, nnz)
    vals = rng.standard_normal(nnz).astype(np.float32)
    dense = np.zeros((n, d), dtype=np.float32)
    np.add.at(dense, (rows, cols), vals)
    return rows, cols, vals, dense


def _check_against_dense(feats, dense, rng, atol=1e-4, rtol=1e-7):
    n, d = dense.shape
    w = rng.standard_normal(d).astype(np.float32)
    c = rng.standard_normal(n).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(feats.matvec(jnp.asarray(w))), dense @ w, atol=atol, rtol=rtol
    )
    np.testing.assert_allclose(
        np.asarray(feats.rmatvec(jnp.asarray(c))), dense.T @ c, atol=atol,
        rtol=rtol,
    )
    np.testing.assert_allclose(
        np.asarray(feats.rmatvec_sq(jnp.asarray(c))), (dense * dense).T @ c,
        atol=atol, rtol=rtol,
    )
    np.testing.assert_allclose(
        np.asarray(feats.row_norms_sq()), (dense * dense).sum(1), atol=atol,
        rtol=rtol,
    )


class TestTileCap:
    """PHOTON_FUSED_TILE_U raises the kernel block height (the dispatch-
    overhead A/B knob for the hardware session); the descend/ascend tiles
    must stay exact wherever the raised u actually binds. from_coo shapes
    below the 128^3 ladder step always have R1 <= 8 (a cap never binds
    there), so the u-sensitive tiling is driven at the kernel level with
    shapes where R1 = 16/64."""

    @pytest.mark.parametrize("cap,B,R", [("32", 2, 2048), ("64", 1, 8192)])
    def test_descend_ascend_roundtrip_at_raised_u(
        self, rng, interpret_kernels, monkeypatch, cap, B, R
    ):
        import jax.numpy as jnp

        monkeypatch.setenv("PHOTON_FUSED_TILE_U", cap)
        R1 = R // 128
        u = fused_perm._tile_rows(R1)
        assert u > 8, (cap, R1, u)  # the raised cap must actually bind
        S = B * R * 128
        x = rng.standard_normal(S).astype(np.float32)
        # identity lane shuffle: the kernel's output is then exactly the
        # documented enter relayout (view [B,R,128], swap last two axes)
        ident = np.tile(np.arange(128, dtype=np.int8), (B * R, 1))
        v3 = fused_perm._descend_call(
            jnp.asarray(x).reshape(B * R, 128), jnp.asarray(ident),
            B, R, pro=None, interpret=True,
        )
        got = np.asarray(v3).reshape(B * 128 * R1, 128)
        expected = x.reshape(B, R, 128).transpose(0, 2, 1).reshape(
            B * 128 * R1, 128
        )
        np.testing.assert_array_equal(got, expected)
        # ascend with the identity shuffle inverts the relayout exactly
        back = fused_perm._ascend_call(
            v3.reshape(B * 128, R1, 128), jnp.asarray(ident),
            B, R, epi=None, interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(back).reshape(-1), x
        )

    def test_full_engine_exact_with_cap_set(self, rng, interpret_kernels,
                                            monkeypatch):
        # end-to-end guard at from_coo scale (R1 <= 8 here, so this checks
        # the cap is a safe no-op on small plans + the base-block scaling)
        monkeypatch.setenv("PHOTON_FUSED_TILE_U", "64")
        n, d, nnz = 4096, 512, 24000
        rows, cols, vals, dense = _random_coo(rng, n, d, nnz)
        feats = from_coo(rows, cols, vals, (n, d), max_hot_cols=0,
                         plan_cache="")
        _check_against_dense(feats, dense, rng)

    def test_tile_rows_growth(self, monkeypatch):
        monkeypatch.setenv("PHOTON_FUSED_TILE_U", "64")
        assert fused_perm._tile_rows(8) == 8
        assert fused_perm._tile_rows(16) == 16
        assert fused_perm._tile_rows(128) == 64
        assert fused_perm._tile_rows(4) == 4  # below-8 plans keep u = R1
        monkeypatch.delenv("PHOTON_FUSED_TILE_U")
        assert fused_perm._tile_rows(128) == 8  # default unchanged

    def test_malformed_cap_falls_back(self, monkeypatch):
        monkeypatch.setenv("PHOTON_FUSED_TILE_U", "not-a-number")
        assert fused_perm._tile_cap() == 8
        monkeypatch.setenv("PHOTON_FUSED_TILE_U", "12")  # not a power of two
        assert fused_perm._tile_cap() == 8


class TestUnfusedFallback:
    """CPU default path (pallas unavailable): unfused XLA execution."""

    def test_matches_dense(self, rng):
        rows, cols, vals, dense = _random_coo(rng, n=64, d=40, nnz=500)
        feats = from_coo(rows, cols, vals, (64, 40), max_hot_cols=0)
        assert not feats._fused_ok() or fused_perm._INTERPRET is False
        _check_against_dense(feats, dense, rng)

    def test_hot_split(self, rng):
        rows, cols, vals, dense = _random_coo(rng, n=128, d=30, nnz=600)
        # every row touches column 0: a hot (intercept-like) column
        rows = np.concatenate([rows, np.arange(128)])
        cols = np.concatenate([cols, np.zeros(128, dtype=cols.dtype)])
        ones = np.ones(128, dtype=np.float32)
        vals = np.concatenate([vals, ones])
        np.add.at(dense, (np.arange(128), 0), ones)
        feats = from_coo(rows, cols, vals, (128, 30), hot_col_threshold=100)
        assert feats.hot_matrix is not None
        _check_against_dense(feats, dense, rng)

    def test_kp_above_128(self, rng):
        # one column with degree > 128 and the hot split disabled: KP = 512
        # (kp_cap=None + col_split=1 keep the big slot group this test
        # exercises; the auto layout would legitimately spill/split instead)
        n, d = 300, 12
        rows = np.arange(n)
        cols = np.full(n, 3)
        vals = rng.standard_normal(n).astype(np.float32)
        dense = np.zeros((n, d), dtype=np.float32)
        dense[rows, cols] = vals
        feats = from_coo(rows, cols, vals, (n, d), max_hot_cols=0,
                         kp_cap=None, col_split=1)
        assert feats.csc_k == 512
        _check_against_dense(feats, dense, rng)

    def test_kp_above_128_auto_layout_stays_exact(self, rng):
        # same matrix with the default auto layout: the heavy column spills
        # and/or the columns split, and results stay exact
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        n, d = 300, 12
        rows = np.arange(n)
        cols = np.full(n, 3)
        vals = rng.standard_normal(n).astype(np.float32)
        dense = np.zeros((n, d), dtype=np.float32)
        dense[rows, cols] = vals
        feats = from_coo(rows, cols, vals, (n, d), max_hot_cols=0)
        assert (
            isinstance(feats, ColumnSplitFeatures)
            or feats.spill_rows is not None
        )
        _check_against_dense(feats, dense, rng)

    def test_empty(self):
        feats = from_coo([], [], [], (8, 8), max_hot_cols=0)
        z = np.asarray(feats.matvec(jnp.ones(8, jnp.float32)))
        np.testing.assert_allclose(z, np.zeros(8))

    def test_powers_of_two_groups(self, rng):
        rows, cols, vals, _ = _random_coo(rng, n=64, d=40, nnz=500)
        feats = from_coo(rows, cols, vals, (64, 40), max_hot_cols=0)
        assert feats.ell_k & (feats.ell_k - 1) == 0
        assert feats.csc_k & (feats.csc_k - 1) == 0


class TestFusedKernels:
    """Pallas kernels through the interpreter; sizes force >=1 recursion."""

    def test_single_level_all_maps(self, rng, interpret_kernels):
        # S >= 128^2 so the plan has exactly one descend/ascend level
        n, d = 1024, 600
        rows, cols, vals, dense = _random_coo(rng, n, d, 6000)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        assert len(parse_plan(feats.plan).descents) >= 1
        assert feats._fused_ok()
        _check_against_dense(feats, dense, rng)

    def test_single_level_hot_split(self, rng, interpret_kernels):
        n, d = 2048, 300
        rows, cols, vals, dense = _random_coo(rng, n, d, 8000)
        rows = np.concatenate([rows, np.arange(n)])
        cols = np.concatenate([cols, np.zeros(n, dtype=cols.dtype)])
        ones = np.ones(n, dtype=np.float32)
        vals = np.concatenate([vals, ones])
        np.add.at(dense, (np.arange(n), 0), ones)
        feats = from_coo(rows, cols, vals, (n, d), hot_col_threshold=n // 2)
        assert feats.hot_matrix is not None
        _check_against_dense(feats, dense, rng)

    @pytest.mark.parametrize("c", [2, 4, 8])
    def test_sublane_base_rows(self, rng, interpret_kernels, c):
        # S = c*128^2 makes the innermost base kernel's sublane stage use
        # rows=c (the vectorized per-lane row movement), not the rows=1
        # identity the other sizes hit
        n, d = 512, 300
        rows, cols, vals, dense = _random_coo(rng, n, d, 4000)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0,
            size_floor=c * 128 * 128,
        )
        parsed = parse_plan(feats.plan)
        assert parsed.base[2] == c
        _check_against_dense(feats, dense, rng)

    def test_two_level_plan(self, rng, interpret_kernels):
        # size_floor pushes S to 128^3: two descents, sublane base, two ascents
        n, d = 512, 256
        rows, cols, vals, dense = _random_coo(rng, n, d, 3000)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 ** 3
        )
        assert len(parse_plan(feats.plan).descents) == 2
        _check_against_dense(feats, dense, rng)

    def test_kp_above_128_fused(self, rng, interpret_kernels):
        n, d = 200, 64
        extra_rows = np.arange(n)
        extra_cols = np.full(n, 5)
        rows, cols, vals, dense = _random_coo(rng, n, d, 1500)
        ev = rng.standard_normal(n).astype(np.float32)
        np.add.at(dense, (extra_rows, extra_cols), ev)
        rows = np.concatenate([rows, extra_rows])
        cols = np.concatenate([cols, extra_cols])
        vals = np.concatenate([vals, ev])
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        assert feats.csc_k >= 256
        _check_against_dense(feats, dense, rng)

    def test_k_above_128_fused(self, rng, interpret_kernels):
        # one row with >128 nnz and no hot split: K = 256 exercises the
        # group>LANES branches of MulBroadcast (rmatvec prologue) and
        # MulReduce (matvec epilogue)
        n, d = 64, 256
        rows, cols, vals, dense = _random_coo(rng, n, d, 800)
        extra_cols = rng.permutation(d)[:200]
        extra_rows = np.full(200, 7)
        ev = rng.standard_normal(200).astype(np.float32)
        np.add.at(dense, (extra_rows, extra_cols), ev)
        rows = np.concatenate([rows, extra_rows])
        cols = np.concatenate([cols, extra_cols])
        vals = np.concatenate([vals, ev])
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        assert feats.ell_k >= 256
        _check_against_dense(feats, dense, rng)

    def test_fused_equals_unfused_execute(self, rng, interpret_kernels):
        n, d = 512, 512
        rows, cols, vals, _ = _random_coo(rng, n, d, 4000)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        S, K, KP = feats.size, feats.ell_k, feats.csc_k
        w = jnp.asarray(rng.standard_normal(S // KP).astype(np.float32))
        c = jnp.asarray(rng.standard_normal(S // K).astype(np.float32))
        for dplan, pro, epi in [
            (feats.plan_inv, Broadcast(w, KP), MulReduce(feats.ell_flat, K)),
            (feats.plan, MulBroadcast(feats.ell_flat, c, K), Reduce(KP)),
            (feats.plan, MulBroadcast(feats.ell_flat, c, K, transform="sq"), Reduce(KP)),
            (feats.plan, MulBroadcast(feats.ell_flat, c, K, transform="abs"), Reduce(KP)),
            (feats.plan, MulBroadcast(feats.ell_flat, c, K, transform="nnz"), Reduce(KP)),
        ]:
            got = np.asarray(fused_execute(dplan, pro, epi, interpret=True))
            want = np.asarray(unfused_execute(dplan, pro, epi))
            np.testing.assert_allclose(got, want, atol=1e-4)


class TestPropertyBased:
    def test_random_problem_shapes(self, interpret_kernels):
        """Property test across random sparsity patterns, shapes, paddings,
        and hot-split settings: the fused engine must match dense algebra
        for every (matvec, rmatvec, rmatvec_sq, row_norms_sq)."""
        hypothesis = pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        @settings(max_examples=12, deadline=None)
        @given(
            n=st.integers(8, 600),
            d=st.integers(4, 500),
            nnz=st.integers(0, 3000),
            floor_pow=st.sampled_from([0, 128 * 128, 2 * 128 * 128]),
            hot=st.sampled_from([0, 64]),
            seed=st.integers(0, 2**31 - 1),
        )
        def check(n, d, nnz, floor_pow, hot, seed):
            rng = np.random.default_rng(seed)
            rows, cols, vals, dense = _random_coo(rng, n, d, nnz)
            feats = from_coo(
                rows, cols, vals, (n, d),
                max_hot_cols=hot, size_floor=floor_pow,
            )
            # high-degree draws accumulate hundreds of fp32 terms; rtol
            # covers ordering differences that scale with the sums
            _check_against_dense(feats, dense, rng, atol=5e-4, rtol=1e-4)

        check()


class TestSummaryStats:
    def test_matches_ell_engine(self, rng, interpret_kernels):
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import from_scipy_like
        from photon_ml_tpu.stat.summary import summarize

        n, d = 512, 300
        rows, cols, vals, dense = _random_coo(rng, n, d, 4000)
        # hot column so the hot-side min/max fold is exercised too
        rows = np.concatenate([rows, np.arange(n)])
        cols = np.concatenate([cols, np.zeros(n, dtype=cols.dtype)])
        ones = np.ones(n, dtype=np.float32)
        vals = np.concatenate([vals, ones])
        np.add.at(dense, (np.arange(n), 0), ones)
        weights = rng.random(n).astype(np.float32) + 0.5

        fused = from_coo(
            rows, cols, vals, (n, d), hot_col_threshold=n // 2,
            size_floor=128 * 128,
        )
        ell = from_scipy_like(rows, cols, vals, (n, d))
        y = jnp.zeros(n, jnp.float32)
        w = jnp.asarray(weights)
        s_f = summarize(LabeledData.create(fused, y, weights=w))
        s_e = summarize(LabeledData.create(ell, y, weights=w))
        for field in ("mean", "variance", "num_nonzeros", "max_abs",
                      "min_val", "max_val", "mean_abs"):
            np.testing.assert_allclose(
                np.asarray(getattr(s_f, field)),
                np.asarray(getattr(s_e, field)),
                rtol=1e-5, atol=1e-3, err_msg=field,
            )


class TestAutoEngineProbe:
    def test_probe_false_without_pallas(self, monkeypatch):
        from photon_ml_tpu.ops import fused_perm as fp

        monkeypatch.setattr(fp, "pallas_available", lambda: False)
        monkeypatch.setattr(fp, "_PROBE_RESULT", None)
        assert fp.fused_engine_works() is False

    def test_auto_prefers_measured_benes_on_tpu(self, monkeypatch):
        """On a TPU backend, "auto" picks the stage-by-stage engine — the
        only large-shard engine with a recorded on-hardware win. The fused
        executor stays opt-in until a TPU A/B records it faster."""
        import jax

        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.ops import fused_perm as fp, sparse_perm as sp

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        called = {}
        monkeypatch.setattr(
            fp, "from_coo", lambda *a, **k: called.setdefault("engine", "fused")
        )
        monkeypatch.setattr(
            sp, "from_coo", lambda *a, **k: called.setdefault("engine", "benes")
        )
        n = 1 << 20
        data = GameData(
            labels=np.zeros(4, np.float32),
            feature_shards={
                "g": FeatureShard(
                    rows=np.zeros(n, np.int64), cols=np.zeros(n, np.int64),
                    vals=np.ones(n, np.float32), dim=8,
                )
            },
            id_tags={},
            offsets=np.zeros(4, np.float32),
            weights=np.ones(4, np.float32),
        )
        data.sparse_features("g", engine="auto")
        assert called["engine"] == "benes"

    def test_fused_rejects_oversized_slot_groups(self):
        """A row/column with more than LANES*LANES nonzeros cannot tile the
        fused prologue/epilogue (the operand BlockSpec height LANES*u//q
        would silently hit zero); assemble must fail loudly, not lower to
        an obscure Mosaic error."""
        from photon_ml_tpu.ops import fused_perm as fp

        nnz = fp.MAX_FUSED_GROUP * 2  # one row, 2*16384 distinct columns
        rows = np.zeros(nnz, np.int64)
        cols = np.arange(nnz, dtype=np.int64)
        vals = np.ones(nnz, np.float32)
        with pytest.raises(fp.FusedGroupTooLarge, match="slot group K="):
            fp.from_coo(
                rows, cols, vals, (1, nnz), max_hot_cols=0, plan_cache=""
            )


class TestValidators:
    def test_validate_labeled_data_fused_engine(self, rng, interpret_kernels):
        from photon_ml_tpu.data.validators import (
            DataValidationError,
            validate_labeled_data,
        )
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.types import TaskType

        n, d = 256, 128
        rows, cols, vals, _ = _random_coo(rng, n, d, 1500)
        # hot column so the concatenated hot side is validated too
        rows = np.concatenate([rows, np.arange(n)])
        cols = np.concatenate([cols, np.zeros(n, dtype=cols.dtype)])
        vals = np.concatenate([vals, np.ones(n, dtype=np.float32)])
        feats = from_coo(rows, cols, vals, (n, d), hot_col_threshold=n // 2)
        y = jnp.asarray((rng.random(n) > 0.5).astype(np.float32))
        validate_labeled_data(
            LabeledData.create(feats, y), TaskType.LOGISTIC_REGRESSION
        )  # clean data passes

        bad = np.array(vals)
        bad[7] = np.nan
        feats_bad = from_coo(rows, cols, bad, (n, d), hot_col_threshold=n // 2)
        with pytest.raises(DataValidationError):
            validate_labeled_data(
                LabeledData.create(feats_bad, y), TaskType.LOGISTIC_REGRESSION
            )


class TestGridFused:
    def test_grid_fused_matches_ell_grid(self, rng, interpret_kernels):
        import jax
        from photon_ml_tpu.parallel.grid_features import (
            grid_from_coo,
            grid_mesh,
            shard_vector_data,
            shard_vector_feat,
        )

        n, d = 256, 192
        rows, cols, vals, dense = _random_coo(rng, n, d, 2000)
        mesh = grid_mesh(2, 4)
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)

        outs = {}
        for engine in ("ell", "fused"):
            gf = grid_from_coo(rows, cols, vals, (n, d), mesh, engine=engine)
            wp = np.zeros(gf.dim, np.float32)
            wp[:d] = w
            cp = np.zeros(gf.num_rows, np.float32)
            cp[:n] = c
            z = np.asarray(gf.matvec(shard_vector_feat(jnp.asarray(wp), mesh)))
            g = np.asarray(gf.rmatvec(shard_vector_data(jnp.asarray(cp), mesh)))
            outs[engine] = (z[:n], g[:d])

        np.testing.assert_allclose(outs["fused"][0], dense @ w, atol=1e-4)
        np.testing.assert_allclose(outs["fused"][1], dense.T @ c, atol=1e-4)
        np.testing.assert_allclose(outs["fused"][0], outs["ell"][0], atol=1e-4)
        np.testing.assert_allclose(outs["fused"][1], outs["ell"][1], atol=1e-4)


class TestEstimatorFused:
    def test_game_estimator_fused_engine(self, rng):
        from photon_ml_tpu.data.game_data import FeatureShard, GameData
        from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
            RandomEffectCoordinateConfiguration,
        )
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        n, d, k = 400, 64, 4
        rows = np.repeat(np.arange(n), k)
        cols = rng.integers(0, d, n * k)
        vals = rng.standard_normal(n * k).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        w_true = (rng.standard_normal(d) * 0.5).astype(np.float32)
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-dense @ w_true))).astype(
            np.float32
        )
        users = [f"u{i % 10}" for i in range(n)]
        data = GameData(
            labels=y,
            feature_shards={"g": FeatureShard(rows=rows, cols=cols, vals=vals, dim=d)},
            id_tags={"userId": users},
            offsets=np.zeros(n, np.float32),
            weights=np.ones(n, np.float32),
        )
        opt = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=25),
            regularization_weight=1.0,
        )

        fits = {}
        for engine in ("ell", "fused"):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinates={
                    "global": FixedEffectCoordinateConfiguration(
                        feature_shard="g", optimizer=opt, sparse_engine=engine
                    ),
                    "per-user": RandomEffectCoordinateConfiguration(
                        feature_shard="g",
                        data=RandomEffectDataConfiguration(
                            random_effect_type="userId"
                        ),
                        optimizer=opt,
                    ),
                },
                num_outer_iterations=1,
            )
            fits[engine] = est.fit(data)
        w_e = np.asarray(fits["ell"].model.models["global"].coefficients.means)
        w_f = np.asarray(fits["fused"].model.models["global"].coefficients.means)
        np.testing.assert_allclose(w_f, w_e, atol=5e-3)


class TestInSolver:
    """The fused engine as a drop-in FeatureMatrix in an actual GLM solve."""

    def test_lbfgs_matches_ell(self, rng, interpret_kernels):
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import from_scipy_like
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve

        n, d = 512, 200
        rows, cols, vals, dense = _random_coo(rng, n, d, 4000)
        w_true = rng.standard_normal(d).astype(np.float32) * 0.5
        z = dense @ w_true
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

        objective = make_glm_objective(LogisticLoss)
        cfg = GlmOptimizationConfiguration(
            optimizer_config=OptimizerConfig.lbfgs(max_iterations=30),
            regularization_weight=1.0,
        )
        l2 = jnp.float32(1.0)

        ell = from_scipy_like(rows, cols, vals, (n, d))
        res_ell = solve(
            objective, jnp.zeros(d, jnp.float32),
            LabeledData.create(ell, jnp.asarray(y)), cfg, l2_weight=l2,
        )
        fused = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        res_fused = solve(
            objective, jnp.zeros(d, jnp.float32),
            LabeledData.create(fused, jnp.asarray(y)), cfg, l2_weight=l2,
        )
        np.testing.assert_allclose(
            np.asarray(res_fused.w), np.asarray(res_ell.w), atol=5e-3
        )

    @pytest.mark.parametrize("optimizer", ["tron", "owlqn"])
    def test_tron_owlqn_match_ell(self, rng, interpret_kernels, optimizer):
        """TRON drives Hessian-vector products (matvec + rmatvec on the
        direction) and OWL-QN the L1 pseudo-gradient through the fused maps."""
        from photon_ml_tpu.losses.objective import make_glm_objective
        from photon_ml_tpu.losses.pointwise import LogisticLoss
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import from_scipy_like
        from photon_ml_tpu.opt.config import (
            GlmOptimizationConfiguration,
            OptimizerConfig,
        )
        from photon_ml_tpu.opt.solve import solve

        n, d = 512, 160
        rows, cols, vals, dense = _random_coo(rng, n, d, 3500)
        w_true = rng.standard_normal(d).astype(np.float32) * 0.5
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(dense @ w_true)))).astype(
            np.float32
        )
        if optimizer == "tron":
            cfg = GlmOptimizationConfiguration(
                optimizer_config=OptimizerConfig.tron(max_iterations=12),
                regularization_weight=1.0,
            )
            l1 = 0.0
        else:
            cfg = GlmOptimizationConfiguration(
                optimizer_config=OptimizerConfig.lbfgs(max_iterations=30),
                regularization_weight=1.0,
            )
            l1 = 0.5
        objective = make_glm_objective(LogisticLoss)
        l2 = jnp.float32(1.0)
        l1_arg = jnp.float32(l1) if l1 else None

        ell = from_scipy_like(rows, cols, vals, (n, d))
        res_ell = solve(
            objective, jnp.zeros(d, jnp.float32),
            LabeledData.create(ell, jnp.asarray(y)), cfg,
            l2_weight=l2, l1_weight=l1_arg,
        )
        fused = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128
        )
        res_fused = solve(
            objective, jnp.zeros(d, jnp.float32),
            LabeledData.create(fused, jnp.asarray(y)), cfg,
            l2_weight=l2, l1_weight=l1_arg,
        )
        np.testing.assert_allclose(
            np.asarray(res_fused.w), np.asarray(res_ell.w), atol=5e-3
        )
        if l1:
            # OWL-QN must produce an actually-sparse solution on both engines
            assert (np.abs(np.asarray(res_fused.w)) < 1e-8).any()


class TestBf16Payload:
    def test_bf16_kernels_interpret(self, rng, interpret_kernels):
        """The fused kernels' bf16 load/store + f32 in-VMEM shuffle paths,
        via the Pallas interpreter."""
        n, d = 1024, 600
        rows, cols, vals, dense = _random_coo(rng, n, d, 6000)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0, size_floor=128 * 128,
            kp_cap=None, col_split=1, payload_dtype="bfloat16",
        )
        assert feats._fused_ok()
        w = rng.standard_normal(d).astype(np.float32)
        c = rng.standard_normal(n).astype(np.float32)
        z_ref, g_ref = dense @ w, dense.T @ c
        z = np.asarray(feats.matvec(jnp.asarray(w)))
        g = np.asarray(feats.rmatvec(jnp.asarray(c)))
        assert np.abs(z - z_ref).max() / (np.abs(z_ref).max() + 1e-6) < 2e-2
        assert np.abs(g - g_ref).max() / (np.abs(g_ref).max() + 1e-6) < 2e-2

    def test_bf16_payload_close_and_f32_exact(self, rng):
        """payload_dtype='bfloat16' halves the permuted intermediates: the
        maps stay within bf16 entry-rounding error (~2^-8 relative) while
        the default f32 path is untouched."""
        rows, cols, vals, dense = _random_coo(rng, n=256, d=512, nnz=4096)
        w = rng.standard_normal(512).astype(np.float32)
        c = rng.standard_normal(256).astype(np.float32)
        fb = from_coo(rows, cols, vals, (256, 512), max_hot_cols=0,
                      kp_cap=None, col_split=1, payload_dtype="bfloat16")
        z = np.asarray(fb.matvec(jnp.asarray(w)))
        g = np.asarray(fb.rmatvec(jnp.asarray(c)))
        z_ref, g_ref = dense @ w, dense.T @ c
        scale_z = np.abs(z_ref).max() + 1e-6
        scale_g = np.abs(g_ref).max() + 1e-6
        assert np.abs(z - z_ref).max() / scale_z < 2e-2
        assert np.abs(g - g_ref).max() / scale_g < 2e-2
        # f32 default still exact
        f32 = from_coo(rows, cols, vals, (256, 512), max_hot_cols=0,
                       kp_cap=None, col_split=1)
        np.testing.assert_allclose(
            np.asarray(f32.matvec(jnp.asarray(w))), z_ref, atol=2e-4
        )

    def test_bf16_payload_through_auto_layout(self, rng):
        """bf16 payload composes with the KP-cap/column-split planner."""
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        n, d, k = 512, 8192, 8
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = rng.integers(0, d, n * k).astype(np.int64)
        vals = rng.standard_normal(n * k).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        f = from_coo(rows, cols, vals, (n, d), max_hot_cols=0,
                     payload_dtype="bfloat16")
        w = rng.standard_normal(d).astype(np.float32)
        z = np.asarray(f.matvec(jnp.asarray(w)))
        z_ref = dense @ w
        assert np.abs(z - z_ref).max() / (np.abs(z_ref).max() + 1e-6) < 2e-2
        if isinstance(f, ColumnSplitFeatures):
            for blk in f.blocks:
                assert getattr(blk, "payload_dtype", "float32") == "bfloat16"
