"""Tests for the public testing/generator module (photon-test-utils
parity): regime properties, label validity per task, factory shapes, and
that the generators compose with validators and estimators."""

import numpy as np
import pytest

from photon_ml_tpu import testing as ptest
from photon_ml_tpu.types import TaskType


class TestDrawSample:
    @pytest.mark.parametrize("task", list(TaskType))
    def test_benign(self, task):
        X, y, w = ptest.draw_sample(task, n=150, d=8, seed=1)
        assert X.shape == (150, 8) and np.isfinite(X).all()
        if task.is_classification:
            assert set(np.unique(y)) <= {0.0, 1.0}
            assert 0.1 < y.mean() < 0.9  # roughly balanced
        if task is TaskType.POISSON_REGRESSION:
            assert (y >= 0).all()

    def test_outlier_regime_is_ill_conditioned(self):
        X, _, _ = ptest.draw_sample(
            TaskType.LINEAR_REGRESSION, n=300, d=6, regime="outlier", seed=2
        )
        assert np.isfinite(X).all()
        col_scale = np.abs(X).max(axis=0)
        assert col_scale.max() / max(col_scale.min(), 1e-30) > 1e4

    def test_invalid_regime_fails_validation(self):
        import jax.numpy as jnp

        from photon_ml_tpu.data.validators import (
            DataValidationError,
            validate_labeled_data,
        )
        from photon_ml_tpu.ops.data import LabeledData
        from photon_ml_tpu.ops.features import DenseFeatures

        X, y, _ = ptest.draw_sample(
            TaskType.LINEAR_REGRESSION, n=100, d=5, regime="invalid", seed=3
        )
        assert not np.isfinite(X).all()
        data = LabeledData.create(
            DenseFeatures(matrix=jnp.asarray(X)), jnp.asarray(y)
        )
        with pytest.raises(DataValidationError):
            validate_labeled_data(data, TaskType.LINEAR_REGRESSION)

    @pytest.mark.parametrize("task", list(TaskType))
    def test_invalid_labels(self, task):
        y = ptest.draw_invalid_labels(task, n=80, seed=4)
        if task is TaskType.POISSON_REGRESSION:
            assert (y < 0).any()
        elif task.is_classification:
            assert ((y != 0) & (y != 1)).any()
        else:
            assert np.isnan(y).any()


class TestFactories:
    def test_fixed_effect_data_trains(self):
        from photon_ml_tpu.estimators.game import (
            FixedEffectCoordinateConfiguration,
            GameEstimator,
        )

        data, w_true = ptest.generate_fixed_effect_data(
            TaskType.LOGISTIC_REGRESSION, n=300, d=8, seed=5
        )
        fit = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={"g": FixedEffectCoordinateConfiguration("global")},
        ).fit(data)
        scores = fit.model.score(data)
        acc = ((scores > 0) == (data.labels > 0.5)).mean()
        assert acc > 0.8

    def test_glmix_data_structure(self):
        data, truth = ptest.generate_glmix_data(
            n_entities=5, rows_per_entity=10, seed=6
        )
        assert data.num_rows == 50
        assert set(data.feature_shards) == {"global", "per_entity"}
        assert len(set(data.id_tags["userId"])) == 5
        assert "w_fixed" in truth and "w_e0000" in truth

    def test_generate_game_model_scores(self):
        data, _ = ptest.generate_glmix_data(
            n_entities=4, rows_per_entity=8, seed=7
        )
        model = ptest.generate_game_model(
            data,
            TaskType.LINEAR_REGRESSION,
            {
                "fixed": {"feature_shard": "global"},
                "per_user": {
                    "feature_shard": "per_entity",
                    "random_effect_type": "userId",
                },
            },
        )
        scores = model.score(data)
        assert scores.shape == (32,)
        assert np.isfinite(scores).all()
        assert np.abs(scores).sum() > 0


class TestPublicApi:
    def test_root_exports_resolve(self):
        """Every lazily re-exported name on the package root must resolve
        (a reference user's one-stop import surface)."""
        import photon_ml_tpu as p

        for name in p._LAZY:
            assert getattr(p, name) is not None, name
        assert "GameEstimator" in dir(p)
        with pytest.raises(AttributeError):
            p.definitely_not_a_symbol
