"""Worker for the real multi-process cluster test (test_multiprocess.py).

Each worker is one "host": its own process, its own local CPU devices,
joined into one JAX cluster through a local coordinator. Exercises the
REAL multi-process branches of parallel/multihost.py — cluster init, file
sharding, global-batch assembly from unequal per-host blocks — plus a
cross-process data-parallel FE solve (psums over the global mesh).
"""

import os
import sys

proc_id = int(sys.argv[1])
n_procs = int(sys.argv[2])
port = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 // n_procs}"
).strip()
os.environ["PHOTON_ML_TPU_PLAN_CACHE"] = ""
os.environ["PHOTON_ML_TPU_COMPILE_CACHE"] = ""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from photon_ml_tpu.parallel.multihost import (
    global_batch_from_host_rows,
    host_shard_files,
    initialize_distributed,
)

ok = initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=n_procs,
    process_id=proc_id,
)
assert ok, "cluster did not form"
assert jax.process_count() == n_procs
assert jax.process_index() == proc_id
n_global = len(jax.devices())
n_local = len(jax.local_devices())
assert n_global == 8 and n_local == 8 // n_procs, (n_global, n_local)

# deterministic, disjoint, complete file assignment
files = [f"part-{i:05d}.avro" for i in range(7)]
mine = host_shard_files(files)
assert mine == [p for k, p in enumerate(sorted(files)) if k % n_procs == proc_id]

# global batch from UNEQUAL per-host row blocks
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.parallel.mesh import DATA_AXIS, data_parallel_mesh

mesh = data_parallel_mesh()  # all global devices
share = 24 * n_local // n_global  # this process's addressable rows
rows = np.full((share, 3), float(proc_id), dtype=np.float32)
garr = global_batch_from_host_rows(
    rows, mesh, P(DATA_AXIS, None), global_rows=24
)
assert garr.shape == (24, 3)
total = float(jax.jit(jnp.sum)(garr))  # cross-process psum via GSPMD
expected = 3.0 * share * sum(range(n_procs))  # sum over hosts of id*share
assert total == expected, (total, expected)

# an unequal block must fail fast with the pad/trim instruction, not trip
# deep inside jax
try:
    global_batch_from_host_rows(
        rows[: share - 1], mesh, P(DATA_AXIS, None), global_rows=24
    )
except ValueError as e:
    assert "zero-weight" in str(e)
else:
    raise AssertionError("unequal host block silently accepted")

# a real data-parallel FE solve over the global mesh: every process runs the
# same program; loss/grad reductions cross the process boundary
from photon_ml_tpu.losses.objective import make_glm_objective
from photon_ml_tpu.losses.pointwise import LogisticLoss
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerConfig
from photon_ml_tpu.opt.solve import solve

rng = np.random.default_rng(0)  # same data recipe on every host
n, d = 64, 6
X_all = rng.standard_normal((n_procs * n, d)).astype(np.float32)
w_true = (rng.standard_normal(d) * 0.7).astype(np.float32)
y_all = (rng.random(n_procs * n) < 1.0 / (1.0 + np.exp(-(X_all @ w_true)))).astype(
    np.float32
)
n_share = n_procs * n * n_local // n_global
lo = proc_id * n_share
X_g = global_batch_from_host_rows(
    X_all[lo : lo + n_share], mesh, P(DATA_AXIS, None), global_rows=n_procs * n
)
y_g = global_batch_from_host_rows(
    y_all[lo : lo + n_share], mesh, P(DATA_AXIS), global_rows=n_procs * n
)
data = LabeledData.create(DenseFeatures(matrix=X_g), y_g)
cfg = GlmOptimizationConfiguration(
    optimizer_config=OptimizerConfig.lbfgs(max_iterations=25),
    regularization_weight=1.0,
)
objective = make_glm_objective(LogisticLoss)
res = jax.jit(
    lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=jnp.float32(1.0))
)(jnp.zeros(d, jnp.float32), data)
w = np.asarray(jax.device_get(res.w))  # replicated -> addressable everywhere
assert np.all(np.isfinite(w)) and np.abs(w).max() > 0.05
corr = float(np.corrcoef(w, w_true)[0, 1])
assert corr > 0.8, corr

# --- the 1B-coefficient layout ACROSS PROCESSES: a (data x feat) grid FE
# solve where coefficients stay feat-sharded and tiles live on whichever
# host owns their device. Every host builds from the same global COO; the
# placement helper hands each process only its addressable shards.
from photon_ml_tpu.parallel.grid_features import (
    grid_from_coo,
    grid_mesh,
    shard_vector_data,
    shard_vector_feat,
)

ng, dg, kg = 128, 96, 4
g_rows = np.repeat(np.arange(ng, dtype=np.int64), kg)
g_cols = rng.integers(0, dg, ng * kg)
g_vals = rng.standard_normal(ng * kg).astype(np.float32)
g_dense = np.zeros((ng, dg), np.float32)
np.add.at(g_dense, (g_rows, g_cols), g_vals)
gw_true = (rng.standard_normal(dg) * 0.5).astype(np.float32)
g_y = (rng.random(ng) < 1.0 / (1.0 + np.exp(-(g_dense @ gw_true)))).astype(
    np.float32
)
gmesh = grid_mesh(2, 4)  # spans every process in the cluster
gf = grid_from_coo(g_rows, g_cols, g_vals, (ng, dg), gmesh, engine="benes")
y_pad = np.zeros(gf.num_rows, np.float32)
y_pad[:ng] = g_y
wt_pad = np.zeros(gf.num_rows, np.float32)
wt_pad[:ng] = 1.0
g_data = LabeledData.create(
    gf,
    shard_vector_data(jnp.asarray(y_pad), gmesh),
    weights=shard_vector_data(jnp.asarray(wt_pad), gmesh),
)
g_res = jax.jit(
    lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=jnp.float32(1.0))
)(shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), gmesh), g_data)
from jax.sharding import NamedSharding

g_w = np.asarray(jax.device_get(
    jax.jit(lambda a: a, out_shardings=NamedSharding(gmesh, P()))(g_res.w)
))  # all-gather the feat-sharded result (replicated -> fetchable anywhere)
# reference: same solve single-host on local dense math
from photon_ml_tpu.ops.features import from_scipy_like

ell_ref = from_scipy_like(g_rows, g_cols, g_vals, (ng, dg))
ref = solve(
    objective, jnp.zeros(dg, jnp.float32),
    LabeledData.create(ell_ref, jnp.asarray(g_y)), cfg,
    l2_weight=jnp.float32(1.0),
)
assert np.allclose(g_w[:dg], np.asarray(ref.w), atol=5e-3), (
    np.abs(g_w[:dg] - np.asarray(ref.w)).max()
)

# --- full GAME training (FE grid + entity-sharded RE) across processes:
# the estimator's multi-chip path under a real multi-controller runtime.
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    ParallelConfiguration,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.types import TaskType

users = [f"u{i % 8}" for i in range(ng)]
game_data = GameData(
    labels=g_y,
    feature_shards={
        "g": FeatureShard(rows=g_rows, cols=g_cols, vals=g_vals, dim=dg)
    },
    id_tags={"userId": users},
    offsets=np.zeros(ng, np.float32),
    weights=np.ones(ng, np.float32),
)
game_coords = {
    "global": FixedEffectCoordinateConfiguration(
        feature_shard="g", optimizer=cfg
    ),
    "per-user": RandomEffectCoordinateConfiguration(
        feature_shard="g",
        data=RandomEffectDataConfiguration(random_effect_type="userId"),
        optimizer=cfg,
    ),
}
est = GameEstimator(
    task=TaskType.LOGISTIC_REGRESSION,
    coordinates=game_coords,
    num_outer_iterations=1,
    parallel=ParallelConfiguration(n_data=2, n_feat=4, engine="benes"),
)
# checkpoint the fit itself: process 0 writes, every host runs the gathers
import tempfile

from photon_ml_tpu.parallel.multihost import barrier

ckdir = os.path.join(tempfile.gettempdir(), f"mp_ckpt_{port}_{os.getppid()}")
if proc_id == 0 and os.path.isdir(ckdir):
    import shutil

    shutil.rmtree(ckdir)
barrier("ckpt-clean")
game_fit = est.fit(game_data, checkpoint_dir=ckdir)
g_scores = np.asarray(game_fit.model.score(game_data))
assert np.all(np.isfinite(g_scores))

# --- model persistence across processes: every host runs the gather
# collectives, only process 0 writes (single-writer contract), then all
# hosts read the shared directory after a barrier
import tempfile

from photon_ml_tpu.io.model_io import load_game_model, save_game_model
from photon_ml_tpu.parallel.multihost import barrier

mdir = os.path.join(tempfile.gettempdir(), f"mp_model_{port}_{os.getppid()}")
if proc_id == 0 and os.path.isdir(mdir):
    import shutil

    shutil.rmtree(mdir)  # stale dir from a crashed run must not mask a save
barrier("model-dir-clean")
save_game_model(game_fit.model, mdir)
barrier("model-saved")
assert os.path.isdir(mdir), "process 0 should have written the shared model"
reloaded, _ = load_game_model(mdir)
from photon_ml_tpu.parallel.mesh import fetch_global

fe0 = fetch_global(game_fit.model.models["global"].coefficients.means)
fe1 = fetch_global(reloaded.models["global"].coefficients.means)
assert fe0.shape == fe1.shape  # dim survives sparse storage (featureShards in metadata)
assert np.allclose(fe0, fe1, atol=1e-6)
r_scores = np.asarray(reloaded.score(game_data))
assert np.allclose(r_scores, g_scores, atol=1e-4), (
    np.abs(r_scores - g_scores).max()
)
barrier("model-reloaded")
if proc_id == 0:
    import shutil

    shutil.rmtree(mdir, ignore_errors=True)

# --- resume across the cluster: a longer run continues from the shared
# checkpoint written during the fit above
barrier("ckpt-written")
assert os.path.isfile(
    os.path.join(ckdir, "training-state.json")
), "process 0 should have written the checkpoint state"
est_resume = GameEstimator(
    task=TaskType.LOGISTIC_REGRESSION,
    coordinates=game_coords,
    num_outer_iterations=2,
    parallel=ParallelConfiguration(n_data=2, n_feat=4, engine="benes"),
)
fit2 = est_resume.fit(game_data, checkpoint_dir=ckdir)  # resumes at iter 2
# the resumed run must splice iteration 1's objective history from the
# checkpoint — exact equality proves it loaded rather than retrained
h1 = game_fit.objective_history
assert fit2.objective_history[: len(h1)] == h1, (
    fit2.objective_history[: len(h1)], h1
)
assert len(fit2.objective_history) > len(h1)  # and trained iteration 2
r2 = np.asarray(fit2.model.score(game_data))
assert np.all(np.isfinite(r2))
barrier("resume-done")
if proc_id == 0:
    import shutil

    shutil.rmtree(ckdir, ignore_errors=True)

print(f"worker {proc_id}: cluster {n_procs} procs x {n_local} devices, "
      f"dp solve corr {corr:.3f}, grid solve matches local, "
      f"GAME estimator fit OK", flush=True)
