"""Checkpoint/resume tests: native round trips per sub-model type, atomic
write semantics, and resume-equivalence of coordinate descent (an improvement
over the reference, which has no mid-training checkpointing — SURVEY.md §5)."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.data import RandomEffectDataConfiguration
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.opt import GlmOptimizationConfiguration, RegularizationContext
from photon_ml_tpu.types import RegularizationType, TaskType

L2 = lambda lam: GlmOptimizationConfiguration(
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=lam,
)


def _problem(rng, n_users=6, rows=25, dg=8, du=4):
    n = n_users * rows
    Xg = rng.normal(size=(n, dg)).astype(np.float32)
    Xu = rng.normal(size=(n, du)).astype(np.float32)
    users = np.repeat([f"u{i}" for i in range(n_users)], rows)
    wg = rng.normal(size=dg).astype(np.float32)
    wu = {f"u{i}": rng.normal(size=du).astype(np.float32) for i in range(n_users)}
    y = Xg @ wg + np.array([Xu[i] @ wu[users[i]] for i in range(n)], np.float32)
    y += 0.05 * rng.normal(size=n).astype(np.float32)

    def coo(X):
        r, c = np.nonzero(X)
        return FeatureShard(rows=r, cols=c, vals=X[r, c], dim=X.shape[1])

    mk = lambda sl: GameData(
        labels=y[sl],
        feature_shards={"g": coo(Xg[sl]), "u": coo(Xu[sl])},
        id_tags={"userId": users[sl]},
    )
    return mk(slice(0, int(0.8 * n))), mk(slice(int(0.8 * n), n))


def _estimator(num_outer=3):
    return GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinates={
            "fixed": FixedEffectCoordinateConfiguration("g", L2(0.1)),
            "per_user": RandomEffectCoordinateConfiguration(
                "u", RandomEffectDataConfiguration(random_effect_type="userId"),
                L2(1.0),
            ),
        },
        num_outer_iterations=num_outer,
    )


class TestSubmodelRoundTrip:
    def test_glm_and_re_round_trip(self, rng, tmp_path):
        from photon_ml_tpu import checkpoint as ckpt

        data, _ = _problem(rng)
        fit = _estimator(num_outer=1).fit(data)
        models = fit.model.models
        d = str(tmp_path / "c")
        ckpt.save_training_checkpoint(d, models, state={"completed_iterations": 1})
        loaded, state, best = ckpt.load_training_checkpoint(d)
        assert state["completed_iterations"] == 1
        assert best is None
        np.testing.assert_allclose(
            np.asarray(models["fixed"].coefficients.means),
            np.asarray(loaded["fixed"].coefficients.means),
        )
        re0, re1 = models["per_user"], loaded["per_user"]
        assert re0.entity_ids == re1.entity_ids
        for b in range(len(re0.coefficients)):
            np.testing.assert_allclose(
                np.asarray(re0.coefficients[b]), np.asarray(re1.coefficients[b])
            )
            np.testing.assert_array_equal(
                np.asarray(re0.proj_indices[b]), np.asarray(re1.proj_indices[b])
            )
        assert re1.entity_to_loc == re0.entity_to_loc

    def test_atomic_overwrite(self, rng, tmp_path):
        from photon_ml_tpu import checkpoint as ckpt

        data, _ = _problem(rng)
        fit = _estimator(num_outer=1).fit(data)
        d = str(tmp_path / "c")
        ckpt.save_training_checkpoint(d, fit.model.models, state={"completed_iterations": 1})
        ckpt.save_training_checkpoint(d, fit.model.models, state={"completed_iterations": 2})
        _, state, _ = ckpt.load_training_checkpoint(d)
        assert state["completed_iterations"] == 2
        # no tmp debris left behind
        leftovers = [p for p in os.listdir(tmp_path) if p.startswith(".ckpt-tmp-")]
        assert leftovers == []


class TestResume:
    def test_resume_matches_uninterrupted(self, rng, tmp_path):
        """Interrupted-at-iteration-1 + resume == straight 3-iteration run."""
        data, vdata = _problem(rng)
        straight = _estimator(3).fit(data, validation_data=vdata)

        ck = str(tmp_path / "ck")
        partial = _estimator(1).fit(data, validation_data=vdata, checkpoint_dir=ck)
        from photon_ml_tpu import checkpoint as ckpt

        assert ckpt.has_checkpoint(ck)
        resumed = _estimator(3).fit(data, validation_data=vdata, checkpoint_dir=ck)

        # same number of total coordinate updates recorded
        assert len(resumed.objective_history) == len(straight.objective_history)
        np.testing.assert_allclose(
            resumed.model.score(vdata), straight.model.score(vdata),
            rtol=1e-4, atol=1e-4,
        )
        assert resumed.validation_metric == pytest.approx(
            straight.validation_metric, rel=1e-4
        )

    def test_fully_complete_checkpoint_skips_training(self, rng, tmp_path):
        data, vdata = _problem(rng)
        ck = str(tmp_path / "ck")
        first = _estimator(2).fit(data, validation_data=vdata, checkpoint_dir=ck)
        again = _estimator(2).fit(data, validation_data=vdata, checkpoint_dir=ck)
        # no new updates happened; histories identical
        assert again.objective_history == first.objective_history
        np.testing.assert_allclose(
            again.model.score(vdata), first.model.score(vdata), rtol=1e-5, atol=1e-5
        )

    def test_incompatible_checkpoint_rejected(self, rng, tmp_path):
        """Resuming with different data must fail fast with a clear error,
        not crash deep in jax or silently mistrain."""
        data, vdata = _problem(rng)
        ck = str(tmp_path / "ck")
        _estimator(1).fit(data, validation_data=vdata, checkpoint_dir=ck)
        other, _ = _problem(np.random.default_rng(99), n_users=9, rows=11)
        with pytest.raises(ValueError, match="incompatible"):
            _estimator(2).fit(other, checkpoint_dir=ck)

    def test_cli_checkpoint_flag(self, rng, tmp_path):
        from photon_ml_tpu.io.data_reader import write_training_examples
        from photon_ml_tpu.cli.train_game import parse_args, run

        data, _ = _problem(rng)
        recs = []
        for i in range(data.num_rows):
            recs.append({
                "label": float(data.labels[i]),
                "features": [],
                "metadataMap": {"userId": str(data.id_tags["userId"][i])},
            })
        # rebuild features from the shards for the avro fixture
        for sid, bag in (("g", "features"), ("u", "userFeatures")):
            s = data.feature_shards[sid]
            for r, c, v in zip(s.rows, s.cols, s.vals):
                recs[r].setdefault(bag, []).append((sid, str(c), float(v)))
        train = tmp_path / "train"
        train.mkdir()
        write_training_examples(str(train / "part-00000.avro"), recs)
        cfg = {
            "feature_shards": {
                "g": {"feature_bags": ["features"], "add_intercept": False},
                "u": {"feature_bags": ["userFeatures"], "add_intercept": False},
            },
            "coordinates": {
                "fixed": {"type": "fixed", "feature_shard": "g",
                          "optimizer": {"regularization": "L2",
                                        "regularization_weight": 0.1}},
            },
        }
        cfg_path = tmp_path / "game.json"
        cfg_path.write_text(json.dumps(cfg))
        ck = tmp_path / "ckpt"
        run(parse_args([
            "--train-data-dirs", str(train),
            "--coordinate-config", str(cfg_path),
            "--task", "LINEAR_REGRESSION",
            "--output-dir", str(tmp_path / "out"),
            "--num-outer-iterations", "2",
            "--checkpoint-dir", str(ck),
        ]))
        assert (ck / "training-state.json").is_file()
        payload = json.loads((ck / "training-state.json").read_text())
        assert payload["state"]["completed_iterations"] == 2

class TestRetention:
    def test_orphan_sweep_after_successful_save(self, rng, tmp_path):
        """A kill between the two renames leaks .ckpt-tmp-*/.ckpt-old-*
        siblings; the next successful save sweeps them."""
        from photon_ml_tpu import checkpoint as ckpt

        data, _ = _problem(rng)
        fit = _estimator(num_outer=1).fit(data)
        for name in (".ckpt-tmp-dead", ".ckpt-old-dead"):
            (tmp_path / name).mkdir()
            (tmp_path / name / "junk.json").write_text("{}")
        ckpt.save_training_checkpoint(
            str(tmp_path / "c"), fit.model.models,
            state={"completed_iterations": 1},
        )
        leftovers = [
            p for p in os.listdir(tmp_path)
            if p.startswith((".ckpt-tmp-", ".ckpt-old-"))
        ]
        assert leftovers == []
        ckpt.load_training_checkpoint(str(tmp_path / "c"))

    def test_keep_last_n_prunes_numbered_siblings(self, rng, tmp_path):
        from photon_ml_tpu import checkpoint as ckpt

        data, _ = _problem(rng)
        fit = _estimator(num_outer=1).fit(data)
        # an unrelated non-checkpoint dir matching nothing must survive
        (tmp_path / "notes").mkdir()
        for i in range(1, 5):
            ckpt.save_training_checkpoint(
                str(tmp_path / f"ckpt-{i:06d}"), fit.model.models,
                state={"completed_iterations": i},
                keep_last_n=2,
            )
        kept = sorted(
            p for p in os.listdir(tmp_path) if p.startswith("ckpt-")
        )
        assert kept == ["ckpt-000003", "ckpt-000004"]
        assert (tmp_path / "notes").is_dir()
        _, state, _ = ckpt.load_training_checkpoint(
            str(tmp_path / "ckpt-000004")
        )
        assert state["completed_iterations"] == 4

    def test_keep_last_n_requires_numbered_name(self, rng, tmp_path):
        from photon_ml_tpu import checkpoint as ckpt

        data, _ = _problem(rng)
        fit = _estimator(num_outer=1).fit(data)
        with pytest.raises(ValueError, match="iteration-numbered"):
            ckpt.save_training_checkpoint(
                str(tmp_path / "latest"), fit.model.models,
                state={"completed_iterations": 1}, keep_last_n=3,
            )
