"""Bounded-staleness async CD schedule: sync-identity at staleness=0,
held-out AUC parity at staleness>0, overlap span attribution across worker
threads, retrace parity with the sync pow2 registry, and the RE bucket
overlap leg.

The async schedule's determinism contract: residuals are computed on the
DRIVER thread at dispatch time and deltas fold back in dispatch order, so
the trajectory depends only on the ``staleness`` bound — never on thread
timing. staleness=0 reconciles before every dispatch, which reproduces the
sync trajectory bitwise; these tests are the oracle for that claim.
"""

import numpy as np
import pytest

from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_ml_tpu.algorithm.schedule import ScheduleExecutor
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.estimators.random_effect import solver_trace_counts
from photon_ml_tpu.event import EventEmitter, EventListener, TransferStatsEvent
from photon_ml_tpu.telemetry.span import disable_tracing, enable_tracing
from photon_ml_tpu.types import TaskType

N_USERS, N_ITEMS, ROWS_PER_USER = 18, 7, 24
D_FE, D_RE = 10, 5
N_OUTER = 3


def _problem(seed=0, task=TaskType.LINEAR_REGRESSION, n_users=N_USERS,
             rows_per_user=ROWS_PER_USER):
    rng = np.random.default_rng(seed)
    n = n_users * rows_per_user
    Xg = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xu = rng.normal(size=(n, D_RE)).astype(np.float32)
    Xi = rng.normal(size=(n, D_RE)).astype(np.float32)
    user_ids = np.repeat([f"u{i:03d}" for i in range(n_users)], rows_per_user)
    item_ids = np.array([f"i{int(v):03d}" for v in rng.integers(0, N_ITEMS, n)])
    w = rng.normal(size=D_FE).astype(np.float32)
    z = Xg @ w + 0.1 * rng.normal(size=n)
    if task is TaskType.LOGISTIC_REGRESSION:
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    else:
        y = z.astype(np.float32)

    def coo(X):
        rows, cols = np.nonzero(X)
        return FeatureShard(rows=rows, cols=cols, vals=X[rows, cols], dim=X.shape[1])

    return GameData(
        labels=y,
        feature_shards={"global": coo(Xg), "per_user": coo(Xu), "per_item": coo(Xi)},
        id_tags={"userId": user_ids, "itemId": item_ids},
    )


def _coords():
    return {
        "fixed": FixedEffectCoordinateConfiguration("global"),
        "per-user": RandomEffectCoordinateConfiguration(
            feature_shard="per_user",
            data=RandomEffectDataConfiguration(random_effect_type="userId"),
        ),
        "per-item": RandomEffectCoordinateConfiguration(
            feature_shard="per_item",
            data=RandomEffectDataConfiguration(random_effect_type="itemId"),
        ),
    }


def _fit(data, schedule="sync", staleness=1, plane="device", emitter=None,
         task=TaskType.LINEAR_REGRESSION, n_outer=N_OUTER):
    est = GameEstimator(
        task=task,
        coordinates=_coords(),
        num_outer_iterations=n_outer,
        score_plane=plane,
        schedule=schedule,
        staleness=staleness,
        emitter=emitter,
    )
    fit = est.fit(data)
    return est, fit


class _Recorder(EventListener):
    def __init__(self):
        self.events = []

    def on_event(self, event):
        self.events.append(event)


def _auc(scores, labels):
    order = np.argsort(scores)
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class TestAsyncTrajectories:
    def test_staleness_zero_bitwise_matches_sync(self):
        """staleness=0 reconciles before every dispatch: every solve sees
        the fully-reconciled plane, so the trajectory IS the sync one —
        identical scores and objective history, not merely close."""
        data = _problem()
        _, fit_s = _fit(data, schedule="sync")
        _, fit_a = _fit(data, schedule="async", staleness=0)
        ss = np.asarray(fit_s.model.score(data))
        sa = np.asarray(fit_a.model.score(data))
        assert np.array_equal(ss, sa)
        assert [c for c, _ in fit_s.objective_history] == [
            c for c, _ in fit_a.objective_history
        ]
        for (_, os_), (_, oa) in zip(
            fit_s.objective_history, fit_a.objective_history
        ):
            assert os_ == oa

    def test_async_auc_parity_on_holdout(self):
        """staleness=1 trains against a one-update-stale plane; with enough
        outer iterations the fit converges to the same quality — held-out
        AUC within a small tolerance of sync (the async gate)."""
        task = TaskType.LOGISTIC_REGRESSION
        data = _problem(task=task)
        holdout = _problem(seed=5, task=task, rows_per_user=8)
        _, fit_s = _fit(data, schedule="sync", task=task, n_outer=6)
        _, fit_a = _fit(
            data, schedule="async", staleness=1, task=task, n_outer=6
        )
        y = np.asarray(holdout.labels)
        auc_s = _auc(np.asarray(fit_s.model.score(holdout), np.float64), y)
        auc_a = _auc(np.asarray(fit_a.model.score(holdout), np.float64), y)
        assert abs(auc_a - auc_s) <= 0.02

    def test_async_histories_and_transfer_stats_structure(self):
        """Async keeps the sync loop's observable structure: one objective
        entry per coordinate update, one TransferStatsEvent per outer
        iteration, and zero row transfers on the device plane."""
        data = _problem()
        emitter = EventEmitter()
        rec = _Recorder()
        emitter.register_listener(rec)
        est, fit = _fit(data, schedule="async", staleness=1, emitter=emitter)
        t = est.last_transfer_stats
        assert t.score_plane == "device"
        assert t.coordinate_updates == 3 * N_OUTER
        assert t.device_plane_updates == 3 * N_OUTER
        assert t.row_transfers_h2d == 0
        assert t.row_transfers_d2h == 0
        assert len(fit.objective_history) == 3 * N_OUTER
        tevents = [e for e in rec.events if isinstance(e, TransferStatsEvent)]
        assert len(tevents) == N_OUTER
        for i, e in enumerate(tevents):
            assert e.outer_iteration == i
            assert e.device_plane_updates == 3

    def test_async_no_new_retraces_after_sync_warmup(self):
        """The async schedule reuses the sync path's pow2 program registry:
        once a sync fit has compiled every shape, an async fit on the same
        workload adds NO solver traces."""
        data = _problem(seed=3)
        _fit(data, schedule="sync")
        before = solver_trace_counts()
        _fit(data, schedule="async", staleness=1)
        assert solver_trace_counts() == before

    def test_host_plane_async_falls_back_to_sync(self):
        """The async schedule needs the device score plane (the running
        total must be safely shareable across threads); on the host plane
        the estimator runs sync — bitwise so."""
        data = _problem()
        est_a, fit_a = _fit(data, schedule="async", staleness=1, plane="host")
        assert est_a._effective_schedule() == "sync"
        _, fit_s = _fit(data, schedule="sync", plane="host")
        assert np.array_equal(
            np.asarray(fit_s.model.score(data)),
            np.asarray(fit_a.model.score(data)),
        )


class TestOverlapSpans:
    def test_overlap_spans_parent_under_outer_iter(self):
        """Worker-thread spans chain under the dispatching iteration's span
        (contextvars are copied at submit): cd/overlap parents under
        cd/outer_iter, and the solve spans opened INSIDE the worker parent
        under cd/overlap — the attribution analyze_run depends on."""
        tracer = enable_tracing(device_sync=False, clear=True)
        try:
            data = _problem()
            _fit(data, schedule="async", staleness=1)
        finally:
            disable_tracing()
        by_id = {r.span_id: r for r in tracer.spans()}
        overlaps = [r for r in tracer.spans() if r.name == "cd/overlap"]
        assert len(overlaps) == 3 * N_OUTER
        for rec in overlaps:
            assert by_id[rec.parent_id].name == "cd/outer_iter"
            assert "coordinate" in rec.attrs
        solves = [
            r for r in tracer.spans() if r.name in ("fe/solve", "re/train")
        ]
        assert solves
        for rec in solves:
            assert by_id[rec.parent_id].name == "cd/overlap"
        # reconcile spans stay on the driver, also under the iteration
        recs = [r for r in tracer.spans() if r.name == "cd/reconcile"]
        assert len(recs) == 3 * N_OUTER
        for rec in recs:
            assert by_id[rec.parent_id].name == "cd/outer_iter"


class TestBucketOverlap:
    def test_bucket_overlap_bitwise_parity(self):
        """Overlapped bucket solves are mutually independent: any
        completion order yields bitwise-identical per-bucket coefficients
        vs the sequential path."""
        from photon_ml_tpu.data import build_random_effect_dataset
        from photon_ml_tpu.estimators.random_effect import train_random_effects
        from photon_ml_tpu.opt import (
            GlmOptimizationConfiguration,
            RegularizationContext,
        )
        from photon_ml_tpu.types import RegularizationType

        rng = np.random.default_rng(0)
        n_ent, d, rows_per = 12, 4, 9
        ids, rows, cols, vals, labels = [], [], [], [], []
        r = 0
        for e in range(n_ent):
            for _ in range(rows_per):
                x = rng.normal(size=d).astype(np.float32)
                for c in range(d):
                    rows.append(r)
                    cols.append(c)
                    vals.append(float(x[c]))
                ids.append(f"e{e:03d}")
                labels.append(float(x.sum() > 0))
                r += 1
        dcfg = RandomEffectDataConfiguration(
            random_effect_type="e", num_buckets=3
        )
        ds = build_random_effect_dataset(
            ids, np.array(rows), np.array(cols),
            np.array(vals, np.float32), d,
            np.array(labels, np.float32), dcfg,
        )
        cfg = GlmOptimizationConfiguration(
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1e-3,
        )
        seq, _ = train_random_effects(ds, TaskType.LOGISTIC_REGRESSION, cfg)
        ovl, _ = train_random_effects(
            ds, TaskType.LOGISTIC_REGRESSION, cfg, overlap_buckets=2
        )
        assert len(seq.coefficients) == len(ovl.coefficients) == len(ds.buckets)
        for cs, co in zip(seq.coefficients, ovl.coefficients):
            assert np.array_equal(np.asarray(cs), np.asarray(co))


class TestValidation:
    def test_schedule_validation(self):
        with pytest.raises(ValueError, match="schedule"):
            GameEstimator(
                task=TaskType.LINEAR_REGRESSION, coordinates=_coords(),
                schedule="eager",
            )
        with pytest.raises(ValueError, match="staleness"):
            GameEstimator(
                task=TaskType.LINEAR_REGRESSION, coordinates=_coords(),
                staleness=-1,
            )
        with pytest.raises(ValueError, match="schedule"):
            CoordinateDescent({"x": object()}, num_rows=4, schedule="lazy")
        with pytest.raises(ValueError, match="staleness"):
            CoordinateDescent({"x": object()}, num_rows=4, staleness=-2)

    def test_executor_validation_and_drain(self):
        with pytest.raises(ValueError, match="max_in_flight"):
            ScheduleExecutor(max_in_flight=0)
        with ScheduleExecutor(max_in_flight=2) as ex:
            works = [ex.submit(i, lambda i=i: i * i) for i in range(5)]
            assert len(ex) == 5
            assert ex.oldest() is works[0]
            assert ex.drain() == [0, 1, 4, 9, 16]
            assert len(ex) == 0
