"""Factored random-effect (MF) coordinate tests.

Mirrors reference FactoredRandomEffectCoordinateTest /
MatrixFactorizationModelTest: kron-feature linear maps against explicit
materialization, alternating training recovering low-rank per-entity
structure, and GameEstimator integration.
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.algorithm.factored_random_effect import (
    FactoredRandomEffectCoordinate,
    KronFeatures,
    MFOptimizationConfiguration,
    _latent_dataset,
)
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.estimators.game import (
    FactoredRandomEffectCoordinateConfiguration,
    FixedEffectCoordinateConfiguration,
    GameEstimator,
)
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.types import TaskType


def _low_rank_data(n=800, d=20, entities=10, k_true=2, seed=0, noise=0.2):
    """Per-entity coefficients w_e = B v_e with a shared low-rank B."""
    rng = np.random.default_rng(seed)
    B = rng.standard_normal((d, k_true)).astype(np.float32)
    V = rng.standard_normal((entities, k_true)).astype(np.float32)
    X = (rng.standard_normal((n, d)) * (rng.random((n, d)) < 0.5)).astype(np.float32)
    e_of = np.arange(n) % entities
    z = np.einsum("nd,nd->n", X, (B @ V.T).T[e_of])
    y = (z + noise * rng.standard_normal(n) > 0).astype(np.float32)
    rows, cols = np.nonzero(X)
    return X, rows, cols, X[rows, cols], y, e_of


def _dataset(seed=0, **kw):
    X, rows, cols, vals, y, e_of = _low_rank_data(seed=seed, **kw)
    ids = np.array([f"e{e}" for e in e_of])
    ds = build_random_effect_dataset(
        entity_ids=ids,
        feature_rows=rows,
        feature_cols=cols,
        feature_vals=vals,
        global_dim=X.shape[1],
        labels=y,
        config=RandomEffectDataConfiguration(random_effect_type="e"),
    )
    return ds, X, y, ids


class TestKronFeatures:
    def _explicit(self, ds, latents, d, k):
        """Materialize the [n, d*k] kron design matrix row-block by row-block."""
        mats = []
        for b, bucket in enumerate(ds.buckets):
            Xb = np.asarray(bucket.X)
            pidx = np.asarray(bucket.proj_indices)
            v = np.asarray(latents[b])
            E, S, D = Xb.shape
            out = np.zeros((E * S, d * k), dtype=np.float32)
            for e in range(E):
                xg = np.zeros((S, d), np.float32)
                for j in range(D):
                    xg[:, pidx[e, j]] += Xb[e, :, j]
                out[e * S : (e + 1) * S] = np.einsum(
                    "sd,k->sdk", xg, v[e]
                ).reshape(S, d * k)
            mats.append(out)
        return np.concatenate(mats)

    def test_linear_maps_match_explicit(self):
        ds, X, y, ids = _dataset(n=120, entities=4)
        d = X.shape[1]
        k = 3
        rng = np.random.default_rng(1)
        latents = [
            jnp.asarray(rng.standard_normal((b.num_entities, k)).astype(np.float32))
            for b in ds.buckets
        ]
        feats = KronFeatures(
            xs=[b.X for b in ds.buckets],
            pidxs=[b.proj_indices for b in ds.buckets],
            latents=latents,
            d_global=d,
            k=k,
        )
        M = self._explicit(ds, latents, d, k)
        w = rng.standard_normal(d * k).astype(np.float32)
        c = rng.standard_normal(M.shape[0]).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(feats.matvec(jnp.asarray(w))), M @ w, rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(feats.rmatvec(jnp.asarray(c))), M.T @ c, rtol=2e-4, atol=2e-3
        )
        np.testing.assert_allclose(
            np.asarray(feats.rmatvec_sq(jnp.asarray(c))),
            (M * M).T @ c,
            rtol=2e-4,
            atol=2e-3,
        )
        np.testing.assert_allclose(
            np.asarray(feats.row_norms_sq()),
            np.sum(M * M, axis=1),
            rtol=2e-4,
            atol=2e-4,
        )


class TestFactoredCoordinate:
    def test_alternating_training_fits(self):
        ds, X, y, ids = _dataset()
        coord = FactoredRandomEffectCoordinate(
            dataset=ds,
            task=TaskType.LOGISTIC_REGRESSION,
            re_configuration=GlmOptimizationConfiguration(regularization_weight=0.1),
            matrix_configuration=GlmOptimizationConfiguration(regularization_weight=0.1),
            mf_configuration=MFOptimizationConfiguration(
                num_latent_factors=4, num_iterations=2
            ),
            base_offsets=np.zeros(len(y), np.float32),
        )
        model = coord.update_model(None, np.zeros(len(y), np.float32))
        scores = coord.score(model)
        acc = float(np.mean((scores > 0) == (y > 0.5)))
        assert acc > 0.85, acc
        # warm-started second update improves or holds
        model2 = coord.update_model(model, np.zeros(len(y), np.float32))
        acc2 = float(np.mean((coord.score(model2) > 0) == (y > 0.5)))
        assert acc2 > 0.85

    def test_random_projected_dataset_rejected(self):
        from photon_ml_tpu.projector import ProjectorType

        X, rows, cols, vals, y, e_of = _low_rank_data(n=60, entities=3)
        ids = np.array([f"e{e}" for e in e_of])
        ds = build_random_effect_dataset(
            entity_ids=ids, feature_rows=rows, feature_cols=cols,
            feature_vals=vals, global_dim=X.shape[1], labels=y,
            config=RandomEffectDataConfiguration(
                random_effect_type="e",
                projector=ProjectorType.RANDOM,
                projected_dim=4,
            ),
        )
        with pytest.raises(ValueError, match="INDEX_MAP or"):
            FactoredRandomEffectCoordinate(
                dataset=ds,
                task=TaskType.LOGISTIC_REGRESSION,
                re_configuration=GlmOptimizationConfiguration(),
                matrix_configuration=GlmOptimizationConfiguration(),
                mf_configuration=MFOptimizationConfiguration(num_latent_factors=2),
                base_offsets=np.zeros(len(y), np.float32),
            )

    def test_latent_dataset_projection(self):
        ds, X, y, ids = _dataset(n=60, entities=3)
        d = X.shape[1]
        B = jnp.asarray(
            np.random.default_rng(0).standard_normal((d, 2)).astype(np.float32)
        )
        lds = _latent_dataset(ds, B)
        b0, l0 = ds.buckets[0], lds.buckets[0]
        Bg = np.asarray(B)[np.asarray(b0.proj_indices)]
        expected = np.einsum("esd,edk->esk", np.asarray(b0.X), Bg)
        np.testing.assert_allclose(np.asarray(l0.X), expected, rtol=1e-4, atol=1e-5)

    def test_model_export(self):
        ds, X, y, ids = _dataset(n=200, entities=5)
        coord = FactoredRandomEffectCoordinate(
            dataset=ds,
            task=TaskType.LOGISTIC_REGRESSION,
            re_configuration=GlmOptimizationConfiguration(regularization_weight=1.0),
            matrix_configuration=GlmOptimizationConfiguration(regularization_weight=1.0),
            mf_configuration=MFOptimizationConfiguration(num_latent_factors=2),
            base_offsets=np.zeros(len(y), np.float32),
        )
        model = coord.update_model(None, np.zeros(len(y), np.float32))
        w = model.coefficients_for("e0")
        assert w is not None and len(w) == X.shape[1]
        assert model.coefficients_for("unseen") is None


class TestGameWithFactoredCoordinate:
    def test_fe_plus_factored_re(self):
        X, rows, cols, vals, y, e_of = _low_rank_data(n=600, entities=8, seed=3)
        ids = np.array([f"e{e}" for e in e_of])
        data = GameData(
            labels=y,
            feature_shards={
                "global": FeatureShard(
                    rows=rows, cols=cols, vals=vals, dim=X.shape[1]
                )
            },
            id_tags={"e": ids},
        )
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={
                "fixed": FixedEffectCoordinateConfiguration(
                    feature_shard="global",
                    optimizer=GlmOptimizationConfiguration(regularization_weight=1.0),
                ),
                "factored": FactoredRandomEffectCoordinateConfiguration(
                    feature_shard="global",
                    data=RandomEffectDataConfiguration(random_effect_type="e"),
                    mf=MFOptimizationConfiguration(num_latent_factors=3),
                    optimizer=GlmOptimizationConfiguration(regularization_weight=0.5),
                ),
            },
            num_outer_iterations=2,
        )
        fit = est.fit(data, validation_data=data)
        assert fit.validation_metric is not None
        assert fit.validation_metric > 0.85  # AUC on train-as-validation
        # scoring via GameModel covers the factored path
        scores = fit.model.score(data)
        assert scores.shape == (len(y),)


class TestMatrixFactorizationModel:
    def _model(self):
        return MatrixFactorizationModel(
            row_effect_type="user",
            col_effect_type="item",
            row_factors=np.array([[1.0, 2.0], [0.5, -1.0]], np.float32),
            col_factors=np.array([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]], np.float32),
            row_index={"u0": 0, "u1": 1},
            col_index={"i0": 0, "i1": 1, "i2": 2},
        )

    def test_score(self):
        m = self._model()
        assert m.score("u0", "i0") == 1.0
        assert m.score("u0", "i2") == 3.0
        assert m.score("u9", "i0") == 0.0  # unseen -> 0

    def test_score_batch(self):
        m = self._model()
        out = m.score_batch(["u0", "u1", "zz"], ["i1", "i2", "i0"])
        np.testing.assert_allclose(out, [2.0, -0.5, 0.0])

    def test_latent_dim_mismatch_rejected(self):
        with pytest.raises(ValueError, match="latent dimension"):
            MatrixFactorizationModel(
                row_effect_type="u",
                col_effect_type="i",
                row_factors=np.zeros((1, 2), np.float32),
                col_factors=np.zeros((1, 3), np.float32),
                row_index={},
                col_index={},
            )
