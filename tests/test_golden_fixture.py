"""Golden-fixture end-to-end driver tests.

Reference parity: cli/game/training/DriverTest.scala — the real driver runs
on a committed ratings fixture (the reference's Yahoo! Music train/test
avro) and asserts held-out RMSE below captured baselines ("baseline RMSE
capture from an assumed-correct implementation", DriverTest.scala:84-85):
fixed-effect-only, random-effects-only, fixed+random, normalization,
off-heap index path, and bad-input failure cases.

Captured baselines (this implementation, 2026-07-29, CPU float32):
  FE only           RMSE 0.8274
  RE only           RMSE 0.3905
  FE + user/movie   RMSE 0.3885
  FE + RE + stdz    RMSE 0.3875
Thresholds below leave ~10-15% headroom, like the reference's gates.

These captures are additionally anchored to INDEPENDENT oracles in
test_oracle.py (scipy L-BFGS-B / sklearn / float64 closed forms on the
same fixture and objective), so a systematic math bug shared by the
capture run and these gates would still be caught there.
"""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

HERE = os.path.join(os.path.dirname(__file__), "fixtures", "ratings")

FIXED = {
    "type": "fixed",
    "feature_shard": "global",
    "optimizer": {
        "optimizer": "TRON",
        "regularization": "L2",
        "regularization_weight": 10.0,
    },
}
PER_USER = {
    "type": "random",
    "feature_shard": "per_user",
    "random_effect_type": "userId",
    "optimizer": {"regularization": "L2", "regularization_weight": 1.0},
}
PER_MOVIE = {
    "type": "random",
    "feature_shard": "per_movie",
    "random_effect_type": "movieId",
    "optimizer": {"regularization": "L2", "regularization_weight": 1.0},
}


def _config(tmp_path, coordinates, update_order):
    cfg = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {"feature_bags": ["userFeatures"], "add_intercept": False},
            "per_movie": {"feature_bags": ["movieFeatures"], "add_intercept": False},
        },
        "coordinates": coordinates,
        "update_order": update_order,
    }
    p = tmp_path / "game.json"
    p.write_text(json.dumps(cfg))
    return str(p)


def _train(tmp_path, coordinates, update_order, extra=()):
    from photon_ml_tpu.cli.train_game import parse_args, run

    return run(parse_args([
        "--train-data-dirs", os.path.join(HERE, "train"),
        "--validation-data-dirs", os.path.join(HERE, "test"),
        "--coordinate-config", _config(tmp_path, coordinates, update_order),
        "--task", "LINEAR_REGRESSION",
        "--output-dir", str(tmp_path / "out"),
        "--evaluator", "RMSE",
        "--num-outer-iterations", "2",
        *extra,
    ]))


class TestGoldenRatings:
    def test_fixed_effect_only(self, tmp_path):
        fit = _train(tmp_path, {"fixed": FIXED}, ["fixed"])
        assert fit.validation_metric < 0.95  # captured 0.8274

    def test_random_effects_only(self, tmp_path):
        fit = _train(
            tmp_path,
            {"per_user": PER_USER, "per_movie": PER_MOVIE},
            ["per_user", "per_movie"],
        )
        assert fit.validation_metric < 0.45  # captured 0.3905

    def test_fixed_and_random_effects(self, tmp_path):
        fit = _train(
            tmp_path,
            {"fixed": FIXED, "per_user": PER_USER, "per_movie": PER_MOVIE},
            ["fixed", "per_user", "per_movie"],
        )
        assert fit.validation_metric < 0.45  # captured 0.3885
        # the full GLMix must beat fixed-effect-only decisively
        fe_only = _train(tmp_path, {"fixed": FIXED}, ["fixed"])
        assert fit.validation_metric < fe_only.validation_metric - 0.3

    def test_fused_engine_same_result(self, tmp_path):
        """The full GLMix through the fused permutation engine (interpret-
        mode kernels on CPU) must hit the same golden RMSE gate."""
        from photon_ml_tpu.ops import fused_perm

        old = fused_perm._INTERPRET
        fused_perm._INTERPRET = True
        try:
            fused = dict(FIXED, sparse_engine="fused")
            fit = _train(
                tmp_path,
                {"fixed": fused, "per_user": PER_USER, "per_movie": PER_MOVIE},
                ["fixed", "per_user", "per_movie"],
            )
        finally:
            fused_perm._INTERPRET = old
        assert fit.validation_metric < 0.45  # captured 0.3885 (ELL engine)

    def test_multiple_optimizer_configs(self, tmp_path):
        """Reference DriverTest.scala:324-338 "multiple optimizer configs":
        the fixed coordinate sweeps λ ∈ {10, 1e7}; the driver fits one GAME
        model per config and the saved best must hit the same golden gate
        (λ=1e7 crushes the fixed effect and cannot win)."""
        fixed_sweep = json.loads(json.dumps(FIXED))
        fixed_sweep["optimizer"].pop("regularization_weight")
        fixed_sweep["optimizer"]["regularization_weights"] = [10.0, 1e7]
        fit = _train(
            tmp_path,
            {"fixed": fixed_sweep, "per_user": PER_USER, "per_movie": PER_MOVIE},
            ["fixed", "per_user", "per_movie"],
        )
        assert fit.validation_metric < 0.45  # captured 0.3885 (single config)

    def test_standardization_matches_unnormalized(self, tmp_path):
        fit = _train(
            tmp_path,
            {"fixed": FIXED, "per_user": PER_USER, "per_movie": PER_MOVIE},
            ["fixed", "per_user", "per_movie"],
            extra=("--normalization-type", "STANDARDIZATION"),
        )
        assert fit.validation_metric < 0.45  # captured 0.3875

    def test_offheap_index_path_same_result(self, tmp_path):
        """PalDB-equivalent off-heap index maps reach the same RMSE
        (reference DriverTest.scala:379-411)."""
        from photon_ml_tpu.cli.build_index import parse_args as iargs
        from photon_ml_tpu.cli.build_index import run as irun

        # all shards indexed with an intercept slot; shards whose read config
        # has add_intercept=False simply never populate it
        idx = tmp_path / "idx"
        irun(iargs([
            "--data-dirs", os.path.join(HERE, "train"),
            "--output-dir", str(idx),
            "--feature-shard", "global=features",
            "--feature-shard", "per_user=userFeatures",
            "--feature-shard", "per_movie=movieFeatures",
        ]))
        from photon_ml_tpu.cli.train_game import parse_args, run

        fit = run(parse_args([
            "--train-data-dirs", os.path.join(HERE, "train"),
            "--validation-data-dirs", os.path.join(HERE, "test"),
            "--coordinate-config", _config(
                tmp_path,
                {"fixed": FIXED, "per_user": PER_USER, "per_movie": PER_MOVIE},
                ["fixed", "per_user", "per_movie"],
            ),
            "--task", "LINEAR_REGRESSION",
            "--output-dir", str(tmp_path / "out_offheap"),
            "--evaluator", "RMSE",
            "--num-outer-iterations", "2",
            "--offheap-indexmap-dir", str(tmp_path / "idx"),
        ]))
        assert fit.validation_metric < 0.45

        # scoring through the same off-heap stores (reference scoring
        # Params --offheap-indexmap-dir) must hit the same gate
        from photon_ml_tpu.cli.score_game import parse_args as sargs
        from photon_ml_tpu.cli.score_game import run as srun

        metric = srun(sargs([
            "--data-dirs", os.path.join(HERE, "test"),
            "--model-dir", str(tmp_path / "out_offheap" / "best"),
            "--output-dir", str(tmp_path / "scores_offheap"),
            "--evaluator", "RMSE",
            "--offheap-indexmap-dir", str(tmp_path / "idx"),
        ]))
        assert metric < 0.45

    def test_scoring_round_trip_on_fixture(self, tmp_path):
        from photon_ml_tpu.cli.score_game import parse_args as sargs
        from photon_ml_tpu.cli.score_game import run as srun

        _train(
            tmp_path,
            {"fixed": FIXED, "per_user": PER_USER, "per_movie": PER_MOVIE},
            ["fixed", "per_user", "per_movie"],
        )
        metric = srun(sargs([
            "--data-dirs", os.path.join(HERE, "test"),
            "--model-dir", str(tmp_path / "out" / "best"),
            "--output-dir", str(tmp_path / "scores"),
            "--evaluator", "RMSE",
        ]))
        assert metric < 0.45

    def test_bad_weights_fail_validation(self, tmp_path):
        """Negative weights must fail fast (reference
        DriverTest.scala:470-496 failure cases)."""
        from photon_ml_tpu.data.validators import DataValidationError
        from photon_ml_tpu.io.avro import read_avro_file
        from photon_ml_tpu.io.data_reader import write_training_examples
        from photon_ml_tpu.cli.train_glm import parse_args, run

        recs = []
        for i, rec in enumerate(
            read_avro_file(os.path.join(HERE, "train", "part-00000.avro"))
        ):
            rec["weight"] = -1.0 if i % 5 == 0 else 1.0
            rec["features"] = [
                (f["name"], f["term"], f["value"]) for f in rec["features"]
            ]
            del rec["userFeatures"], rec["movieFeatures"]
            recs.append(rec)
            if i >= 100:
                break
        bad = tmp_path / "bad"
        bad.mkdir()
        write_training_examples(str(bad / "part-00000.avro"), recs)
        with pytest.raises(DataValidationError):
            run(parse_args([
                "--training-data-dirs", str(bad),
                "--task", "LINEAR_REGRESSION",
                "--output-dir", str(tmp_path / "bad_out"),
            ]))
