"""Ledger-replay analyzer tests: phase classification, exclusive-time and
bubble accounting on synthetic ledgers (fast lane), the analyze_run CLI
contract, and the driver-level gate — a tiny traced train whose ledger
replays into a report that attributes ≥95% of wall-clock (slow lane; CI's
analyze smoke gate runs the same CLI invocation)."""

import json
import time

import numpy as np
import pytest

from photon_ml_tpu.telemetry import (
    RunReport,
    TruncatedLedgerWarning,
    analyze_ledger,
    analyze_records,
    classify_span,
    format_report,
    get_registry,
)
from photon_ml_tpu.telemetry.analyze import PHASES
from photon_ml_tpu.telemetry.span import disable_tracing, span


def _write_ledger(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _span(name, sid, start, dur, parent=None, failed=False):
    return {
        "type": "span", "ts": start + dur, "name": name,
        "path": name if parent is None else f"parent/{name}",
        "span_id": sid, "parent_id": parent, "start_unix": start,
        "duration_s": dur, "thread": "MainThread", "failed": failed,
        "error": None, "attrs": {},
    }


def _synthetic_records():
    """10s run: one cd root span (8s) holding a 2s fe solve and a 3s re
    solve, so cd exclusive time is 3s and 2s of wall is bubble."""
    return [
        {"type": "meta", "ts": 1000.0, "phase": "start", "label": "synth"},
        _span("fe/solve", 2, 1000.5, 2.0, parent=1),
        _span("re/train", 3, 1003.0, 3.0, parent=1),
        _span("cd/run", 1, 1000.0, 8.0),
        {
            "type": "metrics", "ts": 1009.9,
            "snapshot": {
                "counters": {
                    "transfer.row_transfers_h2d": 4,
                    "jit.traces.fe_solve": 2,
                },
                "gauges": {"serving.batch_fill": {"last": 0.5, "peak": 0.9}},
                "histograms": {"lat": {"count": 3, "mean": 1.5, "max": 2.0}},
            },
        },
        {"type": "meta", "ts": 1010.0, "phase": "finish"},
    ]


class TestClassifier:
    @pytest.mark.parametrize("name,phase", [
        ("fe/solve", "fe_solve"),
        ("re/adaptive_round", "re_solve"),
        ("cd/outer_iter", "cd_driver"),
        ("serve/score_batch", "serving"),
        ("incremental/update", "incremental"),
        ("h2d row push", "transfers"),
        ("read training data", "io"),
        ("save model", "io"),
        ("pack artifact", "io"),
        ("hyperparameter tuning", "host_driver"),
        ("fit", "host_driver"),
    ])
    def test_name_to_phase(self, name, phase):
        assert classify_span(name) == phase

    def test_every_phase_is_canonical(self):
        for name in ("fe/x", "re/x", "cd/x", "serve/x", "incremental/x",
                     "transfer", "load artifact", "anything else"):
            assert classify_span(name) in PHASES


class TestAccounting:
    def test_exclusive_time_bubble_and_coverage(self):
        report = analyze_records(_synthetic_records())
        assert report.label == "synth"
        assert report.wall_clock_s == pytest.approx(10.0)
        # parent's exclusive time excludes both direct children
        assert report.phase_seconds("cd_driver") == pytest.approx(3.0)
        assert report.phase_seconds("fe_solve") == pytest.approx(2.0)
        assert report.phase_seconds("re_solve") == pytest.approx(3.0)
        # 10s wall minus the 8s root interval = 2s of host-driver bubble
        assert report.bubble_s == pytest.approx(2.0)
        assert report.attributed_s == pytest.approx(10.0)
        assert report.coverage == pytest.approx(1.0)
        assert report.num_spans == 3 and report.failed_spans == 0
        # joins from the metrics record
        assert report.transfers == {"row_transfers_h2d": 4}
        assert report.jit_traces == {"fe_solve": 2}

    def test_concurrent_siblings_split_not_double_counted(self):
        """Async-schedule ledgers hold sibling spans that genuinely run at
        the same time. The sweep-line splits each concurrent segment evenly
        (coverage stays ~1.0 instead of blowing past it) and reports the
        concurrency as per-phase overlap: fe 1000.5-1006.5 and re
        1001-1007 share 5.5s; each phase keeps busy_s=6.0 but only 3.25s
        of attributed wall, with 2.75s each surfaced as overlap_s."""
        records = [
            {"type": "meta", "ts": 1000.0, "phase": "start",
             "label": "overlap"},
            _span("fe/solve", 2, 1000.5, 6.0, parent=1),
            _span("re/train", 3, 1001.0, 6.0, parent=1),
            _span("cd/run", 1, 1000.0, 8.0),
            {"type": "meta", "ts": 1010.0, "phase": "finish"},
        ]
        report = analyze_records(records)
        assert report.wall_clock_s == pytest.approx(10.0)
        # each 6s sibling keeps its full busy time...
        assert report.phases["fe_solve"]["busy_s"] == pytest.approx(6.0)
        assert report.phases["re_solve"]["busy_s"] == pytest.approx(6.0)
        # ...but attributed wall splits the shared 5.5s segment two ways:
        # 0.5s solo + 5.5/2 shared = 3.25s apiece
        assert report.phase_seconds("fe_solve") == pytest.approx(3.25)
        assert report.phase_seconds("re_solve") == pytest.approx(3.25)
        assert report.phase_overlap("fe_solve") == pytest.approx(2.75)
        assert report.phase_overlap("re_solve") == pytest.approx(2.75)
        # the root's exclusive tails (1000-1000.5, 1007-1008) have no
        # concurrency at all
        assert report.phase_seconds("cd_driver") == pytest.approx(1.5)
        assert report.phase_overlap("cd_driver") == pytest.approx(0.0)
        assert report.overlap_s == pytest.approx(5.5)
        # attribution stays exact: 8s of spans + 2s bubble = the 10s wall
        assert report.attributed_s == pytest.approx(10.0)
        assert report.bubble_s == pytest.approx(2.0)
        assert report.coverage == pytest.approx(1.0)

    def test_missing_finish_warns_and_measures_to_last_span(self):
        records = [r for r in _synthetic_records()
                   if not (r["type"] == "meta" and r["phase"] == "finish")]
        report = analyze_records(records)
        assert any("no finish record" in w for w in report.warnings)
        assert report.wall_clock_s == pytest.approx(8.0)  # last span end

    def test_solver_event_join(self):
        records = _synthetic_records()
        records.insert(2, {
            "type": "event", "ts": 1002.0, "event": "SolverStatsEvent",
            "fields": {
                "num_entities": 8, "rounds": 3,
                "executed_lane_iterations": 100,
                "lockstep_lane_iterations": 250,
                "chunk_retraces": 1, "converged": False,
            },
        })
        report = analyze_records(records)
        assert report.solver["entities"] == 8
        assert report.solver["lane_iteration_savings"] == pytest.approx(2.5)
        assert report.solver["unconverged_buckets"] == 1
        assert report.events["SolverStatsEvent"] == 1

    def test_failed_span_counted(self):
        records = _synthetic_records()
        records.append(_span("cd/objective", 9, 1008.5, 0.5, failed=True))
        report = analyze_records(records)
        assert report.failed_spans == 1

    def test_round_trip_and_metric_lookup(self):
        report = analyze_records(_synthetic_records())
        d = report.to_dict()
        d["unknown_future_key"] = 1  # forward-compat: ignored on load
        back = RunReport.from_dict(d)
        assert back.phases == report.phases
        assert back.coverage == report.coverage
        # counters, then gauge last-values, then histogram means
        assert back.metric("transfer.row_transfers_h2d") == 4.0
        assert back.metric("serving.batch_fill") == 0.5
        assert back.metric("lat") == 1.5
        assert back.metric("nope") is None

    def test_format_report_renders(self):
        text = format_report(analyze_records(_synthetic_records()))
        assert "cd_driver" in text and "coverage" in text
        assert "(bubbles)" in text


class TestLedgerReplay:
    def test_analyze_ledger_truncated_tail(self, tmp_path):
        path = tmp_path / "ledger.jsonl"
        _write_ledger(path, _synthetic_records())
        with open(path, "a") as f:
            f.write('{"type": "span", "name": "cut-mid-wr')  # no newline
        report = analyze_ledger(str(path))
        assert report.num_spans == 3  # the valid prefix
        assert any("partial record" in w for w in report.warnings)

    def test_live_session_coverage(self, tmp_path):
        """A real start_run session (spans + registry + checkpoint) replays
        with ≥95% attribution — the same bar as the CI analyze gate."""
        from photon_ml_tpu.telemetry import start_run

        get_registry().reset()
        ledger = tmp_path / "live.jsonl"
        run = start_run("live", ledger_path=str(ledger), device_sync=False)
        try:
            with span("cd/run"):
                with span("fe/solve"):
                    time.sleep(0.02)
                with span("re/train"):
                    time.sleep(0.02)
            run.checkpoint("mid")
            with span("read data"):
                time.sleep(0.01)
            run.finish()
        finally:
            disable_tracing()
        report = analyze_ledger(str(ledger))
        assert report.num_spans == 4  # checkpoint must not double-write
        assert 0.95 <= report.coverage <= 1.05
        assert report.phase_seconds("fe_solve") > 0
        assert report.phase_seconds("io") > 0


class TestAnalyzeRunCli:
    def _ledger(self, tmp_path):
        return _write_ledger(tmp_path / "l.jsonl", _synthetic_records())

    def test_report_json_and_coverage_gate(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "report.json"
        rc = main([
            self._ledger(tmp_path),
            "--json", str(out), "--check-coverage", "0.95",
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["coverage"] == pytest.approx(1.0)
        assert capsys.readouterr().out  # human table still printed

    def test_coverage_gate_fails_on_gaps(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        # drop the root span: 5s of child spans against a 10s wall
        records = [r for r in _synthetic_records()
                   if r.get("name") != "cd/run"]
        path = _write_ledger(tmp_path / "gappy.jsonl", records)
        assert main([path, "--check-coverage", "0.95"]) == 1
        assert "coverage" in capsys.readouterr().out.lower()

    def test_propose_covers_knob_space(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "proposal.json"
        rc = main([
            self._ledger(tmp_path), "--quiet", "--propose-json", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert len(doc["knobs"]) >= 4  # the declared knob space, audited
        for name, knob in doc["knobs"].items():
            assert knob["rationale"], name


def _cluster_records():
    """2-host / 3-pass cluster ledger: host 1 is the consistent straggler
    (arrives last every pass), pass walls decompose exactly into busy +
    allreduce wait + coordinator bubble, and a rebalance event rides
    along — the merged multi-host shape train_game --hosts emits."""
    recs = [
        {"type": "meta", "ts": 2000.0, "phase": "start", "label": "mh"},
    ]
    ts = 2000.5
    for pass_id in range(3):
        # host 0 finishes at 0.8s, host 1 at 1.0s; coordinator folds for
        # another 0.1s -> wall 1.1 = busy 0.8 + wait 0.2 + bubble 0.1
        recs.append({
            "type": "progress", "ts": ts, "kind": "cluster_pass",
            "outer": 0, "coordinate": "fixed", "pass_id": pass_id,
            "wall_s": 1.1, "busy_s": 0.8, "allreduce_wait_s": 0.2,
            "bubble_s": 0.1, "straggler_index": 1.1 + 0.01 * pass_id,
            "straggler_host": 1, "hosts": 2, "blocks": 8,
            "stray_partials": 1 if pass_id == 0 else 0,
            "requeued_blocks": 0,
        })
        for host, busy, wall, share in (
            (0, 0.78, 0.8, 0.52), (1, 0.97, 1.0, 0.48),
        ):
            recs.append({
                "type": "progress", "ts": ts + 0.001, "kind": "host_pass",
                "outer": 0, "coordinate": "fixed", "pass_id": pass_id,
                "host": host, "busy_s": busy, "wall_s": wall, "blocks": 4,
                "frags": 1, "decode_s": 0.3, "solve_s": 0.45,
                "reply_s": 0.03, "h2d_bytes": 1_000_000,
                "predicted_share": 0.5, "actual_share": share,
            })
        ts += 1.2
    recs.append({
        "type": "progress", "ts": ts, "kind": "cluster",
        "outer": 0, "coordinate": "fixed", "event": "rebalance",
    })
    recs.append({"type": "meta", "ts": 2005.0, "phase": "finish"})
    return recs


class TestClusterReport:
    def test_two_host_attribution_and_coverage(self):
        """The tentpole contract: ≥95% of each pass's wall attributed to
        busy / allreduce wait / bubble, per-host busy+blocks joined."""
        report = analyze_records(_cluster_records())
        cl = report.cluster
        assert cl is not None
        assert cl["num_passes"] == 3
        assert cl["num_hosts"] == 2
        # decomposition is exact by construction -> coverage ~1.0
        assert cl["attribution_coverage"] == pytest.approx(1.0, abs=1e-6)
        for p in cl["passes"]:
            assert p["attribution_coverage"] == pytest.approx(1.0, abs=1e-6)
        assert cl["busy_frac"] == pytest.approx(0.8 / 1.1, abs=1e-4)
        assert cl["comm_wait_frac"] == pytest.approx(0.2 / 1.1, abs=1e-4)
        # per-host attribution: both hosts present with busy time + blocks
        assert set(cl["hosts"]) == {"0", "1"}
        for h in cl["hosts"].values():
            assert h["passes"] == 3
            assert h["busy_s"] > 0
            assert h["blocks"] == 12
            assert h["h2d_bytes"] == 3_000_000
        # share_error = mean |predicted - actual| = |0.5 - 0.52|
        assert cl["hosts"]["0"]["share_error"] == pytest.approx(0.02)
        assert cl["hosts"]["1"]["share_error"] == pytest.approx(0.02)

    def test_straggler_ranking_trend_and_events(self):
        cl = analyze_records(_cluster_records()).cluster
        # host 1 was the last arrival in every pass
        assert cl["straggler_ranking"][0] == "1"
        assert cl["hosts"]["1"]["times_straggler"] == 3
        assert cl["hosts"]["0"]["times_straggler"] == 0
        assert cl["imbalance_trend"] == [1.1, 1.11, 1.12]
        assert cl["straggler_index_mean"] == pytest.approx(1.11)
        assert cl["stray_partials"] == 1
        assert cl["events"] == {"rebalance": 1}

    def test_no_cluster_records_means_none(self):
        from photon_ml_tpu.telemetry import cluster_report

        report = analyze_records(_synthetic_records())
        assert report.cluster is None
        assert cluster_report(_synthetic_records()) is None

    def test_report_round_trips_through_json(self):
        report = analyze_records(_cluster_records())
        doc = json.loads(json.dumps(report.to_dict()))
        assert doc["cluster"]["num_hosts"] == 2
        assert doc["cluster"]["attribution_coverage"] == pytest.approx(1.0)

    def test_format_cluster_report_renders_tables(self):
        from photon_ml_tpu.telemetry import format_cluster_report

        text = format_cluster_report(
            analyze_records(_cluster_records()).cluster
        )
        assert "cluster plane: 3 distributed pass(es) over 2 host(s)" in text
        assert "allreduce wait" in text
        assert "straggler ranking (worst first): host 1, host 0" in text
        assert "imbalance trend" in text
        assert "stray partials dropped: 1" in text
        # the one-line pointer also lands in the main report
        assert "analyze_run --cluster" in format_report(report=analyze_records(
            _cluster_records()
        ))

    def test_truncated_worker_ledger_tolerated(self, tmp_path):
        """A chaos-killed worker leaves a ledger cut mid-write; the merged
        analysis must still build the cluster report from the valid
        prefix (warn, don't crash)."""
        path = _write_ledger(tmp_path / "cut.jsonl", _cluster_records())
        with open(path, "a") as f:
            f.write('{"type": "progress", "kind": "host_pa')  # no newline
        report = analyze_ledger(path)
        assert any("partial record" in w for w in report.warnings)
        assert report.cluster is not None
        assert report.cluster["num_passes"] == 3
        assert report.cluster["num_hosts"] == 2

    def test_analyze_run_cluster_flag(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        path = _write_ledger(tmp_path / "mh.jsonl", _cluster_records())
        assert main([path, "--cluster"]) == 0
        out = capsys.readouterr().out
        assert "cluster plane: 3 distributed pass(es)" in out
        assert "straggler ranking" in out

    def test_analyze_run_cluster_flag_without_records(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        path = _write_ledger(tmp_path / "plain.jsonl", _synthetic_records())
        assert main([path, "--cluster"]) == 1
        assert "no cluster_pass records" in capsys.readouterr().err


@pytest.mark.slow
class TestAnalyzeTrainGate:
    @pytest.fixture(scope="class")
    def traced_train(self, tmp_path_factory):
        """Tiny traced CPU train (same fixture recipe as the telemetry
        smoke gate) -> ledger path."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.data_reader import write_training_examples

        root = tmp_path_factory.mktemp("analyze_train")
        rng = np.random.default_rng(7)
        n_users, dg, du = 6, 4, 3
        records = []
        for i in range(n_users * 8):
            user = f"user{i % n_users}"
            xg = rng.normal(size=dg)
            xu = rng.normal(size=du)
            y = 1.0 if (xg.sum() + xu.sum()) > 0 else 0.0
            records.append({
                "uid": f"r{i}", "label": y,
                "features": [("g", str(j), xg[j]) for j in range(dg)],
                "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                "metadataMap": {"userId": user},
            })
        train_dir = root / "train"
        train_dir.mkdir()
        write_training_examples(str(train_dir / "part-00000.avro"), records)
        config = {
            "feature_shards": {
                "global": {"feature_bags": ["features"],
                           "add_intercept": True},
                "per_user": {"feature_bags": ["userFeatures"],
                             "add_intercept": False},
            },
            "coordinates": {
                "fixed": {
                    "type": "fixed", "feature_shard": "global",
                    "optimizer": {"optimizer": "LBFGS",
                                  "regularization": "L2",
                                  "regularization_weight": 0.1},
                },
                "per_user": {
                    "type": "random", "feature_shard": "per_user",
                    "random_effect_type": "userId",
                    "optimizer": {
                        "optimizer": "LBFGS", "regularization": "L2",
                        "regularization_weight": 1.0,
                        "adaptive": {"enabled": True, "chunk_iters": 4,
                                     "min_lanes": 2},
                    },
                },
            },
            "update_order": ["fixed", "per_user"],
        }
        cfg = root / "game.json"
        cfg.write_text(json.dumps(config))
        ledger = root / "train-ledger.jsonl"
        run(parse_args([
            "--train-data-dirs", str(train_dir),
            "--coordinate-config", str(cfg),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(root / "model"),
            "--telemetry-out", str(ledger),
        ]))
        return str(ledger)

    def test_train_ledger_attributes_wall_clock(self, traced_train):
        report = analyze_ledger(traced_train)
        # phase durations sum within 5% of measured wall-clock (two-sided:
        # >1 would mean concurrent trees double-counting)
        assert 0.95 <= report.coverage <= 1.05, report.to_dict()
        assert report.phase_seconds("re_solve") > 0
        assert report.phase_seconds("cd_driver") > 0
        assert report.phase_seconds("io") > 0
        assert report.events.get("SolverStatsEvent", 0) > 0

    def test_analyze_run_cli_gate(self, traced_train, tmp_path):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "proposal.json"
        rc = main([
            traced_train, "--check-coverage", "0.95",
            "--propose-json", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert len(doc["knobs"]) >= 4
