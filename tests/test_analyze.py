"""Ledger-replay analyzer tests: phase classification, exclusive-time and
bubble accounting on synthetic ledgers (fast lane), the analyze_run CLI
contract, and the driver-level gate — a tiny traced train whose ledger
replays into a report that attributes ≥95% of wall-clock (slow lane; CI's
analyze smoke gate runs the same CLI invocation)."""

import json
import time

import numpy as np
import pytest

from photon_ml_tpu.telemetry import (
    RunReport,
    TruncatedLedgerWarning,
    analyze_ledger,
    analyze_records,
    classify_span,
    format_report,
    get_registry,
)
from photon_ml_tpu.telemetry.analyze import PHASES
from photon_ml_tpu.telemetry.span import disable_tracing, span


def _write_ledger(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _span(name, sid, start, dur, parent=None, failed=False):
    return {
        "type": "span", "ts": start + dur, "name": name,
        "path": name if parent is None else f"parent/{name}",
        "span_id": sid, "parent_id": parent, "start_unix": start,
        "duration_s": dur, "thread": "MainThread", "failed": failed,
        "error": None, "attrs": {},
    }


def _synthetic_records():
    """10s run: one cd root span (8s) holding a 2s fe solve and a 3s re
    solve, so cd exclusive time is 3s and 2s of wall is bubble."""
    return [
        {"type": "meta", "ts": 1000.0, "phase": "start", "label": "synth"},
        _span("fe/solve", 2, 1000.5, 2.0, parent=1),
        _span("re/train", 3, 1003.0, 3.0, parent=1),
        _span("cd/run", 1, 1000.0, 8.0),
        {
            "type": "metrics", "ts": 1009.9,
            "snapshot": {
                "counters": {
                    "transfer.row_transfers_h2d": 4,
                    "jit.traces.fe_solve": 2,
                },
                "gauges": {"serving.batch_fill": {"last": 0.5, "peak": 0.9}},
                "histograms": {"lat": {"count": 3, "mean": 1.5, "max": 2.0}},
            },
        },
        {"type": "meta", "ts": 1010.0, "phase": "finish"},
    ]


class TestClassifier:
    @pytest.mark.parametrize("name,phase", [
        ("fe/solve", "fe_solve"),
        ("re/adaptive_round", "re_solve"),
        ("cd/outer_iter", "cd_driver"),
        ("serve/score_batch", "serving"),
        ("incremental/update", "incremental"),
        ("h2d row push", "transfers"),
        ("read training data", "io"),
        ("save model", "io"),
        ("pack artifact", "io"),
        ("hyperparameter tuning", "host_driver"),
        ("fit", "host_driver"),
    ])
    def test_name_to_phase(self, name, phase):
        assert classify_span(name) == phase

    def test_every_phase_is_canonical(self):
        for name in ("fe/x", "re/x", "cd/x", "serve/x", "incremental/x",
                     "transfer", "load artifact", "anything else"):
            assert classify_span(name) in PHASES


class TestAccounting:
    def test_exclusive_time_bubble_and_coverage(self):
        report = analyze_records(_synthetic_records())
        assert report.label == "synth"
        assert report.wall_clock_s == pytest.approx(10.0)
        # parent's exclusive time excludes both direct children
        assert report.phase_seconds("cd_driver") == pytest.approx(3.0)
        assert report.phase_seconds("fe_solve") == pytest.approx(2.0)
        assert report.phase_seconds("re_solve") == pytest.approx(3.0)
        # 10s wall minus the 8s root interval = 2s of host-driver bubble
        assert report.bubble_s == pytest.approx(2.0)
        assert report.attributed_s == pytest.approx(10.0)
        assert report.coverage == pytest.approx(1.0)
        assert report.num_spans == 3 and report.failed_spans == 0
        # joins from the metrics record
        assert report.transfers == {"row_transfers_h2d": 4}
        assert report.jit_traces == {"fe_solve": 2}

    def test_concurrent_siblings_split_not_double_counted(self):
        """Async-schedule ledgers hold sibling spans that genuinely run at
        the same time. The sweep-line splits each concurrent segment evenly
        (coverage stays ~1.0 instead of blowing past it) and reports the
        concurrency as per-phase overlap: fe 1000.5-1006.5 and re
        1001-1007 share 5.5s; each phase keeps busy_s=6.0 but only 3.25s
        of attributed wall, with 2.75s each surfaced as overlap_s."""
        records = [
            {"type": "meta", "ts": 1000.0, "phase": "start",
             "label": "overlap"},
            _span("fe/solve", 2, 1000.5, 6.0, parent=1),
            _span("re/train", 3, 1001.0, 6.0, parent=1),
            _span("cd/run", 1, 1000.0, 8.0),
            {"type": "meta", "ts": 1010.0, "phase": "finish"},
        ]
        report = analyze_records(records)
        assert report.wall_clock_s == pytest.approx(10.0)
        # each 6s sibling keeps its full busy time...
        assert report.phases["fe_solve"]["busy_s"] == pytest.approx(6.0)
        assert report.phases["re_solve"]["busy_s"] == pytest.approx(6.0)
        # ...but attributed wall splits the shared 5.5s segment two ways:
        # 0.5s solo + 5.5/2 shared = 3.25s apiece
        assert report.phase_seconds("fe_solve") == pytest.approx(3.25)
        assert report.phase_seconds("re_solve") == pytest.approx(3.25)
        assert report.phase_overlap("fe_solve") == pytest.approx(2.75)
        assert report.phase_overlap("re_solve") == pytest.approx(2.75)
        # the root's exclusive tails (1000-1000.5, 1007-1008) have no
        # concurrency at all
        assert report.phase_seconds("cd_driver") == pytest.approx(1.5)
        assert report.phase_overlap("cd_driver") == pytest.approx(0.0)
        assert report.overlap_s == pytest.approx(5.5)
        # attribution stays exact: 8s of spans + 2s bubble = the 10s wall
        assert report.attributed_s == pytest.approx(10.0)
        assert report.bubble_s == pytest.approx(2.0)
        assert report.coverage == pytest.approx(1.0)

    def test_missing_finish_warns_and_measures_to_last_span(self):
        records = [r for r in _synthetic_records()
                   if not (r["type"] == "meta" and r["phase"] == "finish")]
        report = analyze_records(records)
        assert any("no finish record" in w for w in report.warnings)
        assert report.wall_clock_s == pytest.approx(8.0)  # last span end

    def test_solver_event_join(self):
        records = _synthetic_records()
        records.insert(2, {
            "type": "event", "ts": 1002.0, "event": "SolverStatsEvent",
            "fields": {
                "num_entities": 8, "rounds": 3,
                "executed_lane_iterations": 100,
                "lockstep_lane_iterations": 250,
                "chunk_retraces": 1, "converged": False,
            },
        })
        report = analyze_records(records)
        assert report.solver["entities"] == 8
        assert report.solver["lane_iteration_savings"] == pytest.approx(2.5)
        assert report.solver["unconverged_buckets"] == 1
        assert report.events["SolverStatsEvent"] == 1

    def test_failed_span_counted(self):
        records = _synthetic_records()
        records.append(_span("cd/objective", 9, 1008.5, 0.5, failed=True))
        report = analyze_records(records)
        assert report.failed_spans == 1

    def test_round_trip_and_metric_lookup(self):
        report = analyze_records(_synthetic_records())
        d = report.to_dict()
        d["unknown_future_key"] = 1  # forward-compat: ignored on load
        back = RunReport.from_dict(d)
        assert back.phases == report.phases
        assert back.coverage == report.coverage
        # counters, then gauge last-values, then histogram means
        assert back.metric("transfer.row_transfers_h2d") == 4.0
        assert back.metric("serving.batch_fill") == 0.5
        assert back.metric("lat") == 1.5
        assert back.metric("nope") is None

    def test_format_report_renders(self):
        text = format_report(analyze_records(_synthetic_records()))
        assert "cd_driver" in text and "coverage" in text
        assert "(bubbles)" in text


class TestLedgerReplay:
    def test_analyze_ledger_truncated_tail(self, tmp_path):
        path = tmp_path / "ledger.jsonl"
        _write_ledger(path, _synthetic_records())
        with open(path, "a") as f:
            f.write('{"type": "span", "name": "cut-mid-wr')  # no newline
        report = analyze_ledger(str(path))
        assert report.num_spans == 3  # the valid prefix
        assert any("partial record" in w for w in report.warnings)

    def test_live_session_coverage(self, tmp_path):
        """A real start_run session (spans + registry + checkpoint) replays
        with ≥95% attribution — the same bar as the CI analyze gate."""
        from photon_ml_tpu.telemetry import start_run

        get_registry().reset()
        ledger = tmp_path / "live.jsonl"
        run = start_run("live", ledger_path=str(ledger), device_sync=False)
        try:
            with span("cd/run"):
                with span("fe/solve"):
                    time.sleep(0.02)
                with span("re/train"):
                    time.sleep(0.02)
            run.checkpoint("mid")
            with span("read data"):
                time.sleep(0.01)
            run.finish()
        finally:
            disable_tracing()
        report = analyze_ledger(str(ledger))
        assert report.num_spans == 4  # checkpoint must not double-write
        assert 0.95 <= report.coverage <= 1.05
        assert report.phase_seconds("fe_solve") > 0
        assert report.phase_seconds("io") > 0


class TestAnalyzeRunCli:
    def _ledger(self, tmp_path):
        return _write_ledger(tmp_path / "l.jsonl", _synthetic_records())

    def test_report_json_and_coverage_gate(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "report.json"
        rc = main([
            self._ledger(tmp_path),
            "--json", str(out), "--check-coverage", "0.95",
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["coverage"] == pytest.approx(1.0)
        assert capsys.readouterr().out  # human table still printed

    def test_coverage_gate_fails_on_gaps(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        # drop the root span: 5s of child spans against a 10s wall
        records = [r for r in _synthetic_records()
                   if r.get("name") != "cd/run"]
        path = _write_ledger(tmp_path / "gappy.jsonl", records)
        assert main([path, "--check-coverage", "0.95"]) == 1
        assert "coverage" in capsys.readouterr().out.lower()

    def test_propose_covers_knob_space(self, tmp_path, capsys):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "proposal.json"
        rc = main([
            self._ledger(tmp_path), "--quiet", "--propose-json", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert len(doc["knobs"]) >= 4  # the declared knob space, audited
        for name, knob in doc["knobs"].items():
            assert knob["rationale"], name


@pytest.mark.slow
class TestAnalyzeTrainGate:
    @pytest.fixture(scope="class")
    def traced_train(self, tmp_path_factory):
        """Tiny traced CPU train (same fixture recipe as the telemetry
        smoke gate) -> ledger path."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.data_reader import write_training_examples

        root = tmp_path_factory.mktemp("analyze_train")
        rng = np.random.default_rng(7)
        n_users, dg, du = 6, 4, 3
        records = []
        for i in range(n_users * 8):
            user = f"user{i % n_users}"
            xg = rng.normal(size=dg)
            xu = rng.normal(size=du)
            y = 1.0 if (xg.sum() + xu.sum()) > 0 else 0.0
            records.append({
                "uid": f"r{i}", "label": y,
                "features": [("g", str(j), xg[j]) for j in range(dg)],
                "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                "metadataMap": {"userId": user},
            })
        train_dir = root / "train"
        train_dir.mkdir()
        write_training_examples(str(train_dir / "part-00000.avro"), records)
        config = {
            "feature_shards": {
                "global": {"feature_bags": ["features"],
                           "add_intercept": True},
                "per_user": {"feature_bags": ["userFeatures"],
                             "add_intercept": False},
            },
            "coordinates": {
                "fixed": {
                    "type": "fixed", "feature_shard": "global",
                    "optimizer": {"optimizer": "LBFGS",
                                  "regularization": "L2",
                                  "regularization_weight": 0.1},
                },
                "per_user": {
                    "type": "random", "feature_shard": "per_user",
                    "random_effect_type": "userId",
                    "optimizer": {
                        "optimizer": "LBFGS", "regularization": "L2",
                        "regularization_weight": 1.0,
                        "adaptive": {"enabled": True, "chunk_iters": 4,
                                     "min_lanes": 2},
                    },
                },
            },
            "update_order": ["fixed", "per_user"],
        }
        cfg = root / "game.json"
        cfg.write_text(json.dumps(config))
        ledger = root / "train-ledger.jsonl"
        run(parse_args([
            "--train-data-dirs", str(train_dir),
            "--coordinate-config", str(cfg),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(root / "model"),
            "--telemetry-out", str(ledger),
        ]))
        return str(ledger)

    def test_train_ledger_attributes_wall_clock(self, traced_train):
        report = analyze_ledger(traced_train)
        # phase durations sum within 5% of measured wall-clock (two-sided:
        # >1 would mean concurrent trees double-counting)
        assert 0.95 <= report.coverage <= 1.05, report.to_dict()
        assert report.phase_seconds("re_solve") > 0
        assert report.phase_seconds("cd_driver") > 0
        assert report.phase_seconds("io") > 0
        assert report.events.get("SolverStatsEvent", 0) > 0

    def test_analyze_run_cli_gate(self, traced_train, tmp_path):
        from photon_ml_tpu.cli.analyze_run import main

        out = tmp_path / "proposal.json"
        rc = main([
            traced_train, "--check-coverage", "0.95",
            "--propose-json", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert len(doc["knobs"]) >= 4
