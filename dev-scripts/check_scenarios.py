#!/usr/bin/env python
"""Scenario sentinel: validate a BENCH_SCENARIOS.json artifact.

CI runs the scenario replay harness in smoke mode (``BENCH_SMOKE=1
python bench.py --scenarios``) and hands the resulting JSON to this
script; it also runs against the committed ``BENCH_SCENARIOS.json`` so a
stale or hand-mangled artifact cannot ship. The gate asserts the request
plane's contract, not performance numbers (smoke shapes are tiny and CPU
timing is noisy):

* at least ``--min-scenarios`` scenario documents (default 4) — the
  scenario SET is variable (the catalog grows PR over PR), so the gate
  validates whatever set the payload carries and ``--require-names``
  pins the scenarios CI insists on (e.g. the tenancy trio);
* each scenario carries a per-stage p50/p99 breakdown over all six
  request stages, a ``device_resident_rate``, and an SLO verdict;
* each scenario's tail attribution coverage >= ``--min-coverage``
  (default 0.95): the per-stage breakdown must explain the end-to-end
  tail latency, the property the telescoping stage boundaries guarantee;
* a scenario that declares ``tenants`` (the tenancy trio) must carry a
  per-tenant SLO verdict (``slo_verdict`` + full ``slo`` status) for
  EVERY tenant, and ``tenant_isolation`` must carry its
  ``isolation_ok`` boolean — per-tenant budgets are the whole point of
  the tenancy plane, so a doc that lost them is malformed;
* with ``--ledger``, the bench telemetry ledger passes
  ``validate_ledger`` (schema check for every record kind, the sampled
  ``request`` records included) and actually carries request records;
* with ``--require-slo-ok``, every scenario's verdict must be ``ok``
  (the pauseless-swap + overload-control regime holds 8/8 — applied to
  the committed artifact, where timing is not smoke-noisy);
* with ``--max-swap-pause-s``, any scenario whose cumulative
  ``swap_pause`` interference exceeds the cap fails — the
  generation-flip swap's blackout is a pointer flip, so a fat number
  here means the pauseless path regressed.

Exit 0 = artifact sound; exit 1 names every violated invariant.

Usage:
    BENCH_SMOKE=1 python bench.py --scenarios > /tmp/fresh-scenarios.json
    python dev-scripts/check_scenarios.py /tmp/fresh-scenarios.json \
        [--ledger /tmp/scenarios-ledger.jsonl] [--min-scenarios 4] \
        [--min-coverage 0.95] \
        [--require-names tenant_isolation,ramped_rollout,nearline_loop]
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUEST_STAGES = ("queue", "featurize", "route", "dispatch", "device", "reply")


def _last_json_line(path):
    """Accept either form of the artifact: the committed
    BENCH_SCENARIOS.json (one pretty-printed document) or a capture of
    the bench's stdout (one JSON object per line, last line wins)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty")
    return json.loads(lines[-1])


def _check_tenancy(doc, name, problems):
    """Per-tenant SLO contract for scenarios that declare tenants."""
    tenants = doc.get("tenants")
    if not isinstance(tenants, dict) or not tenants:
        problems.append(f"{name}: declares tenancy but no 'tenants' map")
        return
    for tenant, info in sorted(tenants.items()):
        if not isinstance(info, dict) or not info.get("slo_verdict"):
            problems.append(f"{name}: tenant '{tenant}' has no SLO verdict")
            continue
        slo = info.get("slo")
        if not isinstance(slo, dict) or not all(
            isinstance(slo.get(k), (int, float))
            for k in ("burn_rate", "error_budget_remaining")
        ):
            problems.append(
                f"{name}: tenant '{tenant}' SLO status lacks error-budget "
                "accounting"
            )
    if name == "tenant_isolation":
        if not isinstance(doc.get("isolation_ok"), bool):
            problems.append(f"{name}: no isolation_ok verdict")
        if not doc.get("flooding_tenant"):
            problems.append(f"{name}: no flooding_tenant attribution")


def check_payload(
    payload,
    min_scenarios,
    min_coverage,
    require_names=(),
    require_slo_ok=False,
    max_swap_pause_s=None,
):
    """Return the list of violated invariants (empty = sound)."""
    problems = []
    if payload.get("error"):
        return [f"harness errored: {payload['error']}"]
    scenarios = payload.get("scenarios")
    if not isinstance(scenarios, list):
        return ["payload carries no 'scenarios' list"]
    if len(scenarios) < min_scenarios:
        problems.append(
            f"only {len(scenarios)} scenario(s), need >= {min_scenarios}"
        )
    present = {d.get("name") for d in scenarios}
    for required in require_names:
        if required not in present:
            problems.append(f"required scenario '{required}' is missing")
    for doc in scenarios:
        name = doc.get("name", "?")
        if not doc.get("num_requests"):
            problems.append(f"{name}: no requests replayed")
            continue
        plane = doc.get("request_plane") or {}
        stages = plane.get("stages") or {}
        for stage in REQUEST_STAGES:
            dist = stages.get(stage)
            if not isinstance(dist, dict) or not all(
                isinstance(dist.get(k), (int, float))
                for k in ("p50_s", "p99_s")
            ):
                problems.append(
                    f"{name}: stage '{stage}' missing p50/p99 breakdown"
                )
        tail = plane.get("tail") or {}
        coverage = tail.get("attribution_coverage")
        if not isinstance(coverage, (int, float)):
            problems.append(f"{name}: no tail attribution coverage")
        elif coverage < min_coverage:
            problems.append(
                f"{name}: tail attribution coverage {coverage:.4f} < "
                f"{min_coverage} — stage boundaries are leaking time"
            )
        if not isinstance(
            doc.get("device_resident_rate"), (int, float)
        ):
            problems.append(f"{name}: no device_resident_rate")
        if not doc.get("slo_verdict"):
            problems.append(f"{name}: no SLO verdict")
        elif require_slo_ok and doc.get("slo_verdict") != "ok":
            problems.append(
                f"{name}: slo_verdict={doc['slo_verdict']!r}, gate "
                "requires 'ok' for every scenario"
            )
        if max_swap_pause_s is not None:
            interference = plane.get("interference") or {}
            swap = interference.get("swap_pause") or {}
            total = swap.get("total_s", 0.0)
            if (
                isinstance(total, (int, float))
                and total > max_swap_pause_s
            ):
                problems.append(
                    f"{name}: swap_pause total {total:.4f}s > "
                    f"{max_swap_pause_s}s — the generation flip is "
                    "supposed to make swaps pauseless"
                )
        if "tenants" in doc:
            _check_tenancy(doc, name, problems)
    return problems


def check_ledger(path):
    """Schema-validate the bench telemetry ledger and require sampled
    request records in it. Returns the list of problems."""
    sys.path.insert(0, REPO)
    from photon_ml_tpu.telemetry.validate import validate_ledger

    try:
        records = validate_ledger(path)
    except Exception as e:  # noqa: BLE001 - named in the gate output
        return [f"ledger {path} failed validation: {type(e).__name__}: {e}"]
    n_req = sum(1 for r in records if r.get("type") == "request")
    if not n_req:
        return [f"ledger {path} carries no 'request' records"]
    print(
        f"scenario-sentinel: ledger ok — {len(records)} record(s), "
        f"{n_req} sampled request record(s), schema-validated"
    )
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "payload",
        help="BENCH_SCENARIOS.json or a file holding the bench's JSON line",
    )
    ap.add_argument(
        "--ledger", default=None,
        help="also schema-validate this bench telemetry ledger and require "
             "sampled 'request' records in it",
    )
    ap.add_argument("--min-scenarios", type=int, default=4)
    ap.add_argument(
        "--min-coverage", type=float, default=0.95,
        help="minimum tail attribution coverage per scenario (default 0.95)",
    )
    ap.add_argument(
        "--require-names", default="",
        help="comma-separated scenario names that MUST be present (the "
             "scenario set is otherwise variable)",
    )
    ap.add_argument(
        "--require-slo-ok", action="store_true",
        help="every scenario's slo_verdict must be 'ok' (the pauseless-"
             "swap + overload-control regime holds 8/8)",
    )
    ap.add_argument(
        "--max-swap-pause-s", type=float, default=None,
        help="fail any scenario whose cumulative swap_pause interference "
             "exceeds this many seconds (pauseless-flip regression gate)",
    )
    args = ap.parse_args(argv)

    try:
        payload = _last_json_line(args.payload)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"scenario-sentinel: cannot read payload ({e})")
        return 1

    require_names = tuple(
        n.strip() for n in args.require_names.split(",") if n.strip()
    )
    problems = check_payload(
        payload,
        args.min_scenarios,
        args.min_coverage,
        require_names,
        require_slo_ok=args.require_slo_ok,
        max_swap_pause_s=args.max_swap_pause_s,
    )
    if args.ledger:
        problems += check_ledger(args.ledger)

    if problems:
        for p in problems:
            print(f"scenario-sentinel: FAIL — {p}")
        return 1
    scenarios = payload.get("scenarios") or []
    verdicts = ", ".join(
        f"{d.get('name')}={d.get('slo_verdict')}" for d in scenarios
    )
    print(
        f"scenario-sentinel: ok — {len(scenarios)} scenario(s) "
        f"({verdicts}), slo_ok_rate={payload.get('value')}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
