#!/bin/bash
# Poll the device tunnel; the moment it answers, run ONE batched measurement
# session (dev-scripts/tpu_session.py) and exit. Use when the tunnel is down
# and measurements are wanted as soon as it returns.
#   dev-scripts/tpu_watch.sh [session args...]
cd "$(dirname "$0")/.."
for i in $(seq 1 "${TPU_WATCH_PROBES:-200}"); do
  if timeout 120 python -c "import jax, jax.numpy as jnp; jax.block_until_ready(jnp.arange(4).sum())" >/dev/null 2>&1; then
    echo "tunnel up after probe $i; starting measurement session" >&2
    exec python dev-scripts/tpu_session.py "$@"
  fi
  echo "probe $i: tunnel down" >&2
  sleep 120
done
echo "tunnel never came up" >&2
exit 1
