"""ONE batched TPU measurement session — run the moment the tunnel is up.

    python dev-scripts/tpu_session.py [--out TPU_MEASUREMENTS.json]

The device tunnel flaps for hours at a time, so every real-TPU measurement
the repo needs is batched into this single process (pay the startup and
compile cost once):

1. preflight — prove the backend answers (60s timeout, 3 attempts).
2. fused-engine validation — dev-scripts/tpu_validate_fused.py as a child
   process: hardware-lowering correctness vs ELL + benes/fused timings.
3. bench — python bench.py (full engine A/B + AUC clock + 16M grid shard);
   its JSON line is captured verbatim.
4. kernel microbenchmarks — matvec/rmatvec wall time per engine at bench
   scale with derived achieved HBM GB/s (bytes moved per linear map are
   computed from the layouts; see docs/SCALING.md), the utilization
   numbers VERDICT r3 asked for.

Everything lands in ONE json file (default TPU_MEASUREMENTS.json at the
repo root) plus a human summary on stderr, including the recommended
`auto` engine = argmax of measured throughput. Each phase is independent:
a failure records an "error" entry and the session continues.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def _preflight(timeout_s: int = 60, attempts: int = 3) -> None:
    # BENCH_SMOKE: CPU sessions force the backend in-process — the TPU
    # plugin overrides JAX_PLATFORMS and would hang on a dead tunnel
    force_cpu = (
        "jax.config.update('jax_platforms', 'cpu'); "
        if bool(int(os.environ.get("BENCH_SMOKE", "0"))) else ""
    )
    code = (
        "import jax; " + force_cpu +
        "import jax.numpy as jnp; jax.block_until_ready(jnp.arange(4).sum())"
    )
    for i in range(attempts):
        try:
            subprocess.run([sys.executable, "-c", code], timeout=timeout_s, check=True)
            return
        except Exception as e:
            print(f"preflight {i + 1}/{attempts} failed: {type(e).__name__}",
                  file=sys.stderr)
            if i < attempts - 1:
                time.sleep(30)
    raise SystemExit("backend unreachable; try again when the tunnel is up")


def _phase_validate(results: dict) -> None:
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev-scripts", "tpu_validate_fused.py")],
        capture_output=True, text=True, timeout=1800,
    )
    results["validate_fused"] = {
        "returncode": proc.returncode,
        "stdout": proc.stdout[-4000:],
        "stderr": proc.stderr[-2000:],
    }


def _phase_bench(results: dict) -> None:
    # the batched session wants the COMPLETE record, including the
    # default-off bf16 A/B (see bench.py: default-off after the r4 verdict)
    env = dict(os.environ, BENCH_WATCHDOG_S="2400", BENCH_BF16="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=2700, env=env,
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
    try:
        results["bench"] = json.loads(line)
    except json.JSONDecodeError:
        results["bench"] = {"error": f"unparseable bench output: {line[:200]}"}
    results["bench_stderr"] = proc.stderr[-2000:]
    # the recommendation depends only on bench data — write it NOW so a
    # tunnel hang in a later phase cannot lose it. A stale/errored bench
    # line (the lastgood replay) must NOT mint a "measured" recommendation.
    if not results["bench"].get("stale") and not results["bench"].get("error"):
        _recommend(results)


def _recommend(results: dict) -> None:
    engines = {
        k: v
        for k, v in results.get("bench", {}).get("engines", {}).items()
        if k in ("ell", "benes", "fused")  # settable sparse_engine values
    }
    if engines:
        rec = max(engines, key=engines.get)
        results["recommended_auto_engine"] = rec
        print(f"recommended auto engine (measured): {rec} {engines}",
              file=sys.stderr)


# Peak HBM bandwidth of the target chip (v5e ≈ 819 GB/s); override with
# BENCH_PEAK_GBPS when measuring on different hardware.
try:
    PEAK_HBM_GBPS = float(os.environ.get("BENCH_PEAK_GBPS", "819"))
except ValueError:
    print("ignoring malformed BENCH_PEAK_GBPS; using 819", file=sys.stderr)
    PEAK_HBM_GBPS = 819.0

# Chained applications per jit program in the kernels phase: per-op time is
# total/CHAIN, so per-call dispatch (tunnel RPC) overhead amortizes away.
CHAIN = 10


def _phase_kernels(results: dict) -> None:
    """Per-engine matvec/rmatvec device times + achieved HBM bandwidth at
    the bench FE shape, measured two ways (VERDICT r4 weak #3):

    - ``*_dispatch_s``: one jitted call per timing (the r3/r4 method) —
      includes per-call dispatch/tunnel overhead.
    - ``*_s``: CHAIN chained applications inside ONE jit program (each
      iteration data-depends on the last via a tiny scalar feedback), time
      divided by CHAIN — the in-solver cost, dispatch excluded. This is the
      number ``pct_of_peak`` is computed from, since inside L-BFGS the maps
      run under one compiled while_loop exactly like this.

    Byte accounting per linear map (f32):
    - ell:   read values [n,K] + indices [n,K] (int32) + gathered w, write z
             → ~(2·nnz + nnz + n)·4 bytes lower bound (gather granularity
             makes the true figure higher; this is the optimistic bound the
             % is measured against).
    - benes: ~11 passes over the routed [S] array per map → ~11·S·4 bytes.
    - fused: 2m+1 passes over [S] → ~(2m+1)·S·4 bytes.

    Each engine entry carries a one-line ``binding`` diagnosis: what the
    evidence says limits it (dispatch, bandwidth, or latency/occupancy).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from photon_ml_tpu.ops import fused_perm, sparse_perm
    from photon_ml_tpu.ops.features import from_scipy_like

    smoke = bool(int(os.environ.get("BENCH_SMOKE", "0")))
    n, k, d = (1 << 12, 8, 1 << 10) if smoke else (1 << 18, 32, 1 << 17)
    nnz = n * k
    rng = np.random.default_rng(0)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, nnz).astype(np.int64)
    vals = rng.standard_normal(nnz).astype(np.float32)
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    c = jnp.asarray(rng.standard_normal(n).astype(np.float32))

    def _time_best(fn, *args, reps=6):
        jax.block_until_ready(fn(*args))  # compile
        for x in jax.tree.leaves(fn(*args)):
            np.asarray(x)  # settle the remote-dispatch completion signal
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    out = {}
    engines = {
        "ell": lambda: from_scipy_like(rows, cols, vals, (n, d)),
        "benes": lambda: sparse_perm.from_coo(rows, cols, vals, (n, d)),
        "fused": lambda: fused_perm.from_coo(rows, cols, vals, (n, d)),
    }
    if not smoke:
        # tile-height A/B: same plan, taller kernel blocks — separates
        # per-grid-step overhead from bandwidth (fused_perm._tile_cap)
        engines["fused_u32"] = engines["fused"]
        engines["fused_u64"] = engines["fused"]
    cap_prior = os.environ.get("PHOTON_FUSED_TILE_U")
    for name, build in engines.items():
        tile_cap = name.rsplit("_u", 1)[-1] if "_u" in name else None
        if tile_cap:
            os.environ["PHOTON_FUSED_TILE_U"] = tile_cap
        try:
            feats = build()
            mv = jax.jit(feats.matvec)
            rmv = jax.jit(feats.rmatvec)

            # chained: CHAIN data-dependent applications per program. The
            # feedback must consume EVERY output element (jnp.sum) — a
            # single-element slice would let XLA sink the slice through the
            # map and compute one row instead of the full product.
            @jax.jit
            def mv_chain(w0):
                def body(_, wc):
                    z = feats.matvec(wc)
                    return wc + 1e-30 * jnp.sum(z)
                return lax.fori_loop(0, CHAIN, body, w0)

            @jax.jit
            def rmv_chain(c0):
                def body(_, cc):
                    g = feats.rmatvec(cc)
                    return cc + 1e-30 * jnp.sum(g)
                return lax.fori_loop(0, CHAIN, body, c0)

            # one objective evaluation's linear algebra: matvec + pointwise
            # + rmatvec per iteration, as inside the L-BFGS while_loop —
            # the per-eval number VERDICT r4 #2 tracks
            @jax.jit
            def eval_chain(w0):
                def body(_, wc):
                    z = feats.matvec(wc)
                    g = feats.rmatvec(jnp.tanh(z))
                    return wc + 1e-30 * g
                return lax.fori_loop(0, CHAIN, body, w0)

            t_mv_1 = _time_best(mv, w)
            t_rmv_1 = _time_best(rmv, c)
            t_mv = _time_best(mv_chain, w) / CHAIN
            t_rmv = _time_best(rmv_chain, c) / CHAIN
            t_eval = _time_best(eval_chain, w) / CHAIN
            if name == "ell":
                bytes_map = (3 * nnz + n) * 4
                S = None
            else:
                S = feats.plan.size
                m = sum(
                    1 for kind in feats.plan.kinds if kind[0] == "enter"
                )
                passes = 11 if name == "benes" else 2 * m + 1
                bytes_map = passes * S * 4
            gbps_mv = bytes_map / t_mv / 1e9
            gbps_rmv = bytes_map / t_rmv / 1e9
            pct_mv = 100 * gbps_mv / PEAK_HBM_GBPS
            pct_rmv = 100 * gbps_rmv / PEAK_HBM_GBPS

            def _diagnose(t_chained, t_single, pct):
                parts = []
                if t_single > 2 * t_chained:
                    parts.append(
                        f"dispatch-dominated single calls "
                        f"(+{(t_single - t_chained) * 1e3:.1f} ms/call)"
                    )
                if pct > 50:
                    parts.append(
                        f"bandwidth-bound ({pct:.0f}% of peak HBM in-program)"
                    )
                else:
                    parts.append(
                        f"latency/occupancy-bound ({pct:.0f}% of peak HBM "
                        "with dispatch excluded)"
                    )
                return ", ".join(parts)

            binding = (
                f"matvec: {_diagnose(t_mv, t_mv_1, pct_mv)}; "
                f"rmatvec: {_diagnose(t_rmv, t_rmv_1, pct_rmv)}"
            )
            out[name] = {
                "matvec_s": round(t_mv, 6),
                "rmatvec_s": round(t_rmv, 6),
                "objective_eval_s": round(t_eval, 6),
                "matvec_dispatch_s": round(t_mv_1, 6),
                "rmatvec_dispatch_s": round(t_rmv_1, 6),
                "chain": CHAIN,
                "achieved_GBps_matvec": round(gbps_mv, 2),
                "achieved_GBps_rmatvec": round(gbps_rmv, 2),
                "pct_of_peak_matvec": round(pct_mv, 2),
                "pct_of_peak_rmatvec": round(pct_rmv, 2),
                "peak_GBps": PEAK_HBM_GBPS,
                "bytes_per_map": bytes_map,
                "network_slots": S,
                "binding": binding,
            }
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            if tile_cap:  # restore the operator's cap (if any), not unset
                if cap_prior is None:
                    os.environ.pop("PHOTON_FUSED_TILE_U", None)
                else:
                    os.environ["PHOTON_FUSED_TILE_U"] = cap_prior
    results["kernels"] = out

    # spill-cost calibration: ns/entry of an XLA scatter-add (the spill
    # side's op) vs ns/slot of the fastest routed engine. Their ratio is
    # the measured PHOTON_SPILL_SLOT_COST the layout planner should use
    # (sparse_perm._spill_slot_cost; default 32 is a conservative guess).
    try:
        m_sp = 1 << (12 if smoke else 21)
        sp_rows = jnp.asarray(rng.integers(0, n, m_sp).astype(np.int32))
        sp_cols = jnp.asarray(rng.integers(0, d, m_sp).astype(np.int32))
        sp_vals = jnp.asarray(rng.standard_normal(m_sp).astype(np.float32))

        @jax.jit
        def spill_chain(w0):
            # the real spill op: out[rows] += vals * w[cols] (gather +
            # multiply + scatter-add), chained through the carry
            def body(_, wc):
                z = jnp.zeros(n, jnp.float32).at[sp_rows].add(
                    sp_vals * wc[sp_cols]
                )
                return wc + 1e-30 * jnp.sum(z)
            return lax.fori_loop(0, CHAIN, body, w0)

        t_spill = _time_best(spill_chain, jnp.zeros(d, jnp.float32)) / CHAIN
        ns_per_entry = t_spill / m_sp * 1e9
        # calibrate against the fastest measured routed engine — the one
        # the planner's layouts will actually execute on
        slot_ns = None
        routed = [
            e for e in out.values()
            if "matvec_s" in e and e.get("network_slots")
        ]
        if routed:
            best_e = min(routed, key=lambda e: e["matvec_s"])
            slot_ns = best_e["matvec_s"] / best_e["network_slots"] * 1e9
        results["spill_calibration"] = {
            "scatter_ns_per_entry": round(ns_per_entry, 2),
            "routed_ns_per_slot": (
                round(slot_ns, 4) if slot_ns is not None else None
            ),
            "recommended_spill_slot_cost": (
                max(int(round(ns_per_entry / slot_ns)), 1)
                if slot_ns else None
            ),
        }
    except Exception as e:
        results["spill_calibration"] = {"error": f"{type(e).__name__}: {e}"}

    # profiler trace for manual xprof inspection (small, one engine each)
    trace_dir = os.path.join(REPO, "profile-traces")
    try:
        with jax.profiler.trace(trace_dir):
            feats = engines["benes"]()
            jax.block_until_ready(jax.jit(feats.matvec)(w))
        results["trace_dir"] = trace_dir
    except Exception as e:
        results["trace_dir"] = f"trace failed: {e}"


def _phase_memory(results: dict) -> None:
    """Empirical 1B-coefficient memory envelope (VERDICT r4 #5): solve
    single-chip grid tiles at 2^26 and 2^27 coefficients with L-BFGS
    history m=10 vs m=5 (and m=10 in bfloat16 history) and record the
    device-memory high-water mark against docs/SCALING.md's predicted table
    (w-shard + m·2 history vectors dominate). Shapes: nnz is held at bench
    scale (2^20 rows x 16) so the COLUMN side (the 1B axis) is what grows.

    Each variant runs in its OWN child process: PJRT's peak_bytes_in_use is
    a process-lifetime high-water mark with no reset API, so in-process
    variants after the first would all report the largest earlier peak."""
    smoke = bool(int(os.environ.get("BENCH_SMOKE", "0")))
    dims = [1 << 14] if smoke else [1 << 26, 1 << 27]
    variants = [
        (10, "float32"), (5, "float32"), (10, "bfloat16"),
    ]
    out = {}
    for d_grid in dims:
        for m_hist, h_dtype in variants:
            key = f"d{d_grid}_m{m_hist}" + (
                "_bf16" if h_dtype == "bfloat16" else ""
            )
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--memory-variant", f"{d_grid},{m_hist},{h_dtype}"],
                    capture_output=True, text=True, timeout=1500,
                )
                line = (
                    proc.stdout.strip().splitlines()[-1]
                    if proc.stdout.strip() else "{}"
                )
                rec = json.loads(line)
                if proc.returncode != 0 and "error" not in rec:
                    rec["error"] = proc.stderr[-300:]
                out[key] = rec
            except Exception as e:
                out[key] = {"error": f"{type(e).__name__}: {e}"}
    results["memory"] = out


def _memory_variant_main(spec: str) -> None:
    """Child-process body for one memory-envelope variant: solve the tile,
    print ONE JSON line with throughput + this process's device-memory
    high-water mark."""
    d_grid, m_hist, h_dtype = spec.split(",")
    d_grid, m_hist = int(d_grid), int(m_hist)
    smoke = bool(int(os.environ.get("BENCH_SMOKE", "0")))

    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
        RegularizationContext,
    )
    from photon_ml_tpu.opt.solve import solve
    from photon_ml_tpu.parallel.grid_features import (
        grid_from_coo,
        grid_mesh,
        shard_vector_data,
        shard_vector_feat,
    )
    from photon_ml_tpu.types import RegularizationType
    from photon_ml_tpu.utils.cachedir import enable_compilation_cache

    enable_compilation_cache()
    n_rows = 1 << (12 if smoke else 20)
    k_nnz = 16
    rng = np.random.default_rng(7)
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), k_nnz)
    cols = rng.integers(0, d_grid, n_rows * k_nnz).astype(np.int64)
    vals = rng.standard_normal(n_rows * k_nnz).astype(np.float32)
    z = (vals * (rng.standard_normal(d_grid) * 0.1).astype(np.float32)[cols]
         ).reshape(n_rows, k_nnz).sum(-1)
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    mesh = grid_mesh(1, 1)
    gf = grid_from_coo(rows, cols, vals, (n_rows, d_grid), mesh, engine="fused")
    y_pad = np.zeros(gf.num_rows, np.float32)
    y_pad[:n_rows] = y
    data = LabeledData.create(
        gf, shard_vector_data(jnp.asarray(y_pad), mesh)
    )
    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(
            max_iterations=10, history_length=m_hist,
            history_dtype=None if h_dtype == "float32" else h_dtype,
        ),
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    solver = jax.jit(lambda w0, dd: solve(objective, w0, dd, cfg))
    w0 = shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh)
    res = solver(w0, data)
    jax.block_until_ready(res.w)
    t0 = time.perf_counter()
    res = solver(w0, data)
    jax.block_until_ready(res.w)
    dt = time.perf_counter() - t0
    stats = {}
    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        pass
    iters = max(int(res.iterations), 1)
    print(json.dumps({
        "dim": d_grid,
        "history_m": m_hist,
        "history_dtype": h_dtype,
        "iterations": iters,
        "solve_s": round(dt, 3),
        "passes_per_s": round(n_rows * iters / dt, 1),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        "bytes_in_use": stats.get("bytes_in_use"),
        "bytes_limit": stats.get("bytes_limit"),
    }))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "TPU_MEASUREMENTS.json"))
    ap.add_argument("--skip-validate", action="store_true")
    ap.add_argument("--skip-bench", action="store_true")
    ap.add_argument("--skip-kernels", action="store_true")
    ap.add_argument("--skip-memory", action="store_true")
    ap.add_argument(
        "--memory-variant", default=None, help=argparse.SUPPRESS,
    )
    args = ap.parse_args()

    if args.memory_variant:
        _memory_variant_main(args.memory_variant)
        return

    if bool(int(os.environ.get("BENCH_SMOKE", "0"))):
        # CPU smoke session: force the in-process backend too (the TPU
        # plugin overrides JAX_PLATFORMS and hangs on a dead tunnel)
        import jax

        jax.config.update("jax_platforms", "cpu")
    _preflight()
    started = time.time()
    results: dict = {"started_unix": started}
    # bench FIRST: it is the round-critical record and a flapping tunnel
    # must not spend its uptime on the other phases. memory (child
    # processes) runs BEFORE kernels (in-process jax): once the parent
    # holds the device client, children could no longer acquire the chip
    # on backends with exclusive ownership.
    phases = [
        ("bench", _phase_bench, args.skip_bench),
        ("validate", _phase_validate, args.skip_validate),
        ("memory", _phase_memory, args.skip_memory),
        ("kernels", _phase_kernels, args.skip_kernels),
    ]
    for name, fn, skip in phases:
        if skip:
            continue
        print(f"=== phase {name} ===", file=sys.stderr)
        t0 = time.perf_counter()
        try:
            fn(results)
        except Exception as e:
            results[name + "_error"] = f"{type(e).__name__}: {e}"
        print(f"=== phase {name} done in {time.perf_counter() - t0:.0f}s ===",
              file=sys.stderr)
        # persist after every phase: a mid-session tunnel death keeps
        # everything measured so far
        with open(args.out, "w") as f:
            json.dump(_merge_sessions(args.out, results, started), f, indent=2)

    print(f"session written to {args.out}", file=sys.stderr)


def _result_bad(v) -> bool:
    """A phase dict is degraded if it (or any direct sub-dict — the kernels
    phase records per-engine errors one level down) carries an error."""
    if not isinstance(v, dict):
        return v is None
    if v.get("error") or v.get("returncode") not in (None, 0):
        return True
    return any(
        isinstance(sub, dict) and sub.get("error") for sub in v.values()
    )


def _phase_failed(results: dict, key: str, err_key: str) -> bool:
    if err_key in results:
        return True
    return _result_bad(results.get(key))


def _merge_sessions(out_path: str, results: dict, started: float) -> dict:
    """Keep the last SUCCESSFUL measurement per phase (timestamped).

    The device tunnel flaps for hours; a fresh session with a failed or
    watchdogged phase must not erase an earlier good measurement of that
    phase. A degraded new result is stashed under ``<phase>_latest_partial``
    so the record still shows the most recent attempt.
    """
    # derived keys ride with their phase: restoring an old bench must also
    # restore the recommendation/stderr computed FROM that bench
    phase_keys = {
        "validate": ("validate_fused", "validate_error", ()),
        "bench": (
            "bench", "bench_error",
            ("bench_stderr", "recommended_auto_engine"),
        ),
        "kernels": ("kernels", "kernels_error", ()),
        "memory": ("memory", "memory_error", ()),
    }
    try:
        with open(out_path) as f:
            prev = json.load(f)
    except Exception:
        prev = {}
    merged = dict(results)
    merged["note"] = (
        "per-phase record: each phase carries its own measured_at_unix; a "
        "phase that failed in the latest session keeps the previous "
        "successful measurement, with the failed attempt under "
        "<phase>_latest_partial"
    )
    for _, (key, err_key, riders) in phase_keys.items():
        if key in merged and isinstance(merged[key], dict):
            merged[key].setdefault("measured_at_unix", started)
        if not _phase_failed(merged, key, err_key):
            continue
        old = prev.get(key)
        # previous successful measurement (possibly already merged once)
        if isinstance(old, dict) and not _result_bad(old):
            if key in merged:
                merged[key + "_latest_partial"] = merged[key]
            merged[key] = old
            for rider in riders:
                if rider in merged:
                    merged[rider + "_latest_partial"] = merged[rider]
                if rider in prev:
                    merged[rider] = prev[rider]
                else:
                    merged.pop(rider, None)
        elif key not in merged and old is not None:
            merged[key] = old
    return merged


if __name__ == "__main__":
    main()
