"""ONE batched TPU measurement session — run the moment the tunnel is up.

    python dev-scripts/tpu_session.py [--out TPU_MEASUREMENTS.json]

The device tunnel flaps for hours at a time, so every real-TPU measurement
the repo needs is batched into this single process (pay the startup and
compile cost once):

1. preflight — prove the backend answers (60s timeout, 3 attempts).
2. fused-engine validation — dev-scripts/tpu_validate_fused.py as a child
   process: hardware-lowering correctness vs ELL + benes/fused timings.
3. bench — python bench.py (full engine A/B + AUC clock + 16M grid shard);
   its JSON line is captured verbatim.
4. kernel microbenchmarks — matvec/rmatvec wall time per engine at bench
   scale with derived achieved HBM GB/s (bytes moved per linear map are
   computed from the layouts; see docs/SCALING.md), the utilization
   numbers VERDICT r3 asked for.

Everything lands in ONE json file (default TPU_MEASUREMENTS.json at the
repo root) plus a human summary on stderr, including the recommended
`auto` engine = argmax of measured throughput. Each phase is independent:
a failure records an "error" entry and the session continues.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def _preflight(timeout_s: int = 60, attempts: int = 3) -> None:
    code = "import jax, jax.numpy as jnp; jax.block_until_ready(jnp.arange(4).sum())"
    for i in range(attempts):
        try:
            subprocess.run([sys.executable, "-c", code], timeout=timeout_s, check=True)
            return
        except Exception as e:
            print(f"preflight {i + 1}/{attempts} failed: {type(e).__name__}",
                  file=sys.stderr)
            if i < attempts - 1:
                time.sleep(30)
    raise SystemExit("backend unreachable; try again when the tunnel is up")


def _phase_validate(results: dict) -> None:
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev-scripts", "tpu_validate_fused.py")],
        capture_output=True, text=True, timeout=1800,
    )
    results["validate_fused"] = {
        "returncode": proc.returncode,
        "stdout": proc.stdout[-4000:],
        "stderr": proc.stderr[-2000:],
    }


def _phase_bench(results: dict) -> None:
    env = dict(os.environ, BENCH_WATCHDOG_S="2400")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=2700, env=env,
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
    try:
        results["bench"] = json.loads(line)
    except json.JSONDecodeError:
        results["bench"] = {"error": f"unparseable bench output: {line[:200]}"}
    results["bench_stderr"] = proc.stderr[-2000:]
    # the recommendation depends only on bench data — write it NOW so a
    # tunnel hang in a later phase cannot lose it
    _recommend(results)


def _recommend(results: dict) -> None:
    engines = {
        k: v
        for k, v in results.get("bench", {}).get("engines", {}).items()
        if k in ("ell", "benes", "fused")  # settable sparse_engine values
    }
    if engines:
        rec = max(engines, key=engines.get)
        results["recommended_auto_engine"] = rec
        print(f"recommended auto engine (measured): {rec} {engines}",
              file=sys.stderr)


def _phase_kernels(results: dict) -> None:
    """Per-engine matvec/rmatvec wall times + achieved HBM bandwidth at the
    bench FE shape. Byte accounting per linear map (f32):

    - ell:   read values [n,K] + indices [n,K] (int32) + gathered w, write z
             → ~(2·nnz + nnz + n)·4 bytes lower bound (gather granularity
             makes the true figure higher; this is the optimistic bound the
             % is measured against).
    - benes: ~11 passes over the routed [S] array per map → ~11·S·4 bytes.
    - fused: 2m+1 passes over [S] → ~(2m+1)·S·4 bytes.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import fused_perm, sparse_perm
    from photon_ml_tpu.ops.features import from_scipy_like

    smoke = bool(int(os.environ.get("BENCH_SMOKE", "0")))
    n, k, d = (1 << 12, 8, 1 << 10) if smoke else (1 << 18, 32, 1 << 17)
    nnz = n * k
    rng = np.random.default_rng(0)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, nnz).astype(np.int64)
    vals = rng.standard_normal(nnz).astype(np.float32)
    w = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    c = jnp.asarray(rng.standard_normal(n).astype(np.float32))

    out = {}
    engines = {
        "ell": lambda: from_scipy_like(rows, cols, vals, (n, d)),
        "benes": lambda: sparse_perm.from_coo(rows, cols, vals, (n, d)),
        "fused": lambda: fused_perm.from_coo(rows, cols, vals, (n, d)),
    }
    for name, build in engines.items():
        try:
            feats = build()
            mv = jax.jit(feats.matvec)
            rmv = jax.jit(feats.rmatvec)
            jax.block_until_ready(mv(w))
            jax.block_until_ready(rmv(c))
            tm, tr = [], []
            for _ in range(10):
                t0 = time.perf_counter()
                jax.block_until_ready(mv(w))
                tm.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                jax.block_until_ready(rmv(c))
                tr.append(time.perf_counter() - t0)
            t_mv, t_rmv = min(tm), min(tr)
            if name == "ell":
                bytes_map = (3 * nnz + n) * 4
            else:
                S = feats.plan.size
                m = sum(
                    1 for kind in feats.plan.kinds if kind[0] == "enter"
                )
                passes = 11 if name == "benes" else 2 * m + 1
                bytes_map = passes * S * 4
            out[name] = {
                "matvec_s": round(t_mv, 6),
                "rmatvec_s": round(t_rmv, 6),
                "achieved_GBps_matvec": round(bytes_map / t_mv / 1e9, 2),
                "achieved_GBps_rmatvec": round(bytes_map / t_rmv / 1e9, 2),
                "bytes_per_map": bytes_map,
            }
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    results["kernels"] = out

    # profiler trace for manual xprof inspection (small, one engine each)
    trace_dir = os.path.join(REPO, "profile-traces")
    try:
        with jax.profiler.trace(trace_dir):
            feats = engines["benes"]()
            jax.block_until_ready(jax.jit(feats.matvec)(w))
        results["trace_dir"] = trace_dir
    except Exception as e:
        results["trace_dir"] = f"trace failed: {e}"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "TPU_MEASUREMENTS.json"))
    ap.add_argument("--skip-validate", action="store_true")
    ap.add_argument("--skip-bench", action="store_true")
    ap.add_argument("--skip-kernels", action="store_true")
    args = ap.parse_args()

    _preflight()
    started = time.time()
    results: dict = {"started_unix": started}
    phases = [
        ("validate", _phase_validate, args.skip_validate),
        ("bench", _phase_bench, args.skip_bench),
        ("kernels", _phase_kernels, args.skip_kernels),
    ]
    for name, fn, skip in phases:
        if skip:
            continue
        print(f"=== phase {name} ===", file=sys.stderr)
        t0 = time.perf_counter()
        try:
            fn(results)
        except Exception as e:
            results[name + "_error"] = f"{type(e).__name__}: {e}"
        print(f"=== phase {name} done in {time.perf_counter() - t0:.0f}s ===",
              file=sys.stderr)
        # persist after every phase: a mid-session tunnel death keeps
        # everything measured so far
        with open(args.out, "w") as f:
            json.dump(_merge_sessions(args.out, results, started), f, indent=2)

    print(f"session written to {args.out}", file=sys.stderr)


def _result_bad(v) -> bool:
    """A phase dict is degraded if it (or any direct sub-dict — the kernels
    phase records per-engine errors one level down) carries an error."""
    if not isinstance(v, dict):
        return v is None
    if v.get("error") or v.get("returncode") not in (None, 0):
        return True
    return any(
        isinstance(sub, dict) and sub.get("error") for sub in v.values()
    )


def _phase_failed(results: dict, key: str, err_key: str) -> bool:
    if err_key in results:
        return True
    return _result_bad(results.get(key))


def _merge_sessions(out_path: str, results: dict, started: float) -> dict:
    """Keep the last SUCCESSFUL measurement per phase (timestamped).

    The device tunnel flaps for hours; a fresh session with a failed or
    watchdogged phase must not erase an earlier good measurement of that
    phase. A degraded new result is stashed under ``<phase>_latest_partial``
    so the record still shows the most recent attempt.
    """
    # derived keys ride with their phase: restoring an old bench must also
    # restore the recommendation/stderr computed FROM that bench
    phase_keys = {
        "validate": ("validate_fused", "validate_error", ()),
        "bench": (
            "bench", "bench_error",
            ("bench_stderr", "recommended_auto_engine"),
        ),
        "kernels": ("kernels", "kernels_error", ()),
    }
    try:
        with open(out_path) as f:
            prev = json.load(f)
    except Exception:
        prev = {}
    merged = dict(results)
    merged["note"] = (
        "per-phase record: each phase carries its own measured_at_unix; a "
        "phase that failed in the latest session keeps the previous "
        "successful measurement, with the failed attempt under "
        "<phase>_latest_partial"
    )
    for _, (key, err_key, riders) in phase_keys.items():
        if key in merged and isinstance(merged[key], dict):
            merged[key].setdefault("measured_at_unix", started)
        if not _phase_failed(merged, key, err_key):
            continue
        old = prev.get(key)
        # previous successful measurement (possibly already merged once)
        if isinstance(old, dict) and not _result_bad(old):
            if key in merged:
                merged[key + "_latest_partial"] = merged[key]
            merged[key] = old
            for rider in riders:
                if rider in merged:
                    merged[rider + "_latest_partial"] = merged[rider]
                if rider in prev:
                    merged[rider] = prev[rider]
                else:
                    merged.pop(rider, None)
        elif key not in merged and old is not None:
            merged[key] = old
    return merged


if __name__ == "__main__":
    main()
