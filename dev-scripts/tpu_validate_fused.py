"""One-shot TPU validation of the fused permutation engine.

Run on a machine with a reachable TPU backend:

    python dev-scripts/tpu_validate_fused.py

Phases:
1. correctness — fused kernels (real Mosaic lowering, NOT the interpreter)
   vs the ELL engine on a small problem: matvec / rmatvec / rmatvec_sq and
   a full L-BFGS solve must agree.
2. timing — benes vs fused FE solve + per-linear-map timings at bench scale
   (same shapes as bench.py), so the engine choice in
   data/game_data.py:sparse_features ("auto") can be confirmed or flipped.

Exit code 0 = fused correct on hardware (timings are informational).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    assert jax.devices()[0].platform != "cpu", (
        "this script validates real-TPU lowering; run it on a TPU backend"
    )

    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops import fused_perm, sparse_perm
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.ops.features import from_scipy_like
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
    )
    from photon_ml_tpu.opt.solve import solve

    rng = np.random.default_rng(0)

    # ---- phase 1: correctness on hardware --------------------------------
    n, d, nnz = 4096, 3000, 60000
    rows = rng.integers(0, n, nnz)
    cols = rng.integers(0, d, nnz)
    vals = rng.standard_normal(nnz).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (rows, cols), vals)

    fused = fused_perm.from_coo(rows, cols, vals, (n, d))
    assert fused._fused_ok(), "fused path not active on this backend"
    w = rng.standard_normal(d).astype(np.float32)
    c = rng.standard_normal(n).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(fused.matvec(jnp.asarray(w))), dense @ w, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(fused.rmatvec(jnp.asarray(c))), dense.T @ c, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(fused.rmatvec_sq(jnp.asarray(c))), (dense * dense).T @ c,
        atol=2e-3,
    )
    print("phase 1a: fused linear maps match dense reference", flush=True)

    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=30),
        regularization_weight=1.0,
    )
    l2 = jnp.float32(1.0)
    y = (rng.random(n) < 0.5).astype(np.float32)
    ell = from_scipy_like(rows, cols, vals, (n, d))
    r_ell = solve(objective, jnp.zeros(d, jnp.float32),
                  LabeledData.create(ell, jnp.asarray(y)), cfg, l2_weight=l2)
    r_fused = solve(objective, jnp.zeros(d, jnp.float32),
                    LabeledData.create(fused, jnp.asarray(y)), cfg, l2_weight=l2)
    dw = float(jnp.max(jnp.abs(r_fused.w - r_ell.w)))
    print(f"phase 1b: L-BFGS solves agree, max|dw| = {dw:.2e}", flush=True)
    assert dw < 5e-3

    # ---- phase 1c: KP-cap spill + column-split layout on hardware --------
    # thin-column-tail profile (the 1B-coef chip-tile shape): the joint
    # layout planner must engage AND stay exact under real Mosaic lowering
    # + the scatter-add spill side
    n2, d2, k2 = 4096, 65536, 16
    rows2 = np.repeat(np.arange(n2, dtype=np.int64), k2)
    cols2 = rng.integers(0, d2, n2 * k2).astype(np.int64)
    vals2 = rng.standard_normal(n2 * k2).astype(np.float32)
    for eng_name, mod in (("benes", sparse_perm), ("fused", fused_perm)):
        f2 = mod.from_coo(rows2, cols2, vals2, (n2, d2), max_hot_cols=0)
        from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

        layout = (
            f"{len(f2.blocks)} column blocks"
            if isinstance(f2, ColumnSplitFeatures)
            else f"flat, spill={f2.spill_rows is not None}"
        )
        w2 = rng.standard_normal(d2).astype(np.float32)
        c2 = rng.standard_normal(n2).astype(np.float32)
        z2 = np.asarray(jax.jit(f2.matvec)(jnp.asarray(w2)))
        g2 = np.asarray(jax.jit(f2.rmatvec)(jnp.asarray(c2)))
        z_ref = (vals2.reshape(n2, k2) * w2[cols2.reshape(n2, k2)]).sum(-1)
        g_ref = np.zeros(d2, np.float64)
        np.add.at(g_ref, cols2, vals2 * np.repeat(c2, k2))
        assert np.abs(z2 - z_ref).max() < 2e-3, eng_name
        assert np.abs(g2 - g_ref).max() < 2e-3, eng_name
        print(f"phase 1c: {eng_name} auto layout ({layout}) exact on "
              "hardware", flush=True)

    # ---- phase 2: timings at bench scale ---------------------------------
    import bench as B

    fe_np, _, re_np, re_data, _, _ = B._build()

    def t(f, reps=3):
        r = f()
        jax.block_until_ready(jax.tree.leaves(r))
        B._settle_dispatch(f)  # see bench._settle_dispatch
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            r = f()
            jax.block_until_ready(jax.tree.leaves(r))
            best = min(best, time.perf_counter() - t0)
        return best, r

    solver = jax.jit(
        lambda w0, dd: solve(objective, w0, dd,
                             GlmOptimizationConfiguration(
                                 optimizer_config=OptimizerConfig.lbfgs(
                                     max_iterations=50),
                                 regularization_weight=1.0),
                             l2_weight=l2)
    )
    w0 = jnp.zeros((B.D_FE,), dtype=jnp.float32)
    for engine in ("benes", "fused"):
        print(f"building {engine} bench data...", flush=True)
        dd = B._routed_fe_data(fe_np, engine)
        st, res = t(lambda dd=dd: solver(w0, dd))
        it = int(res.iterations)
        print(f"FE {engine}: {st * 1e3:.0f} ms, {it} iters, "
              f"{B.N_FE * it / st / 1e6:.1f}M passes/s", flush=True)
        feats = dd.features
        mv = jax.jit(feats.matvec)
        mt, z = t(lambda: mv(w0), reps=5)
        rmv = jax.jit(feats.rmatvec)
        rt, _ = t(lambda: rmv(z), reps=5)
        print(f"   matvec {mt * 1e3:.2f} ms   rmatvec {rt * 1e3:.2f} ms",
              flush=True)
    print("VALIDATION OK", flush=True)


if __name__ == "__main__":
    main()
