#!/usr/bin/env python
"""Real multi-controller cluster runs (one process per host/pod worker).

The emulated mesh (`train_game --hosts N`) spawns its workers locally; on
a real pod slice each controller runs its OWN process, so the roles are
started explicitly instead:

  # on the coordinator host (also the trainer):
  python dev-scripts/run_multihost.py coordinator \
      --hosts 4 --bind 0.0.0.0 --port 7341 \
      -- --train-data-dirs gs://.../train --coordinate-config game.json \
         --task LOGISTIC_REGRESSION --streaming --block-rows 65536 \
         --output-dir out/

  # on each worker host h = 0..3:
  python dev-scripts/run_multihost.py worker \
      --coordinator COORD_IP:7341 --host-id $h \
      --train-data-dirs gs://.../train --coordinate-config game.json \
      --task LOGISTIC_REGRESSION --feature-shard global --block-rows 65536

The coordinator role runs the full train_game CLI with the cluster plane
pre-bound to --bind/--port (it waits for --hosts hellos before the first
pass); the worker role is a thin wrapper over
``python -m photon_ml_tpu.parallel.cluster.worker``. Every host must see
the same training files so the deterministic block plans agree — the
hello handshake rejects skew.
"""

from __future__ import annotations

import argparse
import sys


def _coordinator(args, train_args) -> int:
    # Monkeypatch the coordinator's bind point: ClusterCoordinator binds
    # 127.0.0.1:0 by default (the emulated mesh); a real run needs a
    # routable address the workers were told about.
    import socket

    from photon_ml_tpu.parallel.cluster import coordinator as coord_mod

    orig_init = coord_mod.ClusterCoordinator.__init__

    def patched_init(self, *a, **kw):
        kw["bind_host"] = args.bind
        orig_init(self, *a, **kw)
        if args.port:
            # rebind to the announced fixed port
            self._server.close()
            self._server = socket.create_server((args.bind, args.port))
            self.address = self._server.getsockname()[:2]

    coord_mod.ClusterCoordinator.__init__ = patched_init

    # ClusterPlane.launch spawns local subprocesses; with remote workers we
    # skip the spawn and only wait for hellos.
    from photon_ml_tpu.parallel.cluster import launcher as launcher_mod

    orig_launch = launcher_mod.ClusterPlane.launch.__func__

    def patched_launch(cls, num_hosts, num_blocks, **kw):
        coordinator = coord_mod.ClusterCoordinator(num_hosts, num_blocks)
        print(
            f"[run_multihost] waiting for {num_hosts} workers on "
            f"{coordinator.address[0]}:{coordinator.address[1]}",
            flush=True,
        )
        coordinator.wait_for_workers(timeout_s=args.startup_timeout_s)
        return cls(coordinator, procs=[], log_paths=[])

    launcher_mod.ClusterPlane.launch = classmethod(patched_launch)

    from photon_ml_tpu.cli.train_game import main as train_main

    return train_main(train_args + ["--hosts", str(args.hosts)])


def _worker(worker_args) -> int:
    from photon_ml_tpu.parallel.cluster.worker import main as worker_main

    return worker_main(worker_args)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("coordinator", "worker"):
        print(__doc__, file=sys.stderr)
        return 2
    role, rest = argv[0], argv[1:]
    if role == "worker":
        # everything after the role goes to the worker module, with
        # --coordinator accepted as an alias for --coordinator-address
        rest = [
            "--coordinator-address" if a == "--coordinator" else a
            for a in rest
        ]
        return _worker(rest)
    p = argparse.ArgumentParser(prog="run_multihost.py coordinator")
    p.add_argument("--hosts", type=int, required=True)
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0,
                   help="fixed coordinator port (0 = ephemeral; use fixed "
                        "so workers can be started first)")
    p.add_argument("--startup-timeout-s", type=float, default=600.0)
    if "--" not in rest:
        p.error("separate train_game args with '--'")
    split = rest.index("--")
    args = p.parse_args(rest[:split])
    return _coordinator(args, rest[split + 1:])


if __name__ == "__main__":
    sys.exit(main())
