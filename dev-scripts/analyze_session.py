"""Summarize TPU_MEASUREMENTS.json into decisions.

    python dev-scripts/analyze_session.py [--in TPU_MEASUREMENTS.json]

Prints, for the latest measurement session: the bench headline and ratios,
the kernel roofline (chained vs dispatch, pct of peak, binding), the
tile-height A/B verdict (should PHOTON_FUSED_TILE_U change?), the measured
spill-cost calibration (should PHOTON_SPILL_SLOT_COST change?), the
memory-envelope table against docs/SCALING.md's predictions, and the bf16
win-or-cut evidence. Pure reporting — no repo mutations.
"""

from __future__ import annotations

import argparse
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt_bytes(b):
    if b is None:
        return "n/a"
    return f"{b / 2**30:.2f} GiB"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--in", dest="path",
                    default=os.path.join(REPO, "TPU_MEASUREMENTS.json"))
    args = ap.parse_args()
    with open(args.path) as f:
        d = json.load(f)

    def _num(v):
        return f"{v:,}" if isinstance(v, (int, float)) else "n/a"

    for phase in ("bench", "kernels", "memory", "validate"):
        if d.get(phase + "_error"):
            print(f"!! {phase} phase failed this session: "
                  f"{d[phase + '_error'][:90]}")
        if (phase + "_latest_partial" in d) or (
            isinstance(d.get(phase), dict) and d[phase].get("error")
            and phase + "_latest_partial" not in d
        ):
            print(f"!! {phase}: shown numbers may be carried from an "
                  "EARLIER session (see measured_at_unix / "
                  f"{phase}_latest_partial in the record)")

    print("== bench ==")
    b = d.get("bench", {})
    if isinstance(b, dict) and b.get("error") and not b.get("value"):
        print(f"ERROR {b['error'][:120]}")
        b = {}
    if b:
        print(f"headline {b.get('headline_workload')}: "
              f"{_num(b.get('value'))} {b.get('unit', '')}"
              + (f"  (measured_at {b.get('measured_at_unix')})"
                 if b.get("measured_at_unix") else ""))
        print(f"vs_baseline {b.get('vs_baseline')} "
              f"(pinned {b.get('vs_baseline_pinned')}, "
              f"fresh {b.get('vs_baseline_fresh')})")
        print(f"time-to-AUC {b.get('wallclock_to_auc_s')}s "
              f"(target {b.get('auc_target')}, final {b.get('auc_final')}; "
              f"trace {b.get('auc_trace')})")
        print(f"smalldim {b.get('smalldim_passes_per_s')} passes/s, "
              f"engines {b.get('engines')}")
        if b.get("stale"):
            print("!! STALE replay — no live measurement this session")

    def _ms(e, key):
        return f"{e[key] * 1e3:.2f}ms" if key in e else "n/a"

    print("\n== kernels (chained = dispatch excluded) ==")
    kern = d.get("kernels", {})
    base = kern.get("fused", {})
    for name, e in kern.items():
        if not isinstance(e, dict):
            continue
        if "error" in e:
            print(f"{name}: ERROR {e['error'][:90]}")
            continue
        if name.startswith("fused_u"):
            continue  # healthy tile-cap variants report in the A/B below
        print(f"{name}: matvec {_ms(e, 'matvec_s')} "
              f"(1-call {_ms(e, 'matvec_dispatch_s')}), "
              f"rmatvec {_ms(e, 'rmatvec_s')}, "
              f"eval {_ms(e, 'objective_eval_s')}, "
              f"{e.get('pct_of_peak_matvec')}%/{e.get('pct_of_peak_rmatvec')}%"
              f" of peak")
        if e.get("binding"):
            print(f"   binding: {e['binding']}")
    for cap in (32, 64):
        v = kern.get(f"fused_u{cap}", {})
        if "matvec_s" in v and "matvec_s" in base:
            speed = base["matvec_s"] / v["matvec_s"]
            verdict = "WINS" if speed > 1.05 else (
                "ties" if speed > 0.95 else "LOSES")
            print(f"tile cap u{cap}: {speed:.2f}x vs default -> {verdict}"
                  + ("  => set PHOTON_FUSED_TILE_U and re-run bench"
                     if speed > 1.05 else ""))

    cal = d.get("spill_calibration", {})
    if cal and "error" in cal:
        print(f"\n== spill calibration == ERROR {cal['error'][:90]}")
    elif cal:
        print("\n== spill calibration ==")
        print(f"scatter {cal.get('scatter_ns_per_entry')} ns/entry, "
              f"routed {cal.get('routed_ns_per_slot')} ns/slot -> "
              f"recommended PHOTON_SPILL_SLOT_COST = "
              f"{cal.get('recommended_spill_slot_cost')} (default 32)")

    mem = d.get("memory", {})
    if mem:
        print("\n== memory envelope (SCALING.md: history = m*2*4B/coef "
              "dominates; 2^26 m=10 predicted ~5.4 GiB history + 0.25 GiB "
              "w + data) ==")
        for key, e in sorted(mem.items()):
            if not isinstance(e, dict):
                continue
            if "error" in e:
                print(f"{key}: ERROR {e['error'][:90]}")
                continue
            print(f"{key}: peak {_fmt_bytes(e.get('peak_bytes_in_use'))} "
                  f"of {_fmt_bytes(e.get('bytes_limit'))}, "
                  f"{_num(e.get('passes_per_s'))} passes/s, "
                  f"solve {e.get('solve_s')}s")

    eng = (d.get("bench") or {}).get("engines", {})
    if "fused_bf16" in eng and "fused" in eng:
        print("\n== bf16 verdict ==")
        r = eng["fused_bf16"] / eng["fused"]
        print(f"fused_bf16/fused = {r:.3f} -> "
              + ("bf16 WINS the small-dim A/B" if r > 1.02 else
                 "bf16 does not pay at small-dim"))

    if d.get("recommended_auto_engine"):
        print(f"\nrecommended auto engine: {d['recommended_auto_engine']}")


if __name__ == "__main__":
    main()
