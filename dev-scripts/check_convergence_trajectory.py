#!/usr/bin/env python
"""Convergence-regression sentinel: fresh progress ledger vs golden history.

CI trains the tiny golden ratings fixture with ``--progress-out`` and hands
the resulting ``progress.jsonl`` to this script, which compares the run's
convergence TRAJECTORY against the golden records committed in
``BENCH_HISTORY.jsonl`` (``mode: "convergence"``):

* ``golden_fixture_final_objective`` — the final training objective; the
  gate fires when the fresh value sits above the reference by more than
  ``--objective-tolerance`` (relative, default 1%);
* ``golden_fixture_iterations_to_tol`` — coordinate updates until the
  objective stays within tolerance of its final value; fires when the
  fresh run needs more than reference + ``--iteration-slack`` updates;
* optionally, with ``--target-metric``, iterations until the held-out
  metric reaches the target (``golden_fixture_iterations_to_target``).

Unlike the perf sentinel these are OPTIMIZATION quantities — deterministic
on the fixed-seed CPU fixture and independent of wall-clock noise — so no
host fingerprint gating applies: a slower machine converges in exactly the
same number of updates to exactly the same objective. Infrastructure
problems (missing ledger, no progress records, no golden baseline) report
and pass; only a measured degradation fails.

Usage:
    python -m photon_ml_tpu.cli.train_game ... --progress-out /tmp/p.jsonl
    python dev-scripts/check_convergence_trajectory.py /tmp/p.jsonl \
        [--history BENCH_HISTORY.jsonl] [--objective-tolerance 0.01] \
        [--iteration-slack 1] [--target-metric 0.9 [--lower-is-better]]
"""
import argparse
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # crash-truncated tail is fine; analyze the prefix
    return out


def _iters_to_tolerance(objectives, tolerance):
    """1-based count of coordinate updates until the objective stays within
    ``tolerance`` (relative) of its final value. Mirrors
    photon_ml_tpu.telemetry.progress._iters_to_tolerance — keep in sync."""
    if not objectives:
        return None
    final = objectives[-1]
    scale = max(1.0, abs(final))
    for i in range(len(objectives)):
        if all(abs(o - final) <= tolerance * scale for o in objectives[i:]):
            return i + 1
    return None


def _iters_to_target(progress, target, higher_is_better):
    for rec in progress:
        if rec.get("kind") != "validation":
            continue
        m = float(rec["metric"])
        if (m >= target) if higher_is_better else (m <= target):
            return int(rec["outer"]) + 1
    return None


def _golden(history_path, metric):
    """Latest mode=convergence history record for ``metric`` (None if the
    baseline was never recorded)."""
    if not os.path.exists(history_path):
        return None
    value = None
    for rec in _read_jsonl(history_path):
        if rec.get("mode") == "convergence" and rec.get("metric") == metric:
            value = rec.get("value")
    return value


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("ledger", help="progress.jsonl from a --progress-out run")
    ap.add_argument(
        "--history", default=os.path.join(REPO, "BENCH_HISTORY.jsonl"),
        help="history file holding the golden mode=convergence records",
    )
    ap.add_argument(
        "--objective-tolerance", type=float, default=0.01,
        help="fail when the fresh final objective exceeds the golden one by "
             "more than this relative margin (default 0.01)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=1e-3,
        help="relative tolerance defining 'converged' for the "
             "iterations-to-tolerance count (default 1e-3; must match the "
             "value used when the golden record was taken)",
    )
    ap.add_argument(
        "--iteration-slack", type=int, default=1,
        help="fail when the fresh run needs more than golden + slack "
             "updates to reach tolerance (default 1)",
    )
    ap.add_argument(
        "--target-metric", type=float, default=None,
        help="also gate iterations-to-target on the held-out metric trace",
    )
    ap.add_argument(
        "--lower-is-better", action="store_true",
        help="the held-out metric improves downward (RMSE-style)",
    )
    args = ap.parse_args(argv)

    try:
        records = _read_jsonl(args.ledger)
    except OSError as e:
        print(f"convergence-trajectory: cannot read ledger ({e}); skipping")
        return 0
    progress = [r for r in records if r.get("type") == "progress"]
    coord = [r for r in progress if r.get("kind") == "coordinate"]
    if not coord:
        print(
            "convergence-trajectory: ledger carries no coordinate progress "
            "records; nothing to gate — skipping"
        )
        return 0
    anomalies = [r for r in progress if r.get("kind") == "anomaly"]
    if anomalies:
        a = anomalies[0]
        print(
            "convergence-trajectory: FAIL — run recorded a divergence "
            f"anomaly ({a.get('anomaly_kind')} at outer {a.get('outer')}, "
            f"coordinate {a.get('coordinate')!r})"
        )
        return 1

    objectives = [float(r["objective"]) for r in coord]
    final_obj = objectives[-1]
    iters = _iters_to_tolerance(objectives, args.tolerance)
    print(
        f"convergence-trajectory: {len(objectives)} update(s), final "
        f"objective {final_obj:.6g}, iterations-to-tolerance "
        f"{iters if iters is not None else 'not reached'}"
    )
    if not math.isfinite(final_obj):
        print("convergence-trajectory: FAIL — non-finite final objective")
        return 1

    failures = []
    ref_obj = _golden(args.history, "golden_fixture_final_objective")
    if ref_obj is None:
        print(
            "convergence-trajectory: no golden_fixture_final_objective in "
            f"{args.history}; objective gate skipped"
        )
    else:
        allowed = float(ref_obj) + args.objective_tolerance * max(
            1.0, abs(float(ref_obj))
        )
        print(
            f"convergence-trajectory: final objective {final_obj:.6g} vs "
            f"golden {float(ref_obj):.6g} (allowed <= {allowed:.6g})"
        )
        if final_obj > allowed:
            failures.append(
                f"final objective {final_obj:.6g} exceeds golden "
                f"{float(ref_obj):.6g} by more than "
                f"{args.objective_tolerance:.2%}"
            )

    ref_iters = _golden(args.history, "golden_fixture_iterations_to_tol")
    if ref_iters is None:
        print(
            "convergence-trajectory: no golden_fixture_iterations_to_tol in "
            f"{args.history}; iteration gate skipped"
        )
    else:
        allowed_iters = int(ref_iters) + args.iteration_slack
        shown = iters if iters is not None else "not reached"
        print(
            f"convergence-trajectory: iterations-to-tolerance {shown} vs "
            f"golden {int(ref_iters)} (allowed <= {allowed_iters})"
        )
        if iters is None or iters > allowed_iters:
            failures.append(
                f"iterations-to-tolerance {shown} exceeds golden "
                f"{int(ref_iters)} + slack {args.iteration_slack}"
            )

    if args.target_metric is not None:
        t_iters = _iters_to_target(
            progress, args.target_metric, not args.lower_is_better
        )
        ref_t = _golden(args.history, "golden_fixture_iterations_to_target")
        shown = t_iters if t_iters is not None else "not reached"
        if ref_t is None:
            print(
                f"convergence-trajectory: iterations-to-target {shown} "
                "(no golden record; gate skipped)"
            )
        else:
            allowed_t = int(ref_t) + args.iteration_slack
            print(
                f"convergence-trajectory: iterations-to-target {shown} vs "
                f"golden {int(ref_t)} (allowed <= {allowed_t})"
            )
            if t_iters is None or t_iters > allowed_t:
                failures.append(
                    f"iterations-to-target-metric {shown} exceeds golden "
                    f"{int(ref_t)} + slack {args.iteration_slack}"
                )

    if failures:
        for f in failures:
            print(f"convergence-trajectory: FAIL — {f}")
        return 1
    print("convergence-trajectory: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
