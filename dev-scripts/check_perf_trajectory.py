#!/usr/bin/env python
"""Perf-trajectory sentinel: fresh smoke bench vs the last-good record.

CI runs a smoke-mode ``bench.py`` (CPU, tiny shapes) and hands its one JSON
line to this script, which compares the headline value against
``BENCH_LASTGOOD.json`` with a tolerance band. The point is catching
order-of-magnitude regressions a unit suite can't see — a retrace storm, an
accidental sync per batch — NOT chasing benchmark noise, hence:

* the gate only fires when the fresh value is BELOW ``tolerance`` × the
  reference (default 0.05: a 20x collapse), never on improvements;
* a host-fingerprint mismatch (CI machine != the machine that measured the
  reference) downgrades the check to a report and exits 0 — cross-machine
  absolute numbers are not comparable;
* a missing reference or unmeasurable fresh run also reports-and-passes:
  the sentinel must never block a round on infrastructure, only on a
  measured collapse on comparable hardware.

Usage:
    python bench.py > /tmp/fresh.json          # BENCH_SMOKE=1 upstream
    python dev-scripts/check_perf_trajectory.py /tmp/fresh.json \
        [--reference BENCH_LASTGOOD.json] [--tolerance 0.05] \
        [--history BENCH_HISTORY.jsonl]

With ``--history`` it also prints the recent trajectory of the fresh
metric (last 5 matching records) for the CI log, purely informational.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# History modes/metrics that are rates or verdicts, not throughput: a low
# value is a legitimate measurement (e.g. scenarios missing their SLO under
# a storm shape), not a perf collapse, so the collapse gate must not fire
# on them — they get the trajectory report only. The scenario sentinel
# (dev-scripts/check_scenarios.py) owns gating those artifacts.
REPORT_ONLY_METRICS = {
    "scenario_slo_ok_rate",
    "eviction_resident_rate_gain",
}


def _host_fingerprint() -> str:
    # must mirror bench.py's fingerprint so equality is meaningful
    model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def _last_json_line(path: str):
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty")
    return json.loads(lines[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("fresh", help="file holding the fresh bench's JSON line")
    ap.add_argument(
        "--reference", default=os.path.join(REPO, "BENCH_LASTGOOD.json"),
        help="last-good record to compare against (default: repo's)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.05,
        help="fail when fresh < tolerance * reference (default 0.05; the "
             "gate hunts collapses, not noise)",
    )
    ap.add_argument(
        "--history", default=None,
        help="optional BENCH_HISTORY.jsonl to print the recent trajectory",
    )
    ap.add_argument(
        "--require-same-host", action="store_true",
        help="fail (rather than skip) on a host-fingerprint mismatch",
    )
    args = ap.parse_args(argv)

    try:
        fresh = _last_json_line(args.fresh)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf-trajectory: cannot read fresh result ({e}); skipping")
        return 0
    if fresh.get("error") or not fresh.get("value"):
        print(
            "perf-trajectory: fresh run did not measure "
            f"(error={fresh.get('error')!r}); the bench's own exit code "
            "already gates this — skipping"
        )
        return 0

    if args.history and os.path.exists(args.history):
        tail = []
        with open(args.history) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("metric") == fresh.get("metric"):
                    tail.append(rec)
        for rec in tail[-5:]:
            print(
                f"perf-trajectory: history {rec.get('ts')}: "
                f"{rec.get('metric')}={rec.get('value')} {rec.get('unit')}"
            )

    if fresh.get("metric") in REPORT_ONLY_METRICS:
        print(
            f"perf-trajectory: {fresh['metric']}={fresh.get('value')} is a "
            "rate/verdict metric, not throughput — report only, no collapse "
            "gate"
        )
        return 0

    if not os.path.exists(args.reference):
        print(
            f"perf-trajectory: no reference at {args.reference}; nothing to "
            "compare — skipping"
        )
        return 0
    with open(args.reference) as f:
        ref = json.load(f)
    if ref.get("metric") != fresh.get("metric"):
        print(
            f"perf-trajectory: metric mismatch (fresh {fresh.get('metric')!r}"
            f" vs reference {ref.get('metric')!r}); skipping"
        )
        return 0
    ref_value = ref.get("value")
    if not ref_value:
        print("perf-trajectory: reference has no value; skipping")
        return 0

    host = _host_fingerprint()
    # only the top-level "host" names the MEASUREMENT machine
    # (baseline_pin_host is the CPU-baseline pin, typically a different
    # machine than the accelerator that produced the headline)
    ref_host = ref.get("host")
    if ref_host != host:
        msg = (
            f"perf-trajectory: host mismatch — reference measured on "
            f"{ref_host!r}, this is {host!r}; absolute numbers are not "
            "comparable"
        )
        if args.require_same_host:
            print(msg + " (--require-same-host set)")
            return 1
        print(msg + "; skipping the gate")
        return 0

    ratio = float(fresh["value"]) / float(ref_value)
    print(
        f"perf-trajectory: {fresh['metric']} fresh={fresh['value']} vs "
        f"reference={ref_value} ({ratio:.3f}x, floor {args.tolerance}x)"
    )
    if ratio < args.tolerance:
        print(
            "perf-trajectory: FAIL — the fresh measurement collapsed below "
            f"{args.tolerance}x of the last good record on the same host; "
            "suspect a retrace storm or an accidental per-batch sync"
        )
        return 1
    print("perf-trajectory: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
