#!/usr/bin/env python
"""CI helper behind the failure-plane chaos gates (docs/RELIABILITY.md).

Two subcommands:

``counters LEDGER --require NAME[:MIN] ...``
    Read the last ``type: "metrics"`` snapshot in a telemetry run ledger
    and assert each required counter is present with value >= MIN
    (default 1). The chaos smoke gate trains with ``PHOTON_FAULTS``
    arming transient faults and then requires the matching
    ``resilience.retry.<site>.recovered`` / ``resilience.fault.<site>.trips``
    counters — proving the faults actually fired AND were recovered, not
    that the run merely happened to pass.

``models DIR_A DIR_B``
    Load two trained GAME model artifacts and assert their coefficients
    are bitwise identical (exact float equality, exact per-entity sparse
    maps). Used by the disabled-path parity gate: a run with an armed but
    never-firing fault site must match an unarmed run bit for bit.
    Compared at the coefficient level (not file bytes) because the Avro
    container embeds a random sync marker per file.
"""

import argparse
import json
import sys


def _fail(msg: str) -> "int":
    print(f"CHAOS GATE FAIL: {msg}", file=sys.stderr)
    return 1


def check_counters(args) -> int:
    snapshot = None
    with open(args.ledger) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # crash-truncated tail line
            if rec.get("type") == "metrics":
                snapshot = rec.get("snapshot", {})
    if snapshot is None:
        return _fail(f"no metrics snapshot in {args.ledger}")
    counters = snapshot.get("counters", {})
    bad = []
    for spec in args.require:
        name, _, floor = spec.partition(":")
        floor = int(floor) if floor else 1
        got = counters.get(name, 0)
        marker = "ok" if got >= floor else "MISSING"
        print(f"  {name} = {got} (require >= {floor}) {marker}")
        if got < floor:
            bad.append(name)
    if bad:
        return _fail(f"counters below floor: {', '.join(bad)}")
    print(f"CHAOS GATE OK: {len(args.require)} recovery counters present")
    return 0


def _model_digest(model):
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for cid in sorted(model.models):
        m = model.models[cid]
        h.update(cid.encode())
        coeffs = getattr(m, "coefficients", None)
        means = getattr(coeffs, "means", None)
        if means is not None:  # fixed-effect GLM
            h.update(np.ascontiguousarray(np.asarray(means)).tobytes())
            continue
        for ent, w in sorted(m.items()):  # random-effect table
            h.update(str(ent).encode())
            if isinstance(w, dict):  # sparse {feature: weight} map
                for k in sorted(w):
                    h.update(f"{k}={float(w[k]).hex()};".encode())
            else:
                h.update(np.ascontiguousarray(np.asarray(w)).tobytes())
    return h.hexdigest()


def check_models(args) -> int:
    from photon_ml_tpu.io.model_io import load_game_model

    model_a, _ = load_game_model(args.dir_a)
    model_b, _ = load_game_model(args.dir_b)
    dig_a, dig_b = _model_digest(model_a), _model_digest(model_b)
    print(f"  {args.dir_a}: {dig_a}")
    print(f"  {args.dir_b}: {dig_b}")
    if dig_a != dig_b:
        return _fail("models differ — armed-but-idle fault plane perturbed "
                     "training output (disabled-path parity broken)")
    print("CHAOS GATE OK: models bitwise identical")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("counters", help="assert recovery counters in ledger")
    c.add_argument("ledger")
    c.add_argument("--require", action="append", default=[],
                   metavar="NAME[:MIN]", required=True)
    c.set_defaults(func=check_counters)
    m = sub.add_parser("models", help="assert two model outputs identical")
    m.add_argument("dir_a")
    m.add_argument("dir_b")
    m.set_defaults(func=check_models)
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
